#!/usr/bin/env python
"""Epilogue-fusion + persistent-autotuner CI gate (the MFU-round
acceptance check: analysis/epilogue_fusion.py, ops/fused_gemm.py,
paddle_tpu.tuning).

  python tools/fusion_check.py --check [--json ci_fusion_report.json]
  python tools/fusion_check.py --negative-control

Gates (exit 1 on any failure, with the house '-> FAIL' marker):

  1. fusion_applies — the pass fuses >= 1 chain on every probe
     (MLP gelu/relu stack, BERT-tiny infer, ResNet-tiny infer) and the
     fused program passes the FULL static-analysis pipeline with zero
     errors (the lint zoo stays clean with fusion enabled).
  2. parity        — fused vs unfused fetches: bit-exact on the dense
     route (CPU CI), within the declared witness tolerance on a TPU.
  3. not_slower    — fused chained-scan step time <= unfused * slack.
     On a TPU backend the gate additionally requires the >= 1.15x
     throughput win on at least one probe; on CPU the report documents
     why the backend cannot express the win (the dense fallback replays
     the identical primitive sequence — the win needs the MXU epilogue).
  4. autotune_roundtrip — a fresh subprocess in FLAGS_autotune=measure
     populates the cost DB; a SECOND fresh subprocess in use mode
     compiles straight to the best-known config: autotune_hits_total
     >= 1, the compiled xla_options equal the recorded best, and the DB
     trial count is unchanged (zero re-trials).

  --negative-control: with FLAGS_epilogue_fusion=0 the probes must show
  ZERO fused ops and bit-exact baseline outputs (the kill switch works);
  exits 0 when confirmed.

Methodology: docs/PERF_NOTES.md "Epilogue fusion" / "Persistent
autotuner"."""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# off-accelerator the fused and unfused legs trace to the SAME primitive
# graph, so the 'not slower' check is a sanity tripwire against a
# catastrophic lowering bug, not a perf claim — CPU chained micro-timings
# jitter 2-3x between repeats (measured), hence the loose bound + floor.
CPU_SLACK = 2.0
CPU_FLOOR_S = 5e-3
TPU_MIN_SPEEDUP = 1.15    # the acceptance-criteria win on a real chip


def _gate(name, ok, detail, report):
    print(f"[{'ok' if ok else 'FAIL'}] {name}: {detail}")
    report["gates"].append({"name": name, "ok": bool(ok), "detail": detail})
    return ok


# ---------------------------------------------------------------------------
# probes — forward-only programs with fusable chains
# ---------------------------------------------------------------------------

def probe_mlp():
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[512], dtype="float32")
            h = fluid.layers.fc(x, 512, act="gelu")
            h = fluid.layers.fc(h, 512, act="relu")
            h = fluid.layers.fc(h, 512, act="gelu")
            pred = fluid.layers.fc(h, 128)
    rng = np.random.RandomState(0)
    # big enough that the chained differencing is above the CPU noise
    # floor (a 64x256 probe differences to ~0 and the speed gate reads
    # garbage ratios)
    feed = {"x": rng.randn(256, 512).astype(np.float32)}
    return main, startup, pred.name, feed


def probe_bert_tiny():
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    cfg = BertConfig.tiny()
    seq, batch = 32, 4
    with un.guard():
        model = build_bert_pretrain(cfg, seq_len=seq, build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "pos_ids": np.tile(np.arange(seq), (batch, 1)),
        "sent_ids": np.zeros((batch, seq)),
        "input_mask": np.ones((batch, seq), np.float32),
        "mask_label": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "next_sent_label": rng.randint(0, 2, (batch, 1)),
    }
    for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
              "next_sent_label"):
        feed[k] = feed[k].astype(np.int64)
    return infer, model["startup"], model["loss"].name, feed


def probe_resnet_tiny():
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.resnet import build_resnet

    with un.guard():
        model = build_resnet(depth=18, class_num=128,
                             image_shape=(3, 32, 32), build_optimizer=False)
    infer = model["main"].clone(for_test=True)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(8, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 128, (8, 1)).astype(np.int64)}
    return infer, model["startup"], model["logits"].name, feed


PROBES = {"mlp": probe_mlp, "bert_tiny": probe_bert_tiny,
          "resnet_tiny": probe_resnet_tiny}


def time_chained(exe, program, feed, fetch_list, scope,
                 k_short=2, k_long=10, repeats=5):
    """Per-step seconds through the one shared chained-differencing
    implementation (tuning.chained_step_seconds)."""
    from paddle_tpu import tuning

    return tuning.chained_step_seconds(exe, program, feed, fetch_list,
                                       scope, k_short=k_short,
                                       k_long=k_long, repeats=repeats)


def run_probe(name, fused: bool, report):
    import jax

    import paddle_tpu as fluid

    main, startup, fetch, feed = PROBES[name]()
    prev = fluid.get_flags(["FLAGS_epilogue_fusion"])
    fluid.set_flags({"FLAGS_epilogue_fusion": fused})
    try:
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            (out,) = exe.run(main, feed=feed, fetch_list=[fetch])
            per_step = time_chained(exe, main, feed, [fetch], scope)
        n_fused, dec = 0, None
        if fused:
            # the executor already ran the pass (and paid its eager jax
            # fidelity witness) inside exe.run — read its recorded
            # decision instead of running fuse_epilogues a second time
            head = (exe._program_fingerprint(main), (fetch,))
            dec = next((d for k, d in exe._fusion_decisions.items()
                        if k[:2] == head), None)
            n_fused = dec.n_fused if dec is not None and dec.applied else 0
        # what the executor ACTUALLY compiled, on every leg: count
        # fused_gemm_epilogue ops across the programs behind its compiled
        # steps. This is the negative control's real signal — a kill-switch
        # regression that bypassed the pass-level counters would still
        # leave fused ops in the compiled program
        n_fused_exec = sum(
            1
            for step in exe._cache.values()
            for blk in getattr(getattr(step, "program", None), "blocks", [])
            for op in blk.ops if op.type == "fused_gemm_epilogue")
        return {"probe": name, "fused": fused, "backend":
                jax.default_backend(), "per_step_s": per_step,
                "n_fused": n_fused, "n_fused_exec": n_fused_exec,
                "decision": dec, "feed_names": sorted(feed),
                "fetch_names": [fetch], "out": np.asarray(out)}
    finally:
        fluid.set_flags(prev)


def check_fusion_legs(report) -> bool:
    import jax

    from paddle_tpu.analysis.epilogue_fusion import WITNESS_TOLERANCES
    from paddle_tpu.analysis.pass_manager import (ALL_ANALYSIS_PASSES,
                                                  default_pass_manager)
    from paddle_tpu.analysis.diagnostics import Severity

    on_tpu = jax.default_backend() == "tpu"
    ok = True
    any_win = False
    report["legs"] = {}
    for name in PROBES:
        base = run_probe(name, fused=False, report=report)
        fus = run_probe(name, fused=True, report=report)
        leg = {
            "unfused_per_step_s": base["per_step_s"],
            "fused_per_step_s": fus["per_step_s"],
            "speedup": base["per_step_s"] / fus["per_step_s"],
            "n_fused": fus["n_fused"],
            "n_fused_exec": fus["n_fused_exec"],
        }
        report["legs"][name] = leg
        # both sides of the switch: the pass matches chains AND the
        # executor actually compiled the fused rewrite
        ok &= _gate(f"{name}_fusion_applies",
                    fus["n_fused"] > 0 and fus["n_fused_exec"] > 0,
                    f"{fus['n_fused']} fused chain(s), "
                    f"{fus['n_fused_exec']} compiled fused op(s)", report)
        if on_tpu:
            rtol, atol = WITNESS_TOLERANCES.get(
                str(base["out"].dtype), WITNESS_TOLERANCES["float32"])
            par = np.allclose(base["out"].astype(np.float32),
                              fus["out"].astype(np.float32),
                              rtol=rtol, atol=atol)
            detail = f"within declared tolerance rtol={rtol} atol={atol}"
        else:
            par = np.array_equal(base["out"], fus["out"])
            detail = "bit-exact (dense route replays the original rules)"
        leg["parity"] = bool(par)
        ok &= _gate(f"{name}_parity", par, detail, report)
        # off-accelerator the two graphs are the SAME primitives, so any
        # delta is measurement noise: a loose relative slack plus an
        # absolute floor (ms-scale CPU probes jitter by scheduler quanta)
        slack = 1.0 / TPU_MIN_SPEEDUP if on_tpu else CPU_SLACK
        floor = 0.0 if on_tpu else CPU_FLOOR_S
        ok &= _gate(
            f"{name}_not_slower",
            fus["per_step_s"] <= max(base["per_step_s"] * slack,
                                     base["per_step_s"] + floor),
            f"fused {fus['per_step_s'] * 1e3:.2f} ms vs unfused "
            f"{base['per_step_s'] * 1e3:.2f} ms "
            f"(speedup {leg['speedup']:.2f}x)", report)
        any_win = any_win or leg["speedup"] >= TPU_MIN_SPEEDUP

        # the fused program must stay clean under the FULL analysis
        # pipeline (the 'lint zoo stays clean with fusion enabled' gate) —
        # reusing the fused leg's decision: each fuse_epilogues call runs
        # the eager jax fidelity witness, so don't pay it a second time
        dec = fus["decision"]
        if dec is None:
            # fusion_applies already failed loudly above — there is no
            # fused program to lint
            leg["lint_errors"] = ["no fusion decision recorded"]
            ok &= _gate(f"{name}_fused_lint_clean", False,
                        "no fusion decision recorded", report)
            continue
        result = default_pass_manager().run_pipeline(
            dec.program, ALL_ANALYSIS_PASSES,
            feed_names=fus["feed_names"],
            fetch_names=fus["fetch_names"], verify="none")
        errs = [str(d) for d in result.diagnostics
                if d.severity == Severity.ERROR]
        leg["lint_errors"] = errs
        ok &= _gate(f"{name}_fused_lint_clean", not errs,
                    f"{len(errs)} error(s)" + (f": {errs[0]}" if errs
                                               else ""), report)
    if on_tpu:
        ok &= _gate("tpu_speedup_win", any_win,
                    f"need >= {TPU_MIN_SPEEDUP}x on at least one probe",
                    report)
    else:
        report["backend_note"] = (
            f"backend '{jax.default_backend()}' cannot express the fused "
            f"win: off-TPU the fused op's dense fallback replays the "
            f"identical primitive sequence the unfused program runs (the "
            f"speedup needs the Pallas MXU kernel's in-VMEM epilogue), so "
            f"this gate enforces parity + not-slower and the "
            f">={TPU_MIN_SPEEDUP}x win gate applies on the TPU leg")
        print(f"[note] {report['backend_note']}")
    return ok


# ---------------------------------------------------------------------------
# autotune round-trip (two fresh subprocesses against one DB file)
# ---------------------------------------------------------------------------

def _child(mode: str, db_path: str) -> int:
    """Subprocess body: measure populates the DB; use must hit it."""
    import paddle_tpu as fluid
    from paddle_tpu import monitor, tuning

    main, startup, fetch, feed = probe_mlp()
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    out = {"mode": mode, "fp": tuning.program_content_fingerprint(main)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        if mode == "measure":
            rep = tuning.measure_candidates(
                exe, main, feed, [fetch], scope, k_short=2, k_long=4,
                candidates=tuning.default_candidates()[:3])
            out["best"] = rep["best"]["candidate"] if rep["best"] else None
            out["trials"] = tuning.get_database(db_path).trial_count()
        else:
            exe.run_chained(main, feed=feed, fetch_list=[fetch], steps=2,
                            scope=scope)
            evs = monitor.recompile_events(recompiles_only=False)
            comp = evs[-1].components if evs else {}
            out["hits"] = monitor.metric_value("autotune_hits_total") or 0
            out["compiled_xla_options"] = dict(
                comp.get("xla_options") or ())
            out["trials"] = tuning.get_database(db_path).trial_count()
    print("CHILD_JSON:" + json.dumps(out))
    return 0


def _spawn(mode: str, db_path: str) -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               FLAGS_autotune=mode, FLAGS_autotune_db=db_path)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         "--db", db_path],
        env=env, capture_output=True, text=True, timeout=900)
    for line in proc.stdout.splitlines():
        if line.startswith("CHILD_JSON:"):
            return json.loads(line[len("CHILD_JSON:"):])
    raise RuntimeError(
        f"autotune child ({mode}) produced no report "
        f"(rc={proc.returncode}):\n{proc.stdout[-2000:]}"
        f"\n{proc.stderr[-2000:]}")


def check_autotune_roundtrip(report) -> bool:
    db_path = os.path.join(tempfile.mkdtemp(prefix="fusion_check_"),
                           "autotune_db.json")
    measured = _spawn("measure", db_path)
    used = _spawn("use", db_path)
    report["autotune"] = {"db": db_path, "measure": measured, "use": used}
    ok = _gate("autotune_measure_populates",
               bool(measured.get("best")) and measured.get("trials", 0) > 0,
               f"{measured.get('trials', 0)} trial(s), best="
               f"{json.dumps(measured.get('best'))}", report)
    ok &= _gate("autotune_use_hits",
                used.get("hits", 0) >= 1,
                f"autotune_hits_total={used.get('hits')}", report)
    best_opts = (measured.get("best") or {}).get("xla_options", {})
    ok &= _gate("autotune_use_compiles_best",
                used.get("compiled_xla_options") == best_opts,
                f"compiled={json.dumps(used.get('compiled_xla_options'))} "
                f"vs best={json.dumps(best_opts)}", report)
    ok &= _gate("autotune_zero_retrials",
                used.get("trials") == measured.get("trials")
                and used.get("fp") == measured.get("fp"),
                f"trials {measured.get('trials')} -> {used.get('trials')} "
                f"(fingerprints match={used.get('fp') == measured.get('fp')})",
                report)
    return ok


def check_negative_control(report) -> bool:
    """FLAGS_epilogue_fusion=0: zero fused ops + bit-exact baseline.

    The baseline run monkeypatches ``Executor._maybe_epilogue_fusion`` to
    the identity, so it is a genuinely untransformed execution — the
    flag-off leg then goes through the real entry point, and the bit-exact
    gate actually tests that the kill switch leaves the program untouched
    (comparing two flag-off runs would be a tautology)."""
    from paddle_tpu import monitor
    from paddle_tpu.executor import Executor

    orig = Executor._maybe_epilogue_fusion
    Executor._maybe_epilogue_fusion = \
        lambda self, program, feed, fetch_names, **kw: program
    try:
        base = run_probe("mlp", fused=False, report=report)
    finally:
        Executor._maybe_epilogue_fusion = orig
    off = run_probe("mlp", fused=False, report=report)
    fused_counter = monitor.metric_value("fusion_programs_total",
                                         outcome="applied") or 0
    # gate on the ops the executor actually compiled (n_fused_exec), not
    # the pass-level n_fused — both legs run fused=False so the latter is
    # 0 by construction and tests nothing about the kill switch
    ok = _gate("negative_zero_fused",
               base["n_fused_exec"] == 0 and off["n_fused_exec"] == 0
               and fused_counter == 0,
               f"compiled fused ops={off['n_fused_exec']}, "
               f"fusion_programs_total(applied)={fused_counter}", report)
    ok &= _gate("negative_bit_exact",
                np.array_equal(base["out"], off["out"]),
                "flag-off outputs bit-equal to a fusion-entry-disabled "
                "baseline", report)
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--negative-control", action="store_true",
                    help="verify the FLAGS_epilogue_fusion=0 kill switch: "
                         "zero fused ops, bit-exact baseline (exit 0 when "
                         "confirmed)")
    ap.add_argument("--json", metavar="PATH")
    ap.add_argument("--skip-autotune", action="store_true",
                    help="skip the subprocess round-trip (debug)")
    ap.add_argument("--child", choices=["measure", "use"],
                    help=argparse.SUPPRESS)
    ap.add_argument("--db", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        return _child(args.child, args.db)

    report = {"gates": [], "cpu_slack": CPU_SLACK,
              "tpu_min_speedup": TPU_MIN_SPEEDUP}
    if args.negative_control:
        ok = check_negative_control(report)
    else:
        ok = check_fusion_legs(report)
        if not args.skip_autotune:
            ok &= check_autotune_roundtrip(report)
    if args.json:
        for leg in report.get("legs", {}).values():
            leg.pop("out", None)
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, default=str)
        print(f"report written to {args.json}")
    if not ok:
        print("fusion gate -> FAIL", file=sys.stderr)
        return 1
    print("fusion gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
