#!/usr/bin/env python
"""Serving load generator + CI robustness gate (``paddle_tpu.serving``).

Drives ResNet-tiny and BERT-tiny inference traffic through a
:class:`ServingEngine` from concurrent submitter threads, then a CHAOS leg
that injects overload pressure, transient compile faults and one
slow-batch hang (armed under the step watchdog). The gate proves the
serving contract end to end:

* **exact accounting** — every submitted request reaches exactly one
  terminal outcome (response or typed rejection); zero silent drops, on
  every leg including chaos;
* **shedding works** — under overload pressure admission control sheds
  with typed ``Overloaded`` (the chaos leg requires ``shed > 0``);
* **faults are absorbed or isolated** — injected transient compile
  faults are retried away (``resilience_retries_total`` grows); the hang
  dies diagnosed under the watchdog (``watchdog_timeouts_total`` grows,
  the batch fails typed, the engine keeps serving);
* **SLOs are measurable** — the JSON artifact carries the full
  ``serving_request_latency_seconds`` histogram with estimated p50/p99.

Usage:
  python tools/load_check.py                 # full legs, prints summary
  python tools/load_check.py --ci --json ci_serving_report.json
      CI gate: tiny probes; exit 1 on any missed requirement.
  python tools/load_check.py --ci --negative-control
      Disables admission control (unbounded queue, no age bound) and
      re-runs the overload leg: with shedding off the gate MUST fail
      (``shed == 0`` under pressure) — CI asserts the non-zero exit.

Failure modes and flag table: docs/SERVING.md.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import monitor, serving  # noqa: E402
from paddle_tpu.resilience import fault_plan_guard  # noqa: E402


# ---------------------------------------------------------------------------
# model probes
# ---------------------------------------------------------------------------

def _resnet_engine(ci: bool, config: serving.ServingConfig):
    from paddle_tpu.models.resnet import build_resnet
    import paddle_tpu.unique_name as un

    with un.guard():
        shape = (3, 16, 16) if ci else (3, 32, 32)
        net = build_resnet(depth=18, class_num=10, image_shape=shape,
                           build_optimizer=False)
        infer = net["main"].clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(net["startup"], scope=scope)
    eng = serving.ServingEngine(
        infer, feed_names=["img", "label"],
        fetch_list=[net["logits"].name], scope=scope, executor=exe,
        config=config)

    def feed(rows=1, seed=0):
        rng = np.random.RandomState(seed)
        return {"img": rng.rand(rows, *shape).astype(np.float32),
                "label": np.zeros((rows, 1), np.int64)}

    return eng, feed


def _bert_engine(ci: bool, config: serving.ServingConfig):
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain
    import paddle_tpu.unique_name as un

    with un.guard():
        seq = 16 if ci else 32
        net = build_bert_pretrain(BertConfig.tiny(), seq_len=seq,
                                  build_optimizer=False, is_test=True)
        infer = net["main"].clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(net["startup"], scope=scope)
    eng = serving.ServingEngine(
        infer, feed_names=list(net["feeds"]),
        fetch_list=[net["loss"].name], scope=scope, executor=exe,
        config=config)

    def feed(rows=1, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "src_ids": rng.randint(0, 1024, (rows, seq)).astype(np.int64),
            "pos_ids": np.tile(np.arange(seq, dtype=np.int64), (rows, 1)),
            "sent_ids": np.zeros((rows, seq), np.int64),
            "input_mask": np.ones((rows, seq), np.float32),
            "mask_label": np.full((rows, seq), -100, np.int64),
            "next_sent_label": np.zeros((rows, 1), np.int64),
        }

    return eng, feed


def _gpt_engine(ci: bool, config: serving.ServingConfig,
                gen_config=None, **net_kw):
    """GPT-tiny generative engine (prefill/decode split scheduling over a
    paged KV cache) — the --decode legs' probe. ``net_kw`` overrides the
    model-build knobs (the speculative leg uses a longer KV + k=8)."""
    from paddle_tpu.models.gpt import GptConfig, build_gpt_generative
    import paddle_tpu.unique_name as un

    kw = dict(batch_slots=4, max_seq=32, page_size=8,
              prompt_buckets=(8, 16))
    kw.update(net_kw)
    with un.guard():
        net = build_gpt_generative(GptConfig.tiny(), **kw)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(net["startup"], scope=scope)
    eng = serving.GenerativeEngine(
        net, scope=scope, executor=exe, config=config,
        gen_config=gen_config or serving.GenerationConfig(decode_chunk=2))
    return eng


# ---------------------------------------------------------------------------
# traffic
# ---------------------------------------------------------------------------

def _drive(eng, feed_fn, n_requests, n_threads, rows_cycle=(1, 2),
           deadline_s=None, stagger_s=0.0):
    """Submit ``n_requests`` from ``n_threads`` threads and wait for every
    terminal outcome. Returns per-outcome counts as seen by CALLERS —
    cross-checked against the engine's own ledger by the gate."""
    seen = {"completed": 0, "overloaded": 0, "deadline": 0,
            "batch_failed": 0, "circuit_open": 0, "injected": 0,
            "stopped": 0, "other_error": 0}
    lock = threading.Lock()
    futures = []

    def note(key):
        with lock:
            seen[key] += 1

    def submitter(tid):
        for i in range(tid, n_requests, n_threads):
            rows = rows_cycle[i % len(rows_cycle)]
            try:
                fut = eng.submit(feed_fn(rows=rows, seed=i),
                                 deadline_s=deadline_s,
                                 priority=i % 3)
                with lock:
                    futures.append(fut)
            except serving.Overloaded:
                note("overloaded")
            except serving.EngineStopped:
                note("stopped")
            except Exception as e:
                from paddle_tpu.resilience.faults import InjectedFault

                note("injected" if isinstance(e, InjectedFault)
                     else "other_error")
            if stagger_s:
                time.sleep(stagger_s)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    for fut in futures:
        err = fut.exception(timeout=600)
        if err is None:
            note("completed")
        elif isinstance(err, serving.DeadlineExceeded):
            note("deadline")
        elif isinstance(err, serving.BatchFailed):
            note("batch_failed")
        elif isinstance(err, serving.CircuitOpen):
            note("circuit_open")
        elif isinstance(err, serving.EngineStopped):
            note("stopped")
        else:
            note("other_error")
    seen["submitted"] = n_requests
    seen["terminal"] = sum(v for k, v in seen.items()
                           if k not in ("submitted", "terminal"))
    return seen


def _latency_snapshot():
    snap = monitor.metric_value("serving_request_latency_seconds",
                                default=None)
    if not isinstance(snap, dict):
        return None
    return snap


# ---------------------------------------------------------------------------
# legs
# ---------------------------------------------------------------------------

def leg_steady(name, make_engine, ci):
    cfg = serving.ServingConfig(max_batch=4, queue_depth=64,
                                batch_window_s=0.01)
    eng, feed = make_engine(ci, cfg)
    eng.warm_up()
    n = 24 if ci else 96
    with eng:
        seen = _drive(eng, feed, n_requests=n, n_threads=3)
    acct = eng.accounting()
    ok = (acct["exact"] and seen["terminal"] == seen["submitted"]
          and seen["completed"] == n and acct["shed"] == 0
          and acct["failed"] == 0 and acct["deadline_exceeded"] == 0)
    return {"name": name, "ok": ok, "requests": n, "caller_view": seen,
            "engine_accounting": acct,
            "why": "all requests completed, zero sheds/failures "
                   "(negative control for the chaos leg)"}


def leg_chaos(name, make_engine, ci, shedding=True):
    """Overload + transient compile faults + one watchdog-diagnosed hang.
    ``shedding=False`` is the --negative-control variant: admission
    control is effectively disabled, so the gate's ``shed > 0``
    requirement MUST fail."""
    retries0 = monitor.metric_value("resilience_retries_total", 0.0,
                                    site="compile")
    wd0 = monitor.metric_value("watchdog_timeouts_total", 0.0,
                               section="step")
    cfg = serving.ServingConfig(
        max_batch=4,
        queue_depth=8 if shedding else 100_000,
        queue_age_s=5.0 if shedding else 0.0,
        degrade_after_s=0.2 if shedding else 1e9,
        recover_after_s=0.2, degraded_min_priority=1,
        breaker_threshold=3, breaker_cooldown_s=0.2)
    eng, feed = make_engine(ci, cfg)
    # transient compile faults during warm-up: the retry/backoff at the
    # compile site must absorb them (no caller ever sees one)
    with fault_plan_guard("compile:2:RuntimeError"):
        eng.warm_up()
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    n = 48 if ci else 160
    try:
        # one slow-batch hang (watchdog must break it, typed) + synthetic
        # overload pressure on top of the real burst
        plan = "hang:@2:hang" + (",overload:2:RuntimeError"
                                 if shedding else "")
        with eng, fault_plan_guard(plan):
            seen = _drive(eng, feed, n_requests=n, n_threads=4,
                          deadline_s=8.0)
    finally:
        fluid.set_flags({"FLAGS_step_timeout_s": 0.0})
    acct = eng.accounting()
    retries = monitor.metric_value("resilience_retries_total", 0.0,
                                   site="compile") - retries0
    wd = monitor.metric_value("watchdog_timeouts_total", 0.0,
                              section="step") - wd0
    shed_total = acct["shed"]
    checks = {
        "exact_accounting": bool(acct["exact"]),
        "every_submit_terminal": seen["terminal"] == seen["submitted"],
        "no_untyped_errors": seen["other_error"] == 0,
        "progress_under_chaos": seen["completed"] > 0,
        "hang_died_diagnosed": wd >= 1,
        "hang_batch_failed_typed": acct["failed"] >= 1,
        "compile_faults_retried": retries >= 2,
        "overload_was_shed": shed_total > 0,
        "engine_still_healthy": acct["pending"] == 0,
    }
    return {"name": name, "ok": all(checks.values()), "requests": n,
            "caller_view": seen, "engine_accounting": acct,
            "checks": checks,
            "watchdog_timeouts": wd, "compile_retries": retries,
            "why": "typed outcomes for 100% of submissions under "
                   "overload + compile faults + a watchdog-broken hang"}


def _drive_generate(eng, n_requests, n_threads, deadline_s=None,
                    seed=0):
    """Submit ``n_requests`` generation prompts from ``n_threads`` threads
    and wait for every terminal outcome. Returns caller-side outcome
    counts plus the expected/streamed token totals."""
    seen = {"completed": 0, "overloaded": 0, "deadline": 0,
            "batch_failed": 0, "stopped": 0, "injected": 0,
            "other_error": 0, "tokens_expected": 0, "tokens_streamed": 0}
    lock = threading.Lock()
    futures = []

    def note(key, n=1):
        with lock:
            seen[key] += n

    def submitter(tid):
        rng = np.random.RandomState(seed + tid)
        for i in range(tid, n_requests, n_threads):
            plen = 3 + (i % 10)
            max_new = 2 + (i % 5)
            try:
                fut = eng.submit(rng.randint(1, 128, plen),
                                 max_new_tokens=max_new,
                                 deadline_s=deadline_s, priority=i % 3)
                with lock:
                    futures.append((fut, max_new))
            except serving.Overloaded:
                note("overloaded")
            except serving.EngineStopped:
                note("stopped")
            except Exception as e:
                from paddle_tpu.resilience.faults import InjectedFault

                note("injected" if isinstance(e, InjectedFault)
                     else "other_error")

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    for fut, max_new in futures:
        err = fut.exception(timeout=600)
        note("tokens_streamed", len(fut.tokens()))
        if err is None:
            note("completed")
            note("tokens_expected", max_new)
            assert len(fut.result()[0]) == max_new
        elif isinstance(err, serving.DeadlineExceeded):
            note("deadline")
        elif isinstance(err, serving.BatchFailed):
            note("batch_failed")
        elif isinstance(err, serving.EngineStopped):
            note("stopped")
        else:
            note("other_error")
    seen["submitted"] = n_requests
    seen["terminal"] = sum(v for k, v in seen.items()
                           if k in ("completed", "overloaded", "deadline",
                                    "batch_failed", "stopped", "injected",
                                    "other_error"))
    return seen


def _decode_metrics(t_wall):
    toks = monitor.metric_value("serving_decode_tokens_total", 0.0)
    it = monitor.metric_value("serving_intertoken_seconds", default=None)
    out = {"tokens_total": toks,
           "tokens_per_s": (toks / t_wall) if t_wall > 0 else None}
    if isinstance(it, dict):
        out["intertoken_p50_ms"] = (it["p50"] or 0.0) * 1e3
        out["intertoken_p99_ms"] = (it["p99"] or 0.0) * 1e3
        out["intertoken_count"] = it["count"]
    return out


def leg_decode(name, ci):
    """GPT-tiny generation burst from multiple threads: every stream
    completes with exact per-stream accounting, one executable per
    (phase, bucket) — zero warm recompiles — and tokens/s + inter-token
    p50/p99 land in the artifact."""
    cfg = serving.ServingConfig(max_batch=4, queue_depth=64, deadline_s=0)
    eng = _gpt_engine(ci, cfg)
    eng.warm_up()
    n = 12 if ci else 48
    t0 = time.time()
    with eng:
        seen = _drive_generate(eng, n_requests=n, n_threads=3)
    t_wall = time.time() - t0
    acct = eng.accounting()
    stats = eng.generation_stats()
    metrics = _decode_metrics(t_wall)
    checks = {
        "exact_accounting": bool(acct["exact"]),
        "every_submit_terminal": seen["terminal"] == seen["submitted"],
        "all_completed": seen["completed"] == n,
        "token_counts_exact":
            seen["tokens_streamed"] == seen["tokens_expected"],
        "no_untyped_errors": seen["other_error"] == 0,
        "zero_warm_recompiles": stats["decode_recompiles"] == 0,
        # prefill:8 + prefill:16 + decode:4 + chunk:8 (the chunked-
        # prefill program is default-on since ISSUE 20)
        "one_executable_per_phase_bucket":
            len(stats["compiled_buckets"]) == 4,
        "intertoken_histogram_present":
            metrics.get("intertoken_count", 0) > 0,
    }
    return {"name": name, "ok": all(checks.values()), "requests": n,
            "caller_view": seen, "engine_accounting": acct,
            "checks": checks, "generation": stats, "decode": metrics,
            "why": "multi-thread generation burst: exact accounting, "
                   "bounded compiles, streaming SLO metrics"}


def leg_decode_chaos(name, ci):
    """Kill one in-flight decode/prefill batch (injected batch_dispatch
    fault): every affected stream must settle with a typed outcome, the
    engine keeps serving, accounting stays exact."""
    cfg = serving.ServingConfig(max_batch=4, queue_depth=64, deadline_s=0)
    eng = _gpt_engine(ci, cfg)
    eng.warm_up()
    n = 12 if ci else 32
    t0 = time.time()
    with eng:
        with fault_plan_guard("batch_dispatch:@3:RuntimeError"):
            seen = _drive_generate(eng, n_requests=n, n_threads=3, seed=7)
        # the engine must keep serving AFTER the killed batch
        post = eng.submit(np.array([3, 1, 4]), max_new_tokens=3)
        post_ok = len(post.result(timeout=600)[0]) == 3
    t_wall = time.time() - t0
    acct = eng.accounting()
    checks = {
        "exact_accounting": bool(acct["exact"]),
        "every_submit_terminal": seen["terminal"] == seen["submitted"],
        "no_untyped_errors": seen["other_error"] == 0,
        "killed_batch_settled_typed": seen["batch_failed"] >= 1,
        "progress_under_chaos": seen["completed"] > 0,
        "engine_serves_after_kill": post_ok,
        "engine_drained": acct["pending"] == 0,
    }
    return {"name": name, "ok": all(checks.values()), "requests": n,
            "caller_view": seen, "engine_accounting": acct,
            "checks": checks, "decode": _decode_metrics(t_wall),
            "why": "one in-flight batch killed: affected streams settle "
                   "typed BatchFailed, engine keeps serving"}


def _first_token_snap():
    s = monitor.metric_value("serving_first_token_seconds", default=None)
    return (s["count"], s["sum"]) if isinstance(s, dict) else (0, 0.0)


def leg_decode_prefix(name, ci, enabled=True):
    """Shared-prefix burst (ISSUE 20): a cold group of distinct long
    prompts, then a warm group repeating one 24-token prefix. Warm
    requests must HIT the prefix cache (skipping prefill for the shared
    pages — one suffix chunk slice instead of four cold slices) and
    show a lower average first-token latency than the cold group.
    ``enabled=False`` is the --negative-control variant: with the cache
    off the hit counters MUST stay zero, so the gate fails."""
    cfg = serving.ServingConfig(max_batch=4, queue_depth=64, deadline_s=0)
    gen = serving.GenerationConfig(decode_chunk=2, prefix_cache=enabled,
                                   chunked_prefill=True)
    eng = _gpt_engine(ci, cfg, gen_config=gen)
    eng.warm_up()
    rng = np.random.RandomState(20)
    shared = rng.randint(1, 128, 24)       # 3 whole 8-row pages
    n = 4 if ci else 12
    with eng:
        c0, s0 = _first_token_snap()
        for _ in range(n):                 # cold: distinct prefixes
            p = np.concatenate([rng.randint(1, 128, 24),
                                rng.randint(1, 128, 6)])
            eng.submit(p, max_new_tokens=2).result(timeout=600)
        c1, s1 = _first_token_snap()
        # seed publishes the shared pages, then the warm group hits them
        eng.submit(np.concatenate([shared, rng.randint(1, 128, 6)]),
                   max_new_tokens=2).result(timeout=600)
        c2, s2 = _first_token_snap()
        for _ in range(n):
            p = np.concatenate([shared, rng.randint(1, 128, 6)])
            eng.submit(p, max_new_tokens=2).result(timeout=600)
        c3, s3 = _first_token_snap()
    acct = eng.accounting()
    stats = eng.generation_stats()
    pc = stats["prefix_cache"] or {"hits": 0, "misses": max(1, 2 * n + 1),
                                   "pages_reused": 0, "pages": 0}
    hit_ratio = pc["hits"] / max(1, pc["hits"] + pc["misses"])
    cold_ms = (s1 - s0) / max(1, c1 - c0) * 1e3
    warm_ms = (s3 - s2) / max(1, c3 - c2) * 1e3
    ft = monitor.metric_value("serving_first_token_seconds", default=None)
    report = {
        "prefix_hit_ratio": hit_ratio,
        "prefix_hits": pc["hits"], "prefix_misses": pc["misses"],
        "pages_reused": pc["pages_reused"], "pages_resident": pc["pages"],
        "first_token_p50_ms":
            (ft["p50"] or 0.0) * 1e3 if isinstance(ft, dict) else None,
        "first_token_p99_ms":
            (ft["p99"] or 0.0) * 1e3 if isinstance(ft, dict) else None,
        "cold_first_token_avg_ms": cold_ms,
        "warm_first_token_avg_ms": warm_ms,
        "warm_speedup": (cold_ms / warm_ms) if warm_ms > 0 else None,
    }
    checks = {
        "exact_accounting": bool(acct["exact"]),
        "prefix_hits_positive": pc["hits"] >= n,
        "shared_pages_reused": pc["pages_reused"] >= 3 * n,
        "first_token_p99_reported":
            report["first_token_p99_ms"] is not None,
        "warm_first_token_faster_than_cold": warm_ms < cold_ms,
        "zero_warm_recompiles": stats["decode_recompiles"] == 0,
    }
    return {"name": name, "ok": all(checks.values()), "requests": 2 * n + 1,
            "caller_view": {"submitted": 2 * n + 1,
                            "completed": acct["completed"]},
            "engine_accounting": acct, "checks": checks,
            "generation": stats, "prefix": report,
            "why": "repeated 24-token prefix provably skips prefill for "
                   "the shared pages: hit counters + first-token delta"}


def leg_decode_spec(name, ci, enabled=True):
    """Speculative-decoding leg (ISSUE 20): the same greedy prompt set
    through a plain engine and a speculative engine. Gates: bit-exact
    streams, >= 1.5x tokens/s, acceptance histogram present.
    ``enabled=False`` is the --negative-control variant: with
    speculation off no acceptance histogram may exist, so the gate
    fails."""
    n = 6 if ci else 12
    max_new = 56

    def run(speculative):
        cfg = serving.ServingConfig(max_batch=4, queue_depth=64,
                                    deadline_s=0)
        gen = serving.GenerationConfig(
            decode_chunk=2, prefix_cache=False, chunked_prefill=False,
            speculative=speculative)
        # longer KV + k=8 (the full sublane tile): a fully accepted
        # verify chunk commits 8 tokens in ONE dispatch vs 2 for a plain
        # decode chunk, and 56-token streams amortize prefill overhead
        eng = _gpt_engine(ci, cfg, gen_config=gen, max_seq=128,
                          prompt_buckets=(8,), spec_k=8)
        eng.warm_up()
        rng = np.random.RandomState(5)
        prompts = [rng.randint(1, 128, 4 + i % 5) for i in range(n)]
        best_tps, outs = 0.0, []
        with eng:
            # one stream at a time: decode is latency-bound, the win is
            # tokens-per-dispatch (verify commits up to k+1 per chunk).
            # Best-of-two passes: greedy streams are deterministic, so
            # the repeat only de-noises the wall clock
            for _ in range(2):
                outs, t0 = [], time.time()
                for p in prompts:
                    outs.append(list(
                        eng.submit(p, max_new_tokens=max_new)
                        .result(timeout=600)[0]))
                wall = time.time() - t0
                toks = sum(len(o) for o in outs)
                best_tps = max(best_tps,
                               toks / wall if wall > 0 else 0.0)
        return eng, outs, best_tps

    plain_eng, plain_out, plain_tps = run(False)
    spec_eng, spec_out, spec_tps = run(enabled)
    acct = spec_eng.accounting()
    stats = spec_eng.generation_stats()
    accepted = monitor.metric_value("serving_spec_accepted_len",
                                    default=None)
    speedup = (spec_tps / plain_tps) if plain_tps > 0 else 0.0
    report = {
        "bit_exact": spec_out == plain_out,
        "tokens_per_s_plain": plain_tps,
        "tokens_per_s_spec": spec_tps,
        "speedup": speedup,
        "verify_chunks": stats["speculative"]["chunks"],
        "accepted_tokens": stats["speculative"]["accepted_tokens"],
        "accepted_len_avg":
            accepted["avg"] if isinstance(accepted, dict) else None,
        "accepted_len_p50":
            accepted["p50"] if isinstance(accepted, dict) else None,
    }
    checks = {
        "exact_accounting":
            bool(acct["exact"] and plain_eng.accounting()["exact"]),
        "greedy_bit_exact": report["bit_exact"],
        "speedup_at_least_1_5x": speedup >= 1.5,
        "acceptance_histogram_present": isinstance(accepted, dict)
            and accepted["count"] > 0,
        "zero_warm_recompiles": stats["decode_recompiles"] == 0
            and plain_eng.generation_stats()["decode_recompiles"] == 0,
    }
    return {"name": name, "ok": all(checks.values()), "requests": 4 * n,
            "caller_view": {"submitted": 4 * n,
                            "completed": acct["completed"]
                            + plain_eng.accounting()["completed"]},
            "engine_accounting": acct, "checks": checks,
            "generation": stats, "spec": report,
            "why": "greedy speculative decode bit-exact vs plain with "
                   ">=1.5x tokens/s (accept-verify in one dispatch)"}


# ---------------------------------------------------------------------------
# fleet legs (--fleet): multi-PROCESS replicas + router + warm start
# ---------------------------------------------------------------------------

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _replica_env():
    """Subprocess env for a replica: CPU backend, ONE device (strip the
    pytest parent's 8-device force), no inherited fault plans, and no
    jax persistent compile cache (it would contaminate the cold-vs-warm
    measurement — the warm-start cache under test must be the only
    cache)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    xf = [p for p in env.get("XLA_FLAGS", "").split()
          if not p.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(xf)
    for k in ("FLAGS_fault_plan", "JAX_COMPILATION_CACHE_DIR",
              "FLAGS_step_timeout_s"):
        env.pop(k, None)
    return env


class _ReplicaProc:
    """One replica subprocess: spawn, parse the ready/exit stdout
    events, SIGTERM-drain, reap."""

    def __init__(self, model: str, replica_id: str, aot_dir: str = "",
                 log_dir: str = ".", extra_args=()):
        cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.replica",
               "--model", model, "--replica-id", replica_id,
               "--queue-depth", "256"]
        if aot_dir:
            cmd += ["--aot-cache", aot_dir]
        cmd += list(extra_args)
        self.replica_id = replica_id
        self.log_path = os.path.join(log_dir, f"replica_{replica_id}.log")
        self._log = open(self.log_path, "w")
        self.t_spawn = time.perf_counter()
        self.proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                     stderr=self._log, text=True,
                                     cwd=_REPO_ROOT, env=_replica_env())
        self.ready_info = None
        self.exit_info = None
        self.wall_to_ready = None
        self._ready_ev = threading.Event()
        threading.Thread(target=self._reader, daemon=True).start()

    def _reader(self):
        for line in self.proc.stdout:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("event") == "ready":
                self.wall_to_ready = time.perf_counter() - self.t_spawn
                self.ready_info = obj
                self._ready_ev.set()
            elif obj.get("event") == "exit":
                self.exit_info = obj

    def wait_ready(self, timeout: float = 240.0):
        if not self._ready_ev.wait(timeout):
            raise RuntimeError(
                f"replica {self.replica_id} did not become ready within "
                f"{timeout:g}s (see {self.log_path})")
        return self.ready_info

    @property
    def port(self) -> int:
        return int(self.ready_info["port"])

    def sigterm(self):
        self.proc.send_signal(signal.SIGTERM)

    def wait_exit(self, timeout: float = 60.0) -> int:
        rc = self.proc.wait(timeout)
        self._log.close()
        return rc

    def destroy(self):
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(10)
        if not self._log.closed:
            self._log.close()


def _drive_fleet(router, feed_fn, n_requests, n_threads,
                 kill_at=None, kill_fn=None):
    """Submit ``n_requests`` through the ROUTER from ``n_threads``
    threads; after ``kill_at`` submissions have started, fire
    ``kill_fn`` (the mid-burst SIGTERM). Returns caller-side outcome
    counts — cross-checked against the router's fleet-wide ledger."""
    from paddle_tpu.serving.fleet import ReplicaLost

    seen = {"completed": 0, "shed": 0, "deadline": 0, "failed": 0,
            "circuit_open": 0, "stopped": 0, "replica_lost": 0,
            "other_error": 0}
    lock = threading.Lock()
    started = [0]
    started_ev = threading.Event()

    def note(key):
        with lock:
            seen[key] += 1

    def submitter(tid):
        for i in range(tid, n_requests, n_threads):
            with lock:
                started[0] += 1
                if kill_at is not None and started[0] >= kill_at:
                    started_ev.set()
            try:
                router.submit(feed_fn(rows=1, seed=i), priority=i % 3)
                note("completed")
            except ReplicaLost:
                note("replica_lost")
            except serving.Overloaded:
                note("shed")
            except serving.DeadlineExceeded:
                note("deadline")
            except serving.BatchFailed:
                note("failed")
            except serving.CircuitOpen:
                note("circuit_open")
            except serving.EngineStopped:
                note("stopped")
            except Exception:
                note("other_error")

    killer = None
    if kill_fn is not None:
        def _killer():
            started_ev.wait(300)
            kill_fn()
        killer = threading.Thread(target=_killer, daemon=True)
        killer.start()
    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    if killer is not None:
        killer.join(60)
    seen["submitted"] = n_requests
    seen["terminal"] = sum(v for k, v in seen.items()
                           if k not in ("submitted", "terminal"))
    return seen


def _mlp_feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"img": rng.rand(rows, 784).astype(np.float32),
            "label": np.zeros((rows, 1), np.int64)}


def leg_fleet(name, ci, log_dir="."):
    """The 2-replica fleet gate: r0 starts COLD and populates the
    warm-start cache; r1 starts from it WARM (the measured cold-vs-warm
    pair). Both serve a multi-thread burst through the router; r0 is
    SIGTERMed mid-burst — it drains everything it admitted (typed, exact)
    while the router routes away and retries only unadmitted dispatches
    on r1. Requirements: exact fleet-wide accounting, zero untyped
    errors, zero admitted-request losses, a clean victim exit, and a
    measurably faster warm start."""
    from paddle_tpu.serving.fleet import FleetRouter, Replica

    aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_fleet_aot_")
    r0 = r1 = None
    try:
        r0 = _ReplicaProc("mlp_tiny", "r0", aot_dir, log_dir)
        cold = dict(r0.wait_ready())
        r1 = _ReplicaProc("mlp_tiny", "r1", aot_dir, log_dir)
        warm = dict(r1.wait_ready())

        router = FleetRouter([Replica("r0", "127.0.0.1", r0.port),
                              Replica("r1", "127.0.0.1", r1.port)])
        n = 36 if ci else 120
        with router:
            seen = _drive_fleet(router, _mlp_feed, n_requests=n,
                                n_threads=4, kill_at=n // 3,
                                kill_fn=r0.sigterm)
            acct = router.accounting()
        rc = r0.wait_exit(60)
        victim = r0.exit_info or {}
        vacct = victim.get("accounting", {})
        r1.sigterm()
        r1.wait_exit(60)
        survivor = (r1.exit_info or {}).get("accounting", {})

        lat = monitor.metric_value("router_request_seconds", default=None)
        cold_cache = cold.get("aot_cache", {})
        warm_cache = warm.get("aot_cache", {})
        checks = {
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal": seen["terminal"] == seen["submitted"],
            "all_completed": seen["completed"] == n,
            "no_untyped_errors": seen["other_error"] == 0,
            "nothing_admitted_lost":
                seen["replica_lost"] == 0 and seen["stopped"] == 0
                and seen["failed"] == 0,
            "victim_exit_clean": rc == 0 and bool(vacct.get("exact"))
                and vacct.get("pending", -1) == 0,
            "victim_shed_nothing_admitted":
                vacct.get("shed", -1) == 0 and vacct.get("failed", -1) == 0,
            "victim_served_before_drain": vacct.get("completed", 0) > 0,
            "survivor_served": survivor.get("completed", 0) > 0,
            "latency_histogram_present":
                isinstance(lat, dict) and lat["count"] > 0
                and lat["p50"] is not None and lat["p99"] is not None,
            # warm start: the restarted-cold-with-cache replica must be
            # measurably faster to ready than the cold baseline
            "cold_populated_cache": cold_cache.get("hits") == 0
                and cold_cache.get("saves", 0) >= 1,
            "warm_loaded_from_cache": warm_cache.get("hits", 0) >= 1
                and warm_cache.get("misses", 1) == 0,
            "warm_up_measurably_faster":
                warm["warm_up_s"] < 0.6 * cold["warm_up_s"],
            "warm_ready_faster":
                warm["time_to_ready_s"] < cold["time_to_ready_s"],
        }
        warmstart = {
            "cold": {"time_to_ready_s": cold["time_to_ready_s"],
                     "warm_up_s": cold["warm_up_s"],
                     "wall_to_ready_s": r0.wall_to_ready,
                     "aot_cache": cold_cache},
            "warm": {"time_to_ready_s": warm["time_to_ready_s"],
                     "warm_up_s": warm["warm_up_s"],
                     "wall_to_ready_s": r1.wall_to_ready,
                     "aot_cache": warm_cache},
            "ready_speedup":
                cold["time_to_ready_s"] / max(warm["time_to_ready_s"],
                                              1e-9),
            "warm_up_speedup":
                cold["warm_up_s"] / max(warm["warm_up_s"], 1e-9),
        }
        return {"name": name, "ok": all(checks.values()), "requests": n,
                "caller_view": seen, "router_accounting": acct,
                "victim_accounting": vacct,
                "survivor_accounting": survivor,
                "checks": checks, "warmstart": warmstart,
                "latency": lat,
                "why": "kill one of two replicas mid-burst: the fleet "
                       "completes 100% of admitted requests with exactly-"
                       "one-outcome accounting, and a warm-start replica "
                       "is measurably faster to ready"}
    finally:
        for r in (r0, r1):
            if r is not None:
                r.destroy()
        shutil.rmtree(aot_dir, ignore_errors=True)


def leg_fleet_negative(name, ci, log_dir="."):
    """--fleet --negative-control: the router runs with drain honoring
    AND the unadmitted-sibling retry disabled (the two behaviors the
    kill scenario exercises). After the mid-burst SIGTERM the router
    keeps dispatching to the draining/dead replica, so requests reach
    typed stopped/replica-lost outcomes — the gate MUST fail."""
    from paddle_tpu.serving.fleet import (FleetRouter, Replica,
                                          RouterConfig)

    aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_fleet_aot_")
    r0 = r1 = None
    try:
        r0 = _ReplicaProc("mlp_tiny", "r0", aot_dir, log_dir)
        r0.wait_ready()
        r1 = _ReplicaProc("mlp_tiny", "r1", aot_dir, log_dir)
        r1.wait_ready()
        router = FleetRouter(
            [Replica("r0", "127.0.0.1", r0.port),
             Replica("r1", "127.0.0.1", r1.port)],
            config=RouterConfig(honor_drain=False,
                                retry_unadmitted=False))
        n = 36 if ci else 120
        with router:
            seen = _drive_fleet(router, _mlp_feed, n_requests=n,
                                n_threads=4, kill_at=n // 3,
                                kill_fn=r0.sigterm)
            acct = router.accounting()
        r1.sigterm()
        checks = {
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal": seen["terminal"] == seen["submitted"],
            "all_completed": seen["completed"] == n,
            "no_untyped_errors": seen["other_error"] == 0,
            "nothing_admitted_lost":
                seen["replica_lost"] == 0 and seen["stopped"] == 0
                and seen["failed"] == 0,
        }
        return {"name": name, "ok": all(checks.values()), "requests": n,
                "caller_view": seen, "router_accounting": acct,
                "checks": checks,
                "why": "drain honoring + unadmitted retry disabled: the "
                       "kill scenario must trip the gate"}
    finally:
        for r in (r0, r1):
            if r is not None:
                r.destroy()
        shutil.rmtree(aot_dir, ignore_errors=True)


def _corrupt_metrics_stub():
    """A 'replica' whose ``/metrics`` endpoints answer 200 with an
    undecodable body — the telemetry leg's negative control. Returns
    ``(server, port)``; the caller shuts it down."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"\x00\xffdefinitely{not a metrics body"
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def _drive_tenant_burst(router, n, n_threads, tenants):
    """Submit ``n`` standard-priority requests through the router, tagged
    with ``tenants`` round-robin. Returns caller-side outcome counts
    (every outcome typed, like :func:`_drive_fleet`)."""
    from paddle_tpu.serving.fleet import ReplicaLost

    seen = {"completed": 0, "failed": 0, "shed": 0, "deadline": 0,
            "circuit_open": 0, "stopped": 0, "replica_lost": 0,
            "other_error": 0}
    lock = threading.Lock()

    def note(key):
        with lock:
            seen[key] += 1

    def submitter(tid):
        for i in range(tid, n, n_threads):
            try:
                router.submit(_mlp_feed(rows=1, seed=i), priority=1,
                              tenant=tenants[i % len(tenants)])
                note("completed")
            except serving.BatchFailed:
                note("failed")
            except ReplicaLost:
                note("replica_lost")
            except serving.Overloaded:
                note("shed")
            except serving.DeadlineExceeded:
                note("deadline")
            except serving.CircuitOpen:
                note("circuit_open")
            except serving.EngineStopped:
                note("stopped")
            except Exception:
                note("other_error")

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    return threads, seen


def leg_fleet_telemetry(name, ci, log_dir="."):
    """The telemetry-plane gate (docs/OBSERVABILITY.md "Fleet telemetry
    plane"): 2 replica PROCESSES serving ``/metrics`` + a third target
    serving a CORRUPT body, scraped by an in-process
    :class:`FleetAggregator`. Proves, end to end over the wire:

    * fleet p50/p99 assembled from SCRAPED per-replica histograms via
      the exact bucket-wise merge, count cross-checked against the
      router's own completed ledger;
    * SLO burn state flips to ``burning`` under injected stalled-batch
      faults on one replica (``batch_dispatch`` fault plan) and recovers
      to ``ok`` once the burn windows drain;
    * the per-tenant ledger sums EXACTLY to the fleet outcome ledger,
      outcome by outcome;
    * at least one exported exemplar ``trace_id`` resolves to a recorded
      trace (the router-side root span — one trace id across processes);
    * the corrupt-``/metrics`` target degrades typed: marked stale,
      ``fleet_scrape_failures_total{kind=corrupt}`` counted, the
      aggregator keeps scraping/publishing the healthy replicas and its
      poll thread stays alive (zero crashes).
    """
    from paddle_tpu import flags as flags_mod
    from paddle_tpu import trace
    from paddle_tpu.serving.fleet import (AggregatorConfig, FleetAggregator,
                                          FleetRouter, Replica)

    # squeezed burn windows so the ok -> burning -> ok round trip fits a
    # CI leg; targets/budget stay at defaults (1% budget: one failed
    # batch flips both windows hot immediately)
    slo_flags = ["--set-flag", "FLAGS_serving_slo_fast_window_s=2",
                 "--set-flag", "FLAGS_serving_slo_slow_window_s=6"]
    tele_args = ["--trace", "--set-flag", "FLAGS_fleet_telemetry=1"]
    stall_args = ["--set-flag",
                  "FLAGS_fault_plan=batch_dispatch:2:TimeoutError"]
    aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_fleet_tele_aot_")
    saved_overrides = dict(flags_mod._overrides)
    r0 = r1 = stub = agg = None
    burn_timeline = []

    def observe_state(agg, t0):
        snap = agg.snapshot()
        st = snap["fleet"]["slo_state"]
        if not burn_timeline or burn_timeline[-1][1] != st:
            burn_timeline.append((round(time.monotonic() - t0, 2), st))
        return st, snap

    try:
        # the aggregator + router run IN PROCESS: they need the plane and
        # tracing on locally too (exemplar resolution joins the router's
        # recorded root spans)
        fluid.set_flags({"FLAGS_fleet_telemetry": 1, "FLAGS_trace": 1})
        r0 = _ReplicaProc("mlp_tiny", "r0", aot_dir, log_dir,
                          extra_args=tele_args + slo_flags)
        r0.wait_ready()
        r1 = _ReplicaProc("mlp_tiny", "r1", aot_dir, log_dir,
                          extra_args=tele_args + slo_flags + stall_args)
        r1.wait_ready()
        stub, bad_port = _corrupt_metrics_stub()

        router = FleetRouter([Replica("r0", "127.0.0.1", r0.port),
                              Replica("r1", "127.0.0.1", r1.port)])
        agg = FleetAggregator(
            [("r0", f"127.0.0.1:{r0.port}"),
             ("r1", f"127.0.0.1:{r1.port}"),
             ("rbad", f"127.0.0.1:{bad_port}")],
            AggregatorConfig(scrape_interval_s=0.25, scrape_timeout_s=5.0))
        n = 28 if ci else 80
        tenants = ("acme", "globex", "initech")
        t0 = time.monotonic()
        burning_seen = recovered = False
        with router:
            with agg:
                threads, seen = _drive_tenant_burst(router, n, 4, tenants)
                # poll while the burst runs: the stalled batches land at
                # its head, so burning must be OBSERVED inside the fast
                # window, not reconstructed afterwards
                while any(t.is_alive() for t in threads):
                    st, _ = observe_state(agg, t0)
                    burning_seen = burning_seen or st == "burning"
                    time.sleep(0.15)
                for t in threads:
                    t.join(600)
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    st, _ = observe_state(agg, t0)
                    burning_seen = burning_seen or st == "burning"
                    if burning_seen and st == "ok":
                        recovered = True
                        break
                    time.sleep(0.25)
                agg.poll_now()
                final = agg.snapshot()
                thread_alive = (agg._thread is not None
                                and agg._thread.is_alive())
            acct = router.accounting()
        seen["submitted"] = n
        seen["terminal"] = sum(v for k, v in seen.items()
                               if k not in ("submitted", "terminal"))

        fleet = final["fleet"]
        replicas = final["replicas"]
        merged_count = (fleet["latency"] or {}).get("count", 0)
        # tenant reconciliation: outcome by outcome, the summed tenant
        # ledger must equal the scraped fleet outcome ledger exactly
        tenant_sums = {}
        for t in fleet["tenants"].values():
            for o, c in t["outcomes"].items():
                tenant_sums[o] = tenant_sums.get(o, 0) + c
        fleet_outcomes = {k: int(v) for k, v in fleet["outcomes"].items()}
        # exemplar resolution: exported trace ids join the router's
        # in-process recorded spans (one trace id across processes)
        exported = set()
        for rec in (replicas.get("r0"), replicas.get("r1")):
            for fam in (rec or {}).get("exemplars", {}).values():
                for child in fam:
                    for ring in child["buckets"].values():
                        exported.update(e["trace_id"] for e in ring)
        recorded = {s.trace_id for s in trace.spans()}
        resolved = sorted(exported & recorded)
        rbad = replicas.get("rbad") or {}
        corrupt_count = monitor.metric_value(
            "fleet_scrape_failures_total", default=0,
            replica="rbad", kind="corrupt")

        checks = {
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal": seen["terminal"] == seen["submitted"],
            "no_untyped_errors": seen["other_error"] == 0,
            "stall_faults_burned_budget": seen["failed"] > 0,
            "fleet_latency_scraped":
                fleet["p50"] is not None and fleet["p99"] is not None,
            "scraped_count_matches_router_ledger":
                merged_count == acct["completed"] > 0,
            "scraped_completed_matches_router_ledger":
                int(fleet_outcomes.get("completed", 0))
                == acct["completed"],
            "slo_burning_observed": burning_seen,
            "slo_recovered": recovered,
            "tenant_ledger_reconciles":
                bool(tenant_sums) and tenant_sums == fleet_outcomes,
            "all_tenants_accounted":
                set(tenants) <= set(fleet["tenants"]),
            "exemplar_resolves_to_trace": len(resolved) > 0,
            "corrupt_target_stale":
                bool(rbad.get("stale")) and not rbad.get("up")
                and rbad.get("error") == "corrupt"
                and rbad.get("consecutive_failures", 0) >= 1,
            "corrupt_failures_counted": corrupt_count >= 1,
            "healthy_replicas_kept_publishing":
                bool(replicas.get("r0", {}).get("up"))
                and bool(replicas.get("r1", {}).get("up")),
            "aggregator_thread_survived": thread_alive,
        }
        telemetry = {
            "fleet_p50_s": fleet["p50"], "fleet_p99_s": fleet["p99"],
            "scraped_latency_count": merged_count,
            "router_completed": acct["completed"],
            "fleet_outcomes": fleet_outcomes,
            "tenants": fleet["tenants"],
            "slo_timeline": burn_timeline,
            "slo_state_final": fleet["slo_state"],
            "exemplars_exported": len(exported),
            "exemplar_resolved_trace_ids": resolved[:4],
            "corrupt_scrapes": int(corrupt_count),
            "scrape_ages_s": {rid: rec.get("scrape_age_s")
                              for rid, rec in replicas.items()},
        }
        return {"name": name, "ok": all(checks.values()), "requests": n,
                "caller_view": seen, "router_accounting": acct,
                "checks": checks, "telemetry": telemetry,
                "why": "fleet p50/p99 from scraped /metrics cross-checked "
                       "vs the router ledger; SLO burns and recovers "
                       "under injected stalled batches; tenant ledger "
                       "reconciles exactly; exemplars resolve to traces; "
                       "a corrupt /metrics target degrades typed with "
                       "zero aggregator crashes"}
    finally:
        if agg is not None:
            agg.stop()
        if stub is not None:
            stub.shutdown()
        for r in (r0, r1):
            if r is not None:
                r.sigterm()
        for r in (r0, r1):
            if r is not None:
                try:
                    r.wait_exit(60)
                except Exception:
                    pass
                r.destroy()
        shutil.rmtree(aot_dir, ignore_errors=True)
        flags_mod._overrides.clear()
        flags_mod._overrides.update(saved_overrides)
        flags_mod._set_epoch += 1


# ---------------------------------------------------------------------------
# fleet control-loop legs (--autoscale): SLO-driven autoscaling + tenant
# fair-share — docs/SERVING.md "Fleet control loop". A supervised fleet
# behind the router plus a FleetAutoscaler: a hot-tenant flood must burn
# the SLO, scale OUT a second replica warm through the fleet-shared AOT
# cache AND the fleet-shared autotune CostDatabase, shed the hot tenant
# typed tenant_quota while innocent tenants keep completing, then scale
# back IN strictly via preemption-drain once calm — fleet ledger exact
# throughout, every decision typed/metered/audited.
# ---------------------------------------------------------------------------

_AUTOSCALE_REPLICA_ARGS = [
    # a deliberately slow dispatcher (wide batch window) + a small queue
    # so a hog flood piles real, sustained admission pressure
    "--batch-window-s", "0.15", "--queue-depth", "16"]
_AUTOSCALE_TENANT_FLAGS = [
    # queue_depth 16 * frac 0.125 -> the hog caps at 2 queued slots: low
    # enough that 8 open-loop hog threads (at most 4 in the in-flight
    # batch + the rest queued) provably overrun it
    "--set-flag", "FLAGS_serving_tenant_fair_share=1",
    "--set-flag", "FLAGS_serving_tenant_quota_frac=0.125"]
_AUTOSCALE_SLO_FLAGS = [
    # squeezed burn windows (the telemetry leg's trick) so the
    # burn -> recover round trip fits one CI leg
    "--set-flag", "FLAGS_serving_slo_fast_window_s=2",
    "--set-flag", "FLAGS_serving_slo_slow_window_s=6"]


def _seed_shared_autotune_db(db_path):
    """Populate the fleet-shared autotune CostDatabase IN-PROCESS with a
    real (tiny) measured sweep over the replica probe's warm-up buckets.
    ``build_probe`` guarantees the program CONTENT fingerprint matches
    what every replica process builds, so a replica spawned with
    ``FLAGS_autotune=use`` + this DB warms straight to best-known
    configs: lookups hit, zero re-trials. (measure_candidates is not
    safe under live traffic — which is exactly why the harness seeds the
    DB offline and the fleet only ever consumes it.)"""
    from paddle_tpu import tuning
    from paddle_tpu.core.types import np_dtype
    from paddle_tpu.serving.fleet.replica import build_probe

    fluid.set_flags({"FLAGS_autotune": "measure",
                     "FLAGS_autotune_db": db_path})
    tuning.reset_database_cache()
    eng, _meta = build_probe("mlp_tiny", serving.ServingConfig(max_batch=4))
    db = tuning.get_database(db_path)
    candidates = [tuning.TunedConfig.make({}),
                  tuning.TunedConfig.make(
                      {"xla_cpu_enable_fast_min_max": True})]
    blk = eng._program.global_block
    buckets = []
    for b in (1, 2, 4):   # the warm-up buckets for max_batch=4
        feed = {}
        for n in eng._feed_names:
            v = blk.var(n)
            tail = tuple(int(d) for d in v.shape[1:])
            feed[n] = np.zeros((b,) + tail, dtype=np_dtype(v.dtype))
        rep = tuning.measure_candidates(
            eng._exe, eng._program, feed, eng._fetch_names, eng._scope,
            candidates=candidates, k_short=1, k_long=2, repeats=1,
            batch_rows=b, db=db)
        buckets.append(rep["bucket"])
    # this process is done measuring; the fleet consumes in use mode
    fluid.set_flags({"FLAGS_autotune": "use"})
    return {"path": db_path, "trials": db.trial_count(),
            "buckets": buckets}


def _drive_autoscale_burst(router, stop_ev, pause_ev=None, hog_threads=8,
                           small_tenants=("acme", "globex")):
    """Open-loop hog flood (each thread re-submits immediately; typed
    sheds back off a beat) + one closed-loop thread per innocent tenant.
    Outcomes are counted per tenant WITH the Overloaded reason split
    out: ``shed_tenant_quota`` vs ``shed_other`` is the whole point of
    the leg. Innocent-tenant latencies are collected caller-side for
    the p99-held check. ``pause_ev`` set suspends the HOG threads only
    (the leg pauses the flood while the scaled-out replica spawns, so
    the warm-vs-cold time-to-ready comparison is load-for-load fair on
    a small box — the innocents keep trickling). Everything submits at
    priority 5: the engine's own degraded mode sheds below
    ``degraded_min_priority``, and this leg needs ``tenant_quota`` to
    be the ONLY shed in play."""
    from paddle_tpu.serving.fleet import ReplicaLost

    lock = threading.Lock()
    seen = {"submitted": 0, "completed": 0, "shed_tenant_quota": 0,
            "shed_other": 0, "failed": 0, "deadline": 0,
            "circuit_open": 0, "stopped": 0, "replica_lost": 0,
            "other_error": 0}
    per_tenant = {}
    small_latencies = []

    def note(tenant, key, latency=None):
        with lock:
            seen["submitted"] += 1
            seen[key] += 1
            t = per_tenant.setdefault(tenant, {})
            t[key] = t.get(key, 0) + 1
            if latency is not None and tenant in small_tenants:
                small_latencies.append(latency)

    def one(tenant, seed):
        t0 = time.perf_counter()
        try:
            router.submit(_mlp_feed(rows=1, seed=seed % 100000),
                          priority=5, tenant=tenant)
            note(tenant, "completed", time.perf_counter() - t0)
            return True
        except serving.Overloaded as e:
            note(tenant, "shed_tenant_quota"
                 if getattr(e, "reason", "") == "tenant_quota"
                 else "shed_other")
        except serving.BatchFailed:
            note(tenant, "failed")
        except serving.DeadlineExceeded:
            note(tenant, "deadline")
        except serving.CircuitOpen:
            note(tenant, "circuit_open")
        except serving.EngineStopped:
            note(tenant, "stopped")
        except ReplicaLost:
            note(tenant, "replica_lost")
        except Exception:
            note(tenant, "other_error")
        return False

    def hog(tid):
        i = 0
        while not stop_ev.is_set():
            if pause_ev is not None and pause_ev.is_set():
                time.sleep(0.05)
                continue
            if not one("hog", tid * 1000 + i):
                time.sleep(0.01)
            i += 1

    def small(tenant, tid):
        i = 0
        while not stop_ev.is_set():
            one(tenant, 7000 + tid * 1000 + i)
            i += 1
            time.sleep(0.05)

    threads = [threading.Thread(target=hog, args=(t,))
               for t in range(hog_threads)]
    threads += [threading.Thread(target=small, args=(name, t))
                for t, name in enumerate(small_tenants)]
    for t in threads:
        t.start()
    return threads, seen, per_tenant, small_latencies


def leg_autoscale(name, ci, log_dir="."):
    """--autoscale: the closed fleet control loop, end to end over
    processes. One supervised replica starts COLD (empty AOT cache, but
    the harness-seeded shared autotune DB); a hot-tenant flood burns the
    SLO budget through typed tenant_quota sheds; the FleetAutoscaler
    must scale out a second replica (warm: shared AOT cache + autotune
    hits, zero re-trials, measurably faster time-to-ready than the cold
    baseline), refuse further scale-out typed at_max_replicas, and —
    once the burst stops and the squeezed burn windows drain — scale
    back in strictly via preemption-drain (victim exits 0 with an exact
    ledger) then hold the floor typed at_min_replicas. Innocent tenants
    must keep completing with their caller-side p99 held the whole
    time."""
    from paddle_tpu import flags as flags_mod
    from paddle_tpu.serving.fleet import (AutoscalerConfig,
                                          FleetAutoscaler,
                                          ReplicaSupervisor,
                                          SupervisorConfig)

    aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_autoscale_aot_")
    db_dir = tempfile.mkdtemp(prefix="paddle_tpu_autoscale_db_")
    db_path = os.path.join(db_dir, "autotune_db.json")
    saved_overrides = dict(flags_mod._overrides)
    router = sup = auto = None
    stop_ev = threading.Event()
    threads = []
    try:
        seeded = _seed_shared_autotune_db(db_path)
        replica_args = (_AUTOSCALE_REPLICA_ARGS + _AUTOSCALE_TENANT_FLAGS
                        + _AUTOSCALE_SLO_FLAGS)
        router = _chaos_router(request_timeout_s=30.0)
        sup = ReplicaSupervisor(
            router,
            SupervisorConfig(
                ready_timeout_s=240.0, exit_grace_s=60.0,
                # the fleet-shared autotune story rides EVERY spawn —
                # including the autoscaler's, which never mentions it
                shared_flags={"FLAGS_autotune": "use",
                              "FLAGS_autotune_db": db_path}),
            log_dir=log_dir, env=_replica_env(), cwd=_REPO_ROOT)
        sup.add_replica("r0", "mlp_tiny", aot_dir,
                        extra_args=replica_args)
        cold = sup.handle("r0").wait_ready(240)
        router.start()
        assert _wait_routable(router, "r0")

        auto = FleetAutoscaler(
            sup, router=router,
            config=AutoscalerConfig(
                min_replicas=1, max_replicas=2, interval_s=0.2,
                cooldown_s=2.0, hot_sustain_s=1.0, calm_sustain_s=3.0,
                max_inflight_spawns=1, queue_high=4),
            model="mlp_tiny", aot_dir=aot_dir, extra_args=replica_args)
        auto.start()

        pause_ev = threading.Event()
        threads, seen, per_tenant, small_lat = _drive_autoscale_burst(
            router, stop_ev, pause_ev)
        # the flood sheds the hog typed tenant_quota; sheds are bad SLO
        # outcomes, so the burn state flips and SUSTAINS -> scale-out
        warm = None
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline and "as1" not in sup.status():
            time.sleep(0.1)
        scaled_spawned = "as1" in sup.status()
        if scaled_spawned:
            # suspend the hog flood while the spawn warms up: the cold
            # baseline spawned on an idle box, and the point of the
            # comparison is the shared caches, not CPU contention
            pause_ev.set()
            warm = sup.handle("as1").wait_ready(240)
            _wait_routable(router, "as1")
            pause_ev.clear()
        # keep the burst on the scaled-out fleet: the refusal ladder at
        # max_replicas must fire typed while both replicas take traffic
        time.sleep(3.5 if ci else 5.0)
        stop_ev.set()
        for t in threads:
            t.join(120)

        # calm: the squeezed windows drain, the loop must scale back IN
        # strictly via preemption-drain of the replica it spawned
        drained_clean = False
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            if sup.status().get("as1", {}).get("state") == "stopped":
                drained_clean = True
                break
            time.sleep(0.2)
        # hold the floor a beat: at_min_replicas must be typed + metered
        time.sleep(2.0 if ci else 3.0)
        status = auto.status()
        audit = status["audit"]
        auto.stop()
        acct = router.accounting()
        as1 = sup.handle("as1") if scaled_spawned else None
        victim_acct = ((as1.exit_info or {}).get("accounting") or {}) \
            if as1 is not None else {}
        last_exit = (as1.last_exit or {}) if as1 is not None else {}
        sup.stop(drain=True)
        router.stop()

        seen["terminal"] = sum(v for k, v in seen.items()
                               if k not in ("submitted", "terminal"))

        def decided(action, reason=None):
            return any(e["action"] == action
                       and (reason is None or e["reason"] == reason)
                       for e in audit)

        out = next((e for e in audit if e["action"] == "scale_out"), None)
        hog = per_tenant.get("hog", {})
        smalls = {t: per_tenant.get(t, {}) for t in ("acme", "globex")}
        small_shed = sum(v.get("shed_tenant_quota", 0)
                         + v.get("shed_other", 0)
                         for v in smalls.values())
        p99 = (sorted(small_lat)[max(0, int(0.99 * (len(small_lat) - 1)))]
               if small_lat else None)

        checks = {
            "scale_out_on_sustained_hot":
                out is not None and scaled_spawned and warm is not None,
            "scale_out_reason_typed_hot":
                out is not None
                and (out["reason"] == "slo_burn"
                     or out["reason"].startswith("pressure")),
            "warm_ready_faster_than_cold":
                warm is not None
                and warm["time_to_ready_s"] < cold["time_to_ready_s"],
            "warm_loaded_from_aot_cache":
                warm is not None and warm["aot_cache"]["hits"] >= 1
                and warm["aot_cache"]["misses"] == 0,
            "autotune_shared_db_hit":
                warm is not None and warm["autotune"]["mode"] == "use"
                and warm["autotune"]["hits"] >= 1,
            "autotune_zero_retrials":
                warm is not None and warm["autotune"]["trials"] == 0
                and cold["autotune"]["trials"] == 0,
            "hot_tenant_shed_typed_tenant_quota":
                hog.get("shed_tenant_quota", 0) >= 1,
            "innocent_tenants_kept_admitted":
                small_shed == 0
                and all(v.get("completed", 0) >= 3
                        for v in smalls.values()),
            "innocent_p99_held": p99 is not None and p99 < 5.0,
            "refusal_ladder_typed":
                decided("refuse_scale_out", "at_max_replicas")
                and decided("refuse_scale_in", "at_min_replicas"),
            "refusals_metered":
                monitor.metric_value("autoscaler_decisions_total", 0.0,
                                     action="refuse_scale_out",
                                     reason="at_max_replicas") >= 1,
            "calm_scale_in_via_drain": decided("scale_in", "calm"),
            "victim_drained_clean":
                drained_clean and last_exit.get("reason") == "drain"
                and last_exit.get("rc") == 0,
            "victim_ledger_exact":
                bool(victim_acct.get("exact"))
                and victim_acct.get("pending") == 0,
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal":
                seen["terminal"] == seen["submitted"],
            "no_untyped_errors": seen["other_error"] == 0,
            "nothing_admitted_lost":
                seen["replica_lost"] == 0 and seen["stopped"] == 0,
        }
        warmstart = {
            "cold": {k: cold.get(k) for k in
                     ("time_to_ready_s", "warm_up_s", "aot_cache",
                      "autotune")},
            "warm": ({k: warm.get(k) for k in
                      ("time_to_ready_s", "warm_up_s", "aot_cache",
                       "autotune")} if warm is not None else None),
            "ready_speedup": (cold["time_to_ready_s"]
                              / max(warm["time_to_ready_s"], 1e-9)
                              if warm is not None else None),
        }
        return {"name": name, "ok": all(checks.values()),
                "requests": seen["submitted"], "caller_view": seen,
                "router_accounting": acct,
                "victim_accounting": victim_acct,
                "tenants": per_tenant, "autotune_seed": seeded,
                "warmstart": warmstart,
                "innocent_latency": {"count": len(small_lat),
                                     "p99_s": p99},
                "autoscaler": {"audit": audit,
                               "last_decision": status["last_decision"],
                               "spawned": status["spawned"]},
                "checks": checks,
                "why": "hot-tenant SLO burn scales out warm (shared AOT "
                       "cache + autotune DB, zero re-trials), the hog is "
                       "shed typed tenant_quota while innocents hold, "
                       "calm scales back in via preemption-drain with "
                       "the fleet ledger exact, and every refusal is "
                       "typed + metered"}
    finally:
        stop_ev.set()
        for t in threads:
            t.join(10)
        if auto is not None:
            auto.stop()
        if sup is not None:
            sup.stop(drain=True)
        if router is not None:
            router.stop()
        shutil.rmtree(aot_dir, ignore_errors=True)
        shutil.rmtree(db_dir, ignore_errors=True)
        flags_mod._overrides.clear()
        flags_mod._overrides.update(saved_overrides)


def leg_autoscale_negative(name, ci, log_dir="."):
    """--autoscale --negative-control: NO autoscaler attached and tenant
    fair-share off. The same hog flood piles real queue pressure, but
    nothing answers it: the replica count stays pinned at one and the
    hog's sheds (if any) stay untyped-by-tenant — the control-loop
    checks must FAIL the gate."""
    from paddle_tpu.serving.fleet import (ReplicaSupervisor,
                                          SupervisorConfig)

    aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_autoscale_neg_aot_")
    router = sup = None
    stop_ev = threading.Event()
    threads = []
    try:
        router = _chaos_router(request_timeout_s=30.0)
        sup = ReplicaSupervisor(
            router, SupervisorConfig(ready_timeout_s=240.0,
                                     exit_grace_s=60.0),
            log_dir=log_dir, env=_replica_env(), cwd=_REPO_ROOT)
        sup.add_replica(
            "r0", "mlp_tiny", aot_dir,
            extra_args=_AUTOSCALE_REPLICA_ARGS + _AUTOSCALE_SLO_FLAGS)
        sup.handle("r0").wait_ready(240)
        router.start()
        assert _wait_routable(router, "r0")

        threads, seen, per_tenant, _lat = _drive_autoscale_burst(
            router, stop_ev)
        peak_queue = 0
        t_end = time.monotonic() + (4.0 if ci else 6.0)
        while time.monotonic() < t_end:
            router.poll_now()
            r = router.get_replica("r0")
            if r is not None:
                peak_queue = max(peak_queue,
                                 r.snapshot().get("queue_depth", 0))
            time.sleep(0.1)
        stop_ev.set()
        for t in threads:
            t.join(120)
        acct = router.accounting()
        seen["terminal"] = sum(v for k, v in seen.items()
                               if k not in ("submitted", "terminal"))
        hog = per_tenant.get("hog", {})
        checks = {
            # sanity (passes): the hot condition was genuinely present
            "hot_pressure_observed": peak_queue >= 4,
            "exact_fleet_accounting": bool(acct["exact"]),
            # the control-loop requirements (must FAIL):
            "scale_out_on_sustained_hot": len(sup.status()) > 1,
            "hot_tenant_shed_typed_tenant_quota":
                hog.get("shed_tenant_quota", 0) >= 1,
        }
        return {"name": name, "ok": all(checks.values()),
                "requests": seen["submitted"], "caller_view": seen,
                "router_accounting": acct, "tenants": per_tenant,
                "peak_queue_depth": peak_queue, "checks": checks,
                "why": "no autoscaler + no tenant quotas: sustained "
                       "pressure goes unanswered and the hot tenant is "
                       "never shed typed — the gate must FAIL"}
    finally:
        stop_ev.set()
        for t in threads:
            t.join(10)
        if sup is not None:
            sup.stop(drain=True)
        if router is not None:
            router.stop()
        shutil.rmtree(aot_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# fleet self-healing legs (--fleet-chaos): supervisor + bisection + wire
# chaos — ISSUE 15's gate. Three failure families against a 2-replica
# fleet: injected wire faults (drop + stall + corrupt), one poison
# request co-batched with innocents, and a crashed + a crash-looping
# replica under the supervisor.
# ---------------------------------------------------------------------------

_BISECT_FLAGS = ["--set-flag", "FLAGS_serving_bisect_depth=3",
                 "--set-flag", "FLAGS_check_nan_inf=1"]


def _chaos_router(request_timeout_s=2.0):
    from paddle_tpu.serving.fleet import FleetRouter, RouterConfig

    return FleetRouter([], RouterConfig(
        poll_interval_s=0.1, connect_timeout_s=3.0,
        request_timeout_s=request_timeout_s,
        breaker_threshold=2, breaker_cooldown_s=0.4))


def _chaos_supervisor(router, log_dir, restart=True, max_restarts=2):
    from paddle_tpu.serving.fleet import (ReplicaSupervisor,
                                          SupervisorConfig)

    cfg = SupervisorConfig(max_restarts=max_restarts,
                           restart_window_s=60.0, backoff_base_s=0.25,
                           backoff_max_s=1.0, ready_timeout_s=240.0,
                           exit_grace_s=30.0, restart=restart)
    return ReplicaSupervisor(router, cfg, log_dir=log_dir,
                             env=_replica_env(), cwd=_REPO_ROOT)


def _wait_routable(router, replica_id, timeout=90.0):
    """Wait until the router's snapshot marks one replica ok+ready (the
    'fresh capacity within one poll' observation point)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = router.get_replica(replica_id)
        if r is not None:
            snap = r.snapshot()
            if snap["ok"] and snap["ready"]:
                return True
        time.sleep(0.05)
    return False


def _wait_removed(router, replica_id, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if router.get_replica(replica_id) is None:
            return True
        time.sleep(0.05)
    return False


def _poison_feed(seed=999):
    f = _mlp_feed(rows=1, seed=seed)
    f["img"][0, :7] = np.nan
    return f


def _submit_concurrent(router, feeds, priority=1):
    """Submit each feed from its own thread (so the replica's batch
    window coalesces them) and classify every outcome."""
    from paddle_tpu.serving import (BatchFailed, CircuitOpen,
                                    DeadlineExceeded, EngineStopped,
                                    Overloaded, PoisonRequest)
    from paddle_tpu.serving.fleet import ReplicaLost

    results = [None] * len(feeds)
    outcomes = [None] * len(feeds)

    def one(i):
        try:
            results[i] = router.submit(feeds[i], priority=priority)
            outcomes[i] = "completed"
        except PoisonRequest:
            outcomes[i] = "poisoned"
        except Overloaded:
            outcomes[i] = "shed"
        except BatchFailed:
            outcomes[i] = "failed"
        except ReplicaLost:
            outcomes[i] = "replica_lost"
        except DeadlineExceeded:
            outcomes[i] = "deadline"
        except (CircuitOpen, EngineStopped):
            outcomes[i] = "rejected"
        except Exception:
            outcomes[i] = "other_error"

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(len(feeds))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    return results, outcomes


def leg_fleet_chaos_wire_poison(name, ci, log_dir=".", aot_dir=""):
    """Wire chaos + poison bisection against a supervised 2-replica
    fleet. r1 carries its OWN fault plan (its first two submit responses
    stall past the router's request timeout — the stalling-but-listening
    replica the per-replica breaker must eject); the router process
    injects a connect drop and a corrupt request payload (both
    unadmitted, absorbed by the sibling retry). Then one NaN poison
    request rides a batch with innocents: replica-side bisection must
    complete every innocent bit-exactly, settle the culprit typed
    PoisonRequest, and shed its resubmission from quarantine."""
    from paddle_tpu import monitor
    from paddle_tpu.resilience import fault_plan_guard
    from paddle_tpu.serving import Overloaded

    router = _chaos_router(request_timeout_s=2.0)
    sup = _chaos_supervisor(router, log_dir)
    base_args = ["--batch-window-s", "0.02", "--max-batch", "4",
                 "--queue-depth", "256"] + _BISECT_FLAGS
    try:
        sup.add_replica("r0", "mlp_tiny", aot_dir, extra_args=base_args)
        sup.add_replica(
            "r1", "mlp_tiny", aot_dir,
            extra_args=base_args + [
                "--set-flag", "FLAGS_fault_plan=wire_response:2:stall",
                "--set-flag", "FLAGS_fault_stall_s=8"])
        sup.handle("r0").wait_ready(240)
        sup.handle("r1").wait_ready(240)
        router.start()
        assert _wait_routable(router, "r0") and _wait_routable(router, "r1")

        # -- phase S: the stalling-but-listening replica ----------------
        # SEQUENTIAL submissions so the breaker ladder is deterministic:
        # r1's first two responses stall (its own fault plan) past the
        # router timeout — they are necessarily its first two recorded
        # transport outcomes, so two consecutive failures OPEN the
        # breaker before any r1 success could reset the count
        probe = {"completed": 0, "replica_lost": 0, "other": 0}
        from paddle_tpu.serving.fleet import ReplicaLost as _RL
        for i in range(12):
            try:
                router.submit(_mlp_feed(rows=1, seed=700 + i))
                probe["completed"] += 1
            except _RL:
                probe["replica_lost"] += 1
            except Exception:
                probe["other"] += 1
            if probe["replica_lost"] >= 2:
                break
        opened = monitor.metric_value("router_breaker_transitions_total",
                                      0.0, replica="r1", to="open")
        # cooldown + healthz half-open probe must READMIT r1
        r1 = router.get_replica("r1")
        deadline = time.time() + 15.0
        while r1.breaker.state != "closed" and time.time() < deadline:
            time.sleep(0.05)
        readmitted = r1.breaker.state == "closed"

        # -- phase W: burst under router-side wire faults ---------------
        n = 24 if ci else 72
        with fault_plan_guard("wire_connect:@9:drop,"
                              "wire_connect:@12:corrupt") as plan:
            seen = _drive_fleet(router, _mlp_feed, n_requests=n,
                                n_threads=4)
            wire_fired = list(plan.fired)

        # -- phase P: poison bisection through the fleet ----------------
        # all traffic onto r1: r0 drains away (also proves the breaker
        # re-admitted r1 after its cooldown probe). Three rounds of one
        # poison co-batched with one innocent: bisection re-dispatches
        # the innocent as a SOLO batch, so a solo clean resubmission is
        # the exact same executable + bucket — a true bit-exactness
        # baseline (cross-bucket XLA results differ in ULPs by design).
        sup.drain("r0")
        assert _wait_removed(router, "r0"), "drained r0 not deregistered"
        rounds = 3
        poison_outcomes, innocent_outcomes = [], []
        bit_exact = True
        for j in range(rounds):
            poison = _poison_feed(seed=990 + j)   # distinct fingerprints
            innocent = _mlp_feed(rows=1, seed=100 + j)
            results, outcomes = _submit_concurrent(router,
                                                   [poison, innocent])
            poison_outcomes.append(outcomes[0])
            innocent_outcomes.append(outcomes[1])
            if outcomes[1] == "completed":
                clean = router.submit(innocent)
                bit_exact = bit_exact and all(
                    np.array_equal(a, b)
                    for a, b in zip(clean, results[1]))
            else:
                bit_exact = False
        # quarantine: the round-0 poison feed again is shed at admission
        try:
            router.submit(_poison_feed(seed=990))
            quarantine_shed = False
        except Overloaded:
            quarantine_shed = True
        except Exception:
            quarantine_shed = False
        acct = router.accounting()
        sup.stop(drain=True)
        router.stop()
        victim = (sup.handle("r1").exit_info or {}).get("accounting", {})

        checks = {
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal": seen["terminal"] == seen["submitted"],
            "no_untyped_errors": seen["other_error"] == 0,
            # the two stalled responses were typed losses; everything
            # else in the probe completed on the healthy sibling
            "stalled_requests_typed_lost":
                probe["replica_lost"] == 2 and probe["other"] == 0,
            "stalling_replica_ejected": opened >= 1,
            "breaker_readmitted_via_healthz": readmitted,
            # with the stall plan exhausted and the breaker closed, the
            # burst completes 100% (drop/corrupt retried on the sibling)
            "wire_burst_completed":
                seen["completed"] == n and seen["replica_lost"] == 0,
            "unadmitted_wire_faults_retried": acct["retries"] >= 2,
            "wire_faults_audited":
                sum(1 for f in wire_fired if f[0] == "wire_connect") == 2,
            "poison_isolated_typed":
                all(o == "poisoned" for o in poison_outcomes),
            "innocents_complete":
                all(o == "completed" for o in innocent_outcomes),
            "innocents_bit_exact": bit_exact,
            "quarantine_sheds_repeat": quarantine_shed,
            "victim_ledger_exact": bool(victim.get("exact")),
            "victim_poisoned_per_round": victim.get("poisoned") == rounds,
            # bisection saved every innocent: the victim never failed a
            # whole batch
            "victim_zero_batch_failures": victim.get("failed") == 0,
        }
        return {"name": name, "ok": all(checks.values()), "requests": n,
                "caller_view": seen, "stall_probe": probe,
                "router_accounting": acct,
                "poison_outcomes": poison_outcomes,
                "innocent_outcomes": innocent_outcomes,
                "victim_accounting": victim,
                "wire_fired": [list(f) for f in wire_fired],
                "breaker_opens_r1": opened, "checks": checks,
                "why": "drop+stall+corrupt wire faults + one poison "
                       "request: typed outcomes for everything, "
                       "innocents bit-exact via bisection, stalling "
                       "replica ejected by the router breaker"}
    finally:
        sup.stop(drain=False)
        router.stop()


def leg_fleet_chaos_supervisor(name, ci, log_dir=".", aot_dir=""):
    """Supervisor self-healing: r1 is SIGKILLed mid-burst (no exit
    event — the 'kill' classification) and must be restarted within the
    backoff budget, re-registered under the same id on a NEW port, and
    serve again as the only ready replica. A third replica crash-loops
    on purpose and must be RETIRED with a typed ReplicaCrashLoop, never
    a silent restart spin."""
    from paddle_tpu import monitor
    from paddle_tpu.serving.fleet import ReplicaCrashLoop

    router = _chaos_router(request_timeout_s=10.0)
    sup = _chaos_supervisor(router, log_dir, max_restarts=2)
    base_args = ["--batch-window-s", "0.005", "--max-batch", "4",
                 "--queue-depth", "256"]
    try:
        sup.add_replica("r0", "mlp_tiny", aot_dir, extra_args=base_args)
        sup.add_replica("r1", "mlp_tiny", aot_dir, extra_args=base_args)
        sup.handle("r0").wait_ready(240)
        sup.handle("r1").wait_ready(240)
        router.start()
        assert _wait_routable(router, "r0") and _wait_routable(router, "r1")

        # -- phase K: SIGKILL r1 mid-burst, supervisor must heal --------
        n = 24 if ci else 72
        t_kill = [None]

        def killer():
            t_kill[0] = time.perf_counter()
            sup.kill("r1")

        seen = _drive_fleet(router, _mlp_feed, n_requests=n, n_threads=4,
                            kill_at=n // 3, kill_fn=killer)
        # wait for the ACTUAL restart (the pre-kill pressure snapshot is
        # stale for up to one poll — the supervisor's own state is the
        # ground truth), then for the router to see the new port ready
        h1 = sup.handle("r1")
        deadline = time.time() + 90.0
        while (h1.restarts < 1 or h1.state != "ready") \
                and time.time() < deadline:
            time.sleep(0.05)
        restarted = (h1.restarts == 1 and h1.state == "ready"
                     and _wait_routable(router, "r1", timeout=30.0))
        restart_s = (time.perf_counter() - t_kill[0]
                     if t_kill[0] is not None else None)
        # only the RESTARTED replica left: its service proves the router
        # treats same-id/new-port as fresh capacity
        sup.drain("r0")
        assert _wait_removed(router, "r0"), "drained r0 not deregistered"
        k = 6
        _, outcomes = _submit_concurrent(
            router, [_mlp_feed(rows=1, seed=500 + i) for i in range(k)])

        # -- phase L: forced crash loop must retire typed ---------------
        sup.add_replica("r2", "mlp_tiny", aot_dir,
                        extra_args=base_args + ["--crash-after-s", "0.4"])
        h2 = sup.handle("r2")
        retired = h2.wait_retired(240)
        try:
            sup.check()
            retired_typed = False
        except ReplicaCrashLoop:
            retired_typed = True
        # the fleet keeps serving through the whole crash loop
        _, outcomes2 = _submit_concurrent(
            router, [_mlp_feed(rows=1, seed=600 + i) for i in range(3)])
        acct = router.accounting()
        restarts_crash = monitor.metric_value(
            "supervisor_restarts_total", 0.0, reason="crash")
        restarts_kill = monitor.metric_value(
            "supervisor_restarts_total", 0.0, reason="kill")

        checks = {
            "exact_fleet_accounting": bool(acct["exact"]),
            "every_submit_terminal": seen["terminal"] == seen["submitted"],
            "no_untyped_errors": seen["other_error"] == 0,
            "nothing_admitted_lost_to_routing":
                seen["stopped"] == 0 and seen["failed"] == 0,
            "burst_progressed": seen["completed"] > 0,
            "kill_classified": (h1.last_exit or {}).get("reason") == "kill",
            "restarted_within_budget": restarted and h1.restarts == 1,
            "restarted_replica_serves":
                all(o == "completed" for o in outcomes),
            "restart_counted": restarts_kill >= 1,
            "crash_loop_retired": retired and h2.state == "retired",
            "crash_loop_typed": retired_typed
                and isinstance(h2.error, ReplicaCrashLoop),
            "crash_loop_restarts_bounded": h2.restarts == 2,
            "crash_restarts_counted": restarts_crash >= 2,
            "retired_deregistered": router.get_replica("r2") is None,
            "fleet_serves_through_crash_loop":
                all(o == "completed" for o in outcomes2),
        }
        return {"name": name, "ok": all(checks.values()), "requests": n,
                "caller_view": seen, "router_accounting": acct,
                "restart_elapsed_s": restart_s,
                "victim_status": h1.status(),
                "crashloop_status": h2.status(), "checks": checks,
                "why": "SIGKILLed replica restarted warm under the same "
                       "id within the backoff budget; forced crash loop "
                       "retired typed; fleet ledger exact throughout"}
    finally:
        sup.stop(drain=False)
        router.stop()


def leg_fleet_chaos_negative(name, ci, log_dir=".", aot_dir=""):
    """--fleet-chaos --negative-control: supervision (restarts) and
    bisection BOTH disabled. The poison request must fail its innocent
    batch mates, and the killed replica must stay dead — the gate's
    checks must provably FAIL."""
    router = _chaos_router(request_timeout_s=5.0)
    sup = _chaos_supervisor(router, log_dir, restart=False)
    # bisection off (default), nan checks on: the poison still kills
    # its batch — but now the whole batch dies with it
    base_args = ["--batch-window-s", "0.02", "--max-batch", "4",
                 "--queue-depth", "256",
                 "--set-flag", "FLAGS_check_nan_inf=1"]
    try:
        sup.add_replica("r0", "mlp_tiny", aot_dir, extra_args=base_args)
        sup.add_replica("r1", "mlp_tiny", aot_dir, extra_args=base_args)
        sup.handle("r0").wait_ready(240)
        sup.handle("r1").wait_ready(240)
        router.start()
        assert _wait_routable(router, "r0") and _wait_routable(router, "r1")

        # poison WITHOUT bisection: innocents die with the culprit
        sup.drain("r0")
        assert _wait_removed(router, "r0")
        feeds = [_poison_feed()] + [_mlp_feed(rows=1, seed=100 + i)
                                    for i in range(6)]
        _, outcomes = _submit_concurrent(router, feeds)

        # kill WITHOUT restart: the replica stays dead, the fleet is gone
        sup.kill("r1")
        time.sleep(2.0)
        restarted = _wait_routable(router, "r1", timeout=5.0)
        _, outcomes2 = _submit_concurrent(
            router, [_mlp_feed(rows=1, seed=500 + i) for i in range(4)])
        acct = router.accounting()

        checks = {
            "poison_isolated_typed": outcomes[0] == "poisoned",
            "innocents_complete":
                all(o == "completed" for o in outcomes[1:]),
            "restarted_within_budget": restarted,
            "restarted_replica_serves":
                all(o == "completed" for o in outcomes2),
        }
        return {"name": name, "ok": all(checks.values()),
                "requests": len(feeds), "caller_view": {},
                "poison_outcomes": outcomes,
                "post_kill_outcomes": outcomes2,
                "router_accounting": acct, "checks": checks,
                "why": "restarts + bisection disabled: innocents must "
                       "fail with the poison and the killed replica must "
                       "stay dead — the gate must FAIL"}
    finally:
        sup.stop(drain=False)
        router.stop()


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _witness_gate():
    """Runtime lock-witness verdict for the artifact and the gate:
    zero runtime lock-order cycles, and every observed edge between
    framework-named locks predicted by the static graph
    (paddle_tpu.analysis.concurrency). Returns (section, ok)."""
    from paddle_tpu.analysis.concurrency import analyze_package

    rep = monitor.witness_report()
    static_rep = analyze_package()
    static = static_rep.edge_set()
    known = set(static_rep.locks) | {n for e in static for n in e}
    runtime = sorted(monitor.witness_edges())
    # only framework-named locks participate in the subset check:
    # harness-local locks (this tool, test fixtures) are outside the
    # static scan and prove nothing about the framework
    framework = [e for e in runtime if e[0] in known and e[1] in known]
    extra = sorted(set(framework) - static)
    cycles = rep["cycles"]
    ok = rep["enabled"] and not cycles and not extra
    section = {
        "enabled": rep["enabled"],
        "locks": rep["locks"],
        "runtime_edges": [list(e) for e in runtime],
        "static_edges": sorted(list(e) for e in static),
        "edges_not_in_static_graph": [list(e) for e in extra],
        "runtime_cycles": cycles,
        "ok": ok,
    }
    return section, ok


def _print_witness(witness) -> None:
    locks = witness["locks"]
    tail = max((s["hold"]["p99"] or 0) for s in locks.values()) \
        if locks else 0.0
    print(f"lock witness: {len(locks)} locks, "
          f"{len(witness['runtime_edges'])} runtime edges "
          f"({len(witness['edges_not_in_static_graph'])} outside the "
          f"static graph), {len(witness['runtime_cycles'])} cycle(s), "
          f"worst hold p99 {tail * 1e3:.2f}ms")
    for e in witness["edges_not_in_static_graph"]:
        print(f"       UNPREDICTED edge: {e[0]} -> {e[1]}")
    for c in witness["runtime_cycles"]:
        print(f"       RUNTIME CYCLE: {' -> '.join(c)}")


def _merge_concurrency_json(path, witness) -> None:
    """Land the runtime section next to the static report so
    ci_concurrency_report.json carries both halves of the gate."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc["lock_witness"] = witness
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    print(f"lock_witness section merged into {path}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ci", action="store_true",
                    help="tiny probes + gate checks (the CI mode)")
    ap.add_argument("--check", action="store_true",
                    help="alias for --ci (sibling-tool convention)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the serving report artifact")
    ap.add_argument("--negative-control", action="store_true",
                    help="disable admission control; the gate must FAIL")
    ap.add_argument("--skip-bert", action="store_true",
                    help="resnet legs only (debugging)")
    ap.add_argument("--decode", action="store_true",
                    help="add the generative legs: a GPT-tiny multi-thread "
                         "generation burst (exact accounting, zero warm "
                         "recompiles, tokens/s + inter-token p50/p99 in "
                         "the artifact) and a chaos sub-leg that kills one "
                         "in-flight batch (affected streams settle typed)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the multi-PROCESS fleet gate instead: two "
                         "replica subprocesses behind the router, one "
                         "SIGTERMed mid-burst (drain honored, unadmitted "
                         "retry, exact fleet-wide accounting) plus the "
                         "cold-vs-warm AOT-cache startup measurement. "
                         "With --negative-control the router runs without "
                         "drain honoring/retry and the gate must FAIL")
    ap.add_argument("--fleet-chaos", action="store_true",
                    help="run the fleet SELF-HEALING gate: a supervised "
                         "2-replica fleet under injected wire faults "
                         "(drop + stall + corrupt), one poison request "
                         "isolated by batch bisection (innocents "
                         "bit-exact), a SIGKILLed replica restarted warm "
                         "within its backoff budget, and a forced crash "
                         "loop retired with a typed ReplicaCrashLoop. "
                         "With --negative-control the supervisor never "
                         "restarts and bisection is off — the gate must "
                         "FAIL")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the fleet CONTROL-LOOP gate: a supervised "
                         "replica + FleetAutoscaler under a hot-tenant "
                         "flood — sustained SLO burn scales out a second "
                         "replica warm (shared AOT cache + shared "
                         "autotune DB, zero re-trials), the hog is shed "
                         "typed tenant_quota while innocent tenants hold "
                         "their p99, calm scales back in strictly via "
                         "preemption-drain (ledger exact), and every "
                         "refusal is typed + metered. With "
                         "--negative-control there is no autoscaler and "
                         "no tenant quotas — the gate must FAIL")
    ap.add_argument("--log-dir", default=".",
                    help="where fleet replica stderr logs land")
    ap.add_argument("--lock-witness", action="store_true",
                    help="run with FLAGS_lock_witness=1: every named "
                         "framework lock is instrumented, and after the "
                         "legs the gate additionally requires zero "
                         "runtime lock-order cycles and every observed "
                         "edge to be predicted by the static graph "
                         "(paddle_tpu.analysis.concurrency)")
    ap.add_argument("--concurrency-json", metavar="PATH", default=None,
                    help="merge the runtime lock_witness section into "
                         "this existing lint_concurrency JSON artifact "
                         "(ci_concurrency_report.json)")
    args = ap.parse_args(argv)
    ci = args.ci or args.check

    if args.lock_witness:
        # before any engine/router/supervisor construction: the factories
        # read the flag once at lock-creation time
        fluid.set_flags({"FLAGS_lock_witness": 1})
        monitor.reset_witness()
    monitor.reset()
    legs = []
    t0 = time.time()
    if args.fleet_chaos:
        aot_dir = tempfile.mkdtemp(prefix="paddle_tpu_fleet_chaos_aot_")
        try:
            if args.negative_control:
                legs.append(leg_fleet_chaos_negative(
                    "fleet_chaos_no_healing", ci, args.log_dir, aot_dir))
            else:
                legs.append(leg_fleet_chaos_wire_poison(
                    "fleet_chaos_wire_poison", ci, args.log_dir, aot_dir))
                legs.append(leg_fleet_chaos_supervisor(
                    "fleet_chaos_supervisor", ci, args.log_dir, aot_dir))
        finally:
            shutil.rmtree(aot_dir, ignore_errors=True)
        gate_ok = all(l["ok"] for l in legs)
        witness = None
        if args.lock_witness:
            witness, w_ok = _witness_gate()
            if not args.negative_control:
                gate_ok = gate_ok and w_ok
        for l in legs:
            status = "ok" if l["ok"] else "MISS"
            view = ", ".join(f"{k}={v}" for k, v in
                             sorted(l.get("caller_view", {}).items()) if v)
            print(f"[{status}] {l['name']}: {l['requests']} requests"
                  + (f" -> {view}" if view else ""))
            for k, v in sorted(l.get("checks", {}).items()):
                if not v:
                    print(f"       FAILED check: {k}")
            if l.get("restart_elapsed_s") is not None:
                print(f"supervisor: kill -> routable again in "
                      f"{l['restart_elapsed_s']:.1f}s")
        if witness is not None:
            _print_witness(witness)
        print(f"serving gate ({time.time() - t0:.1f}s) -> "
              f"{'ok' if gate_ok else 'FAIL'}")
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump({
                    "legs": legs,
                    "lock_witness": witness,
                    "snapshot": monitor.snapshot(),
                    "check": {"status": "ok" if gate_ok else "fail",
                              "negative_control":
                                  bool(args.negative_control)},
                }, f, indent=2, default=str)
            print(f"fleet-chaos artifact written to {args.json}")
        if args.concurrency_json and witness is not None:
            _merge_concurrency_json(args.concurrency_json, witness)
        return 0 if gate_ok else 1
    if args.autoscale:
        if args.negative_control:
            legs.append(leg_autoscale_negative("autoscale_open_loop", ci,
                                               args.log_dir))
        else:
            legs.append(leg_autoscale("autoscale_control_loop", ci,
                                      args.log_dir))
        gate_ok = all(l["ok"] for l in legs)
        for l in legs:
            status = "ok" if l["ok"] else "MISS"
            print(f"[{status}] {l['name']}: {l['requests']} requests -> "
                  + ", ".join(f"{k}={v}" for k, v in
                              sorted(l["caller_view"].items()) if v))
            for k, v in sorted(l.get("checks", {}).items()):
                if not v:
                    print(f"       FAILED check: {k}")
            for tname in sorted(l.get("tenants", {})):
                tview = ", ".join(
                    f"{k}={v}" for k, v in
                    sorted(l["tenants"][tname].items()) if v)
                print(f"tenant {tname}: {tview}")
            ws = l.get("warmstart")
            if ws and ws.get("warm"):
                print(f"scale-out warm start: cold ready "
                      f"{ws['cold']['time_to_ready_s']:.2f}s -> warm "
                      f"{ws['warm']['time_to_ready_s']:.2f}s "
                      f"(speedup {ws['ready_speedup']:.1f}x), autotune "
                      f"hits={ws['warm']['autotune']['hits']} "
                      f"trials={ws['warm']['autotune']['trials']}, "
                      f"aot hits={ws['warm']['aot_cache']['hits']} "
                      f"misses={ws['warm']['aot_cache']['misses']}")
            for e in (l.get("autoscaler") or {}).get("audit", []):
                print(f"autoscaler: {e['action']} ({e['reason']}) "
                      f"x{e['count']} — {e['detail']}")
        print(f"serving gate ({time.time() - t0:.1f}s) -> "
              f"{'ok' if gate_ok else 'FAIL'}")
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump({
                    "legs": legs,
                    "autoscaler": next(
                        (l.get("autoscaler") for l in legs
                         if l.get("autoscaler")), None),
                    "warmstart": next((l.get("warmstart") for l in legs
                                       if l.get("warmstart")), None),
                    "snapshot": monitor.snapshot(),
                    "check": {"status": "ok" if gate_ok else "fail",
                              "negative_control":
                                  bool(args.negative_control)},
                }, f, indent=2, default=str)
            print(f"autoscale artifact written to {args.json}")
        return 0 if gate_ok else 1
    if args.fleet:
        if args.negative_control:
            legs.append(leg_fleet_negative("fleet_no_drain_honor", ci,
                                           args.log_dir))
        else:
            legs.append(leg_fleet("fleet_kill_one_replica", ci,
                                  args.log_dir))
            legs.append(leg_fleet_telemetry("fleet_telemetry_plane", ci,
                                            args.log_dir))
        gate_ok = all(l["ok"] for l in legs)
        for l in legs:
            status = "ok" if l["ok"] else "MISS"
            print(f"[{status}] {l['name']}: {l['requests']} requests -> "
                  + ", ".join(f"{k}={v}" for k, v in
                              sorted(l["caller_view"].items()) if v))
            for k, v in sorted(l.get("checks", {}).items()):
                if not v:
                    print(f"       FAILED check: {k}")
            ws = l.get("warmstart")
            if ws:
                print(f"warm start: cold ready "
                      f"{ws['cold']['time_to_ready_s']:.2f}s "
                      f"(warm_up {ws['cold']['warm_up_s']:.2f}s) -> warm "
                      f"{ws['warm']['time_to_ready_s']:.2f}s "
                      f"(warm_up {ws['warm']['warm_up_s']:.2f}s), "
                      f"speedup {ws['ready_speedup']:.1f}x ready / "
                      f"{ws['warm_up_speedup']:.1f}x warm-up")
            lat = l.get("latency")
            if isinstance(lat, dict) and lat.get("count"):
                print(f"fleet latency: count={lat['count']} "
                      f"p50={lat['p50'] * 1e3:.1f}ms "
                      f"p99={lat['p99'] * 1e3:.1f}ms")
            tele = l.get("telemetry")
            if tele:
                print(f"telemetry: scraped fleet "
                      f"count={tele['scraped_latency_count']} "
                      f"p50={(tele['fleet_p50_s'] or 0) * 1e3:.1f}ms "
                      f"p99={(tele['fleet_p99_s'] or 0) * 1e3:.1f}ms "
                      f"(router completed={tele['router_completed']}), "
                      f"tenants={sorted(tele['tenants'])}, "
                      f"corrupt scrapes={tele['corrupt_scrapes']}, "
                      f"exemplars resolved="
                      f"{len(tele['exemplar_resolved_trace_ids'])}")
                print("slo burn: " + " -> ".join(
                    f"{st}@{t:.1f}s" for t, st in tele["slo_timeline"]))
        print(f"serving gate ({time.time() - t0:.1f}s) -> "
              f"{'ok' if gate_ok else 'FAIL'}")
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump({
                    "legs": legs,
                    "warmstart": next((l.get("warmstart") for l in legs
                                       if l.get("warmstart")), None),
                    "telemetry": next((l.get("telemetry") for l in legs
                                       if l.get("telemetry")), None),
                    "snapshot": monitor.snapshot(),
                    "check": {"status": "ok" if gate_ok else "fail",
                              "negative_control":
                                  bool(args.negative_control)},
                }, f, indent=2, default=str)
            print(f"fleet artifact written to {args.json}")
        return 0 if gate_ok else 1
    if args.negative_control:
        # only the chaos leg matters: with shedding disabled the
        # overload_was_shed requirement must trip the gate
        legs.append(leg_chaos("chaos_resnet_no_shedding", _resnet_engine,
                              ci, shedding=False))
        if args.decode:
            # prefix cache OFF => hit counters must stay zero; spec OFF
            # => no acceptance histogram — both legs must MISS
            legs.append(leg_decode_prefix("decode_gpt_prefix_off", ci,
                                          enabled=False))
            legs.append(leg_decode_spec("decode_gpt_spec_off", ci,
                                        enabled=False))
    else:
        legs.append(leg_steady("steady_resnet", _resnet_engine, ci))
        if not args.skip_bert:
            legs.append(leg_steady("steady_bert", _bert_engine, ci))
        legs.append(leg_chaos("chaos_resnet", _resnet_engine, ci))
        if args.decode:
            legs.append(leg_decode("decode_gpt", ci))
            legs.append(leg_decode_chaos("decode_gpt_chaos", ci))
            legs.append(leg_decode_prefix("decode_gpt_prefix", ci))
            legs.append(leg_decode_spec("decode_gpt_spec", ci))

    latency = _latency_snapshot()
    gate_ok = all(l["ok"] for l in legs) and latency is not None \
        and latency["count"] > 0 and latency["p50"] is not None \
        and latency["p99"] is not None
    decode_report = prefix_report = spec_report = None
    if args.decode and not args.negative_control:
        decode_report = next((l["decode"] for l in legs
                              if l["name"] == "decode_gpt"), None)
        prefix_report = next((l.get("prefix") for l in legs
                              if l["name"] == "decode_gpt_prefix"), None)
        spec_report = next((l.get("spec") for l in legs
                            if l["name"] == "decode_gpt_spec"), None)
        gate_ok = gate_ok and decode_report is not None \
            and (decode_report.get("tokens_per_s") or 0) > 0 \
            and decode_report.get("intertoken_p99_ms") is not None
        # ISSUE 20 acceptance: prefix-hit-ratio + first-token p99 in the
        # artifact, bit-exact speculative decode at >= 1.5x tokens/s
        gate_ok = gate_ok and prefix_report is not None \
            and prefix_report["prefix_hit_ratio"] > 0 \
            and prefix_report["first_token_p99_ms"] is not None
        gate_ok = gate_ok and spec_report is not None \
            and spec_report["bit_exact"] \
            and spec_report["speedup"] >= 1.5

    for l in legs:
        status = "ok" if l["ok"] else "MISS"
        print(f"[{status}] {l['name']}: {l['requests']} requests -> "
              + ", ".join(f"{k}={v}" for k, v in
                          sorted(l["caller_view"].items()) if v))
        for k, v in sorted(l.get("checks", {}).items()):
            if not v:
                print(f"       FAILED check: {k}")
    if latency:
        print(f"latency: count={latency['count']} "
              f"p50={latency['p50'] * 1e3:.1f}ms "
              f"p99={latency['p99'] * 1e3:.1f}ms "
              f"max={latency['max'] * 1e3:.1f}ms")
    if decode_report:
        print(f"decode: tokens={decode_report['tokens_total']:.0f} "
              f"tokens/s={decode_report['tokens_per_s']:.1f} "
              f"intertoken p50={decode_report['intertoken_p50_ms']:.2f}ms "
              f"p99={decode_report['intertoken_p99_ms']:.2f}ms")
    if prefix_report:
        print(f"prefix: hit_ratio={prefix_report['prefix_hit_ratio']:.2f} "
              f"pages_reused={prefix_report['pages_reused']} "
              f"first-token cold="
              f"{prefix_report['cold_first_token_avg_ms']:.2f}ms warm="
              f"{prefix_report['warm_first_token_avg_ms']:.2f}ms "
              f"p99={prefix_report['first_token_p99_ms']:.2f}ms")
    if spec_report:
        print(f"speculative: bit_exact={spec_report['bit_exact']} "
              f"tokens/s {spec_report['tokens_per_s_plain']:.1f} -> "
              f"{spec_report['tokens_per_s_spec']:.1f} "
              f"({spec_report['speedup']:.2f}x), accepted/chunk avg="
              f"{spec_report['accepted_len_avg'] or 0:.2f}")
    print(f"serving gate ({time.time() - t0:.1f}s) -> "
          f"{'ok' if gate_ok else 'FAIL'}")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({
                "legs": legs,
                "latency_histogram": latency,
                "decode": decode_report,
                "decode_prefix": prefix_report,
                "decode_spec": spec_report,
                "snapshot": monitor.snapshot(),
                "check": {"status": "ok" if gate_ok else "fail",
                          "negative_control": bool(args.negative_control)},
            }, f, indent=2, default=str)
        print(f"serving artifact written to {args.json}")
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
