"""Round-5 measurement-integrity probe. Small, prints progress as it goes.

Protocol (perf_probe.py): data-dependent chain inside ONE jit (lax.scan),
host float() fetch as the sync point, RTT removed by differencing two chain
lengths. Everything here is sized to finish in minutes through the tunnel.
"""
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

V5E_PEAK = 197.0
RNG = np.random.RandomState(0)


def timed(f, iters=3):
    float(f())  # compile + warm
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(f())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def probe_matmul(n=4096, k_short=4, k_long=64):
    a = jax.device_put(RNG.randn(n, n).astype(np.float32)).astype(jnp.bfloat16)
    b = jax.device_put(RNG.randn(n, n).astype(np.float32)).astype(jnp.bfloat16)

    def make(k):
        @jax.jit
        def f():
            def body(x, _):
                return (x @ b) * (1.0 / n), None
            x, _ = jax.lax.scan(body, a, None, length=k)
            return x.astype(jnp.float32).sum()
        return f

    print(f"[{time.strftime('%H:%M:%S')}] compiling matmul k={k_short}...",
          flush=True)
    t_s = timed(make(k_short))
    print(f"[{time.strftime('%H:%M:%S')}] k={k_short}: {t_s*1e3:.1f} ms total",
          flush=True)
    t_l = timed(make(k_long))
    dt = (t_l - t_s) / (k_long - k_short)
    tf = 2 * n**3 / dt / 1e12
    print(f"matmul {n}^3 bf16: {dt*1e3:.3f} ms/iter, {tf:.1f} TF/s "
          f"({100*tf/V5E_PEAK:.0f}% peak); rtt~{t_s - k_short*dt:.3f}s",
          flush=True)
    return dt


def probe_rtt():
    x = jax.device_put(np.float32(1.0))
    f = jax.jit(lambda v: v + 1)
    float(f(x))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    print(f"dispatch+fetch RTT (tiny jit): min {min(ts)*1e3:.1f} ms, "
          f"median {sorted(ts)[2]*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    print("backend:", jax.default_backend(), jax.devices(), flush=True)
    probe_rtt()
    which = sys.argv[1] if len(sys.argv) > 1 else "matmul"
    if which == "matmul":
        probe_matmul()
