#!/usr/bin/env python
"""Auto-remat CI gate (the perf-round acceptance check, analysis/remat.py).

Static, hardware-free: the gate runs the Pass 6 chooser on a BERT-base-
shaped training program and gates on the MEMORY PLANNER's predicted peak —
the same score the executor's FLAGS_auto_recompute path uses to pick
checkpoint sets, so a regression here is a regression in exactly what the
runtime would do.

  python tools/remat_check.py --check [--json report.json]

Gates (exit 1 on any failure):
  1. positive   — FLAGS_auto_recompute-style transform on BERT-base
                  (bs=64) inserts recompute segments with no user-provided
                  checkpoints and drops the predicted peak >= 30%.
  2. budget     — re-running with FLAGS_remat_budget_mb set to the fitted
                  peak chooses a set whose predicted peak fits the budget.
  3. negative   — with FLAGS_auto_recompute=0 the executor hook returns
                  the SAME program object: zero segments inserted, peak
                  unchanged (the tripwire against the transform leaking
                  into un-flagged runs).
  4. bit-exact  — a small MLP trained 4 steps with and without
                  FLAGS_auto_recompute produces bit-identical losses (the
                  tests prove this at scale; the gate keeps a cheap
                  end-to-end witness in CI).

Methodology: docs/PERF_NOTES.md "Automatic rematerialisation"."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

MIN_PEAK_DROP = 0.30
BERT_BATCH = 64


def _gate(name, ok, detail, report):
    print(f"[{'ok' if ok else 'FAIL'}] {name}: {detail}")
    report["gates"].append({"name": name, "ok": bool(ok), "detail": detail})
    return ok


def check_bert(report) -> bool:
    import paddle_tpu.unique_name as un
    from paddle_tpu.analysis.remat import auto_recompute_program
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    t0 = time.time()
    with un.guard():
        model = build_bert_pretrain(BertConfig.base(), seq_len=128, amp=True)
    feeds = list(model["feeds"])
    fetches = [model["loss"].name]
    dec = auto_recompute_program(model["main"], feed_names=feeds,
                                 fetch_names=fetches, batch_size=BERT_BATCH)
    drop = 1.0 - dec.peak_after / max(dec.peak_before, 1)
    report["bert"] = dict(dec.to_dict(), seconds=round(time.time() - t0, 1))
    ok = _gate(
        "bert_peak_drop", dec.applied and dec.n_segments > 0
        and drop >= MIN_PEAK_DROP,
        f"applied={dec.applied} segments={dec.n_segments} "
        f"peak {dec.peak_before >> 20} MiB -> {dec.peak_after >> 20} MiB "
        f"(drop {drop:.1%}, need >= {MIN_PEAK_DROP:.0%})", report)

    # budget gate: ask for the peak the free search just achieved (+margin);
    # the chooser must return a set that fits it
    budget_mb = (dec.peak_after >> 20) + 256
    dec_b = auto_recompute_program(model["main"], feed_names=feeds,
                                   fetch_names=fetches,
                                   batch_size=BERT_BATCH,
                                   budget_mb=budget_mb)
    report["bert_budget"] = dict(dec_b.to_dict(), budget_mb=budget_mb)
    ok &= _gate(
        "bert_budget_respected",
        dec_b.applied and dec_b.peak_after <= budget_mb << 20,
        f"budget {budget_mb} MiB, fitted peak "
        f"{dec_b.peak_after >> 20} MiB, k={len(dec_b.checkpoints)}", report)
    return ok


def check_negative_and_bitexact(report) -> bool:
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un

    # activation-dominated on purpose (wide batch vs narrow weights) so
    # remat has something to win; the tiny-weights regime where the chooser
    # refuses is covered by tests/test_auto_remat.py instead
    width, depth, batch = 128, 8, 256

    def build():
        with un.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[width], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                h = x
                for _ in range(depth):
                    h = fluid.layers.fc(h, width, act="relu")
                pred = fluid.layers.fc(h, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, width).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}

    def train(auto: bool):
        main, startup, loss = build()
        main.random_seed = 11
        prev = fluid.get_flags(["FLAGS_auto_recompute"])
        fluid.set_flags({"FLAGS_auto_recompute": auto})
        try:
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            losses = []
            with fluid.scope_guard(scope):
                exe.run(startup)
                for _ in range(4):
                    (lv,) = exe.run(main, feed=feed,
                                    fetch_list=[loss.name])
                    losses.append(float(np.asarray(lv).reshape(-1)[0]))
            # the cache entry for MAIN (the startup program has its own)
            ran = next((p for k, p in exe._remat_cache.items()
                        if k[0][0] == main._serial), main)
            segs = sum(1 for op in ran.global_block.ops
                       if op.type == "recompute_segment")
            peak = ran.memory_plan(feed_names=["x", "y"],
                                   fetch_names=[loss.name],
                                   batch_size=batch).peak_bytes
            return losses, segs, peak, ran is main
        finally:
            fluid.set_flags(prev)

    base_losses, base_segs, base_peak, base_same = train(False)
    rc_losses, rc_segs, rc_peak, _ = train(True)
    report["negative_control"] = {
        "flag_off_segments": base_segs, "flag_off_peak_bytes": base_peak,
        "flag_off_program_untouched": base_same,
    }
    report["bit_exact"] = {"plain": base_losses, "remat": rc_losses,
                           "remat_segments": rc_segs}
    ok = _gate("negative_control",
               base_segs == 0 and base_same,
               f"FLAGS_auto_recompute=0: segments={base_segs}, program "
               f"untouched={base_same}, peak={base_peak}", report)
    ok &= _gate("bit_exact_training",
                rc_segs > 0 and base_losses == rc_losses,
                f"remat segments={rc_segs}, losses bit-identical="
                f"{base_losses == rc_losses}", report)
    ok &= _gate("remat_peak_improves", rc_peak < base_peak,
                f"predicted peak {base_peak} -> {rc_peak}", report)
    return ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="run the gates; exit 1 on failure (CI mode)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the machine-readable report (CI artifact)")
    args = ap.parse_args(argv)

    report = {"min_peak_drop": MIN_PEAK_DROP, "gates": []}
    ok = check_bert(report)
    ok &= check_negative_and_bitexact(report)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")
    if not ok:
        print("remat gate -> FAIL", file=sys.stderr)
        return 1
    print("remat gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
