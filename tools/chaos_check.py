#!/usr/bin/env python
"""Chaos CI gate for ``paddle_tpu.resilience`` (sibling of
tools/metrics_report.py, docs/RESILIENCE.md for the failure model).

Proves, end to end with REAL process kills, that restart-after-failure is a
working path and not an accident:

1. **baseline** — a short deterministic training loop runs uninterrupted in
   a subprocess; final step / loss / param digest are recorded.
2. **kill mid-checkpoint** — the same loop runs under
   ``FLAGS_fault_plan="ckpt_write:@2:kill"``: the process is killed
   (``os._exit(137)``) inside the SECOND checkpoint write, after the blobs
   hit disk but before manifest + atomic rename. The gate asserts the live
   checkpoint dir holds only verified checkpoints plus a torn TEMP dir —
   the crash-safe write can not tear a published checkpoint.
3. **torn promotion** — the torn temp dir is renamed to ``checkpoint_10``,
   simulating a pre-resilience (non-atomic) writer dying mid-write.
4. **resume under compile faults** — the worker restarts in the same dir
   under ``FLAGS_fault_plan="compile:2:RuntimeError"``. It must: skip the
   torn checkpoint_10 with a PT6xx diagnostic (reported, never loaded),
   resume from the last VERIFIED checkpoint, absorb both transient compile
   faults via retry/backoff, and finish with the exact final loss + param
   digest of the uninterrupted baseline.

Usage:
  python tools/chaos_check.py                 # run + print the phase table
  python tools/chaos_check.py --check --json ci_chaos_report.json
      CI gate: exit 1 unless every phase assertion holds.
  python tools/chaos_check.py --check --negative-control
      Kill + torn-promotion as above, but the resume runs with retries
      DISABLED (FLAGS_retry_max_attempts=1) under a persistent compile
      fault plan: resume must fail and the gate must FAIL (non-zero exit)
      — CI runs this once to prove the gate actually trips.
  python tools/chaos_check.py --check --multichip --json ci_chaos_dist_report.json
      Distributed leg (resilience.distributed, 8 virtual CPU devices,
      ZeRO-sharded Adam state, sharded format_version-2 checkpoints):
      1. baseline — uninterrupted dp=8 run, sharded checkpoints, final
         param digest recorded (cross-replica divergence sweep armed the
         whole way: an honest run must never trip it).
      2. kill INSIDE one shard's write of the 2nd checkpoint
         (``shard_write:@12:kill``) — the serial must stay unpublished
         (only the previous verified serial + a torn temp dir).
      3. resume in the same dir — recovers from the last verified serial
         and finishes bit-identical to the baseline.
      4. elastic restore — the final dp=8 sharded checkpoint is loaded by
         fresh workers on 4 virtual devices and on 1 device; loaded state
         must be byte-equal to the baseline digest (the full-gather
         equivalence).
      5. watchdog — an injected in-step hang under FLAGS_step_timeout_s
         must die as a diagnosed WatchdogTimeout within the deadline;
         negative control: the same hang with the watchdog DISABLED must
         still be hanging when the harness gives up waiting.
  python tools/chaos_check.py --check --elastic --json ci_chaos_elastic_report.json
      Elastic preemption-tolerance leg (resilience.elastic, contrib.
      Trainer wiring, 8 virtual CPU devices, ZeRO Adam + sharded
      checkpoints):
      1. victim — a dp=8 run takes an injected ``device_lost`` fault
         mid-run; it must AUTOMATICALLY rescale to dp=4 on the surviving
         devices, restore from the last verified sharded serial,
         fast-forward the data cursor and finish — consuming exactly the
         remaining batch sequence (no duplicates, no gaps, proven by the
         recorded batch-id trace), with the divergence sweep armed across
         the rescale and silent.
      2. baseline — an uninterrupted dp=4 run restored from a COPY of the
         same serial and fed the same post-resume data must reach a
         bit-identical final params digest.
      3. negative control — the same fault with FLAGS_elastic=0 must die
         with a typed DeviceLostError (no silent recovery).
      4. retry control — call_with_retry over a DeviceLostError must
         re-raise immediately (retry provably never absorbs a dead chip).
      5. upscale — with FLAGS_elastic_upscale_after_steps set and
         capacity returning, the run must rescale dp=4 -> dp=8 without a
         restore and still consume the exact batch sequence.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

TOTAL_STEPS = 30
CKPT_EVERY = 5
KILL_SERIAL = 2 * CKPT_EVERY       # the save the kill interrupts
RESUME_SERIAL = KILL_SERIAL - CKPT_EVERY  # last verified checkpoint


# ---------------------------------------------------------------------------
# worker: one deterministic training run (invoked as a subprocess so a
# fault-plan `kill` takes out a real process, not the gate)
# ---------------------------------------------------------------------------

def _batch(step: int):
    import numpy as np

    rng = np.random.RandomState(1234 + step)
    w = np.arange(1, 5, dtype=np.float32).reshape(4, 1) / 4.0
    x = rng.rand(8, 4).astype(np.float32)
    return {"x": x, "y": (x @ w).astype(np.float32)}


def run_worker(args) -> int:
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import monitor, resilience

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        main = fluid.default_main_program()
        startup = fluid.default_startup_program()

        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            meta, serial, skipped = resilience.load_latest_checkpoint(
                exe, args.ckpt_dir, main_program=main, scope=scope)
            start = int(meta.get("step", 0)) if meta else 0
            final_loss = None
            for step in range(start, args.total_steps):
                (lv,) = exe.run(main, feed=_batch(step), fetch_list=[loss])
                final_loss = float(np.asarray(lv).reshape(-1)[0])
                done = step + 1
                if done % args.ckpt_every == 0:
                    fluid.io.save_checkpoint(
                        exe, os.path.join(args.ckpt_dir,
                                          f"checkpoint_{done}"),
                        main, scope=scope, meta={"step": done})
    result = {
        "start_step": start,
        "resumed_from_serial": serial,
        "skipped_checkpoints": skipped,
        "final_step": args.total_steps,
        "final_loss": final_loss,
        "params_sha256": _digest_scope(scope),
        "retries": monitor.metric_value("resilience_retries_total",
                                        default=0.0, site="compile"),
        "giveups": monitor.metric_value("resilience_giveups_total",
                                        default=0.0, site="compile"),
        "fallbacks": len(skipped),
    }
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1)
    return 0


# ---------------------------------------------------------------------------
# multichip worker: dp=8 ZeRO training with sharded checkpoints
# ---------------------------------------------------------------------------

MC_STEPS = 20
MC_CKPT_EVERY = 5
MC_KILL_SHARD_HIT = 12            # shard 4 of the 2nd checkpoint (8/save)
MC_KILL_SERIAL = 2 * MC_CKPT_EVERY
MC_RESUME_SERIAL = MC_KILL_SERIAL - MC_CKPT_EVERY


def _mc_batch(step: int, dp: int = 8):
    import numpy as np

    rng = np.random.RandomState(4321 + step)
    x = rng.rand(2 * dp, 16).astype(np.float32)
    w = (np.arange(1, 17, dtype=np.float32).reshape(16, 1)) / 16.0
    return {"x": x, "y": (x @ w).astype(np.float32)}


def _mc_build():
    import paddle_tpu as fluid

    x = fluid.layers.data("x", shape=[16], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    h = fluid.layers.fc(x, 16)
    pred = fluid.layers.fc(h, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return loss


def _digest_scope(scope):
    import hashlib

    import numpy as np

    digest = hashlib.sha256()
    for name in sorted(scope.vars):
        digest.update(name.encode())
        digest.update(np.ascontiguousarray(
            np.asarray(scope.find_var(name))).tobytes())
    return digest.hexdigest()


def run_multichip_worker(args) -> int:
    """One deterministic dp=8 ZeRO training run with sharded checkpoints
    (+ the divergence sweep armed as a standing negative control)."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import resilience

    fluid.set_flags({"FLAGS_replica_check_interval": 5})
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _mc_build()
        main = fluid.default_main_program()
        startup = fluid.default_startup_program()
        bs = fluid.BuildStrategy()
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)
        mesh = prog._mesh
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            meta, serial, skipped = resilience.load_latest_checkpoint(
                exe, args.ckpt_dir, main_program=main, scope=scope)
            start = int(meta.get("step", 0)) if meta else 0
            final_loss = None
            for step in range(start, args.total_steps):
                (lv,) = exe.run(prog, feed=_mc_batch(step),
                                fetch_list=[loss])
                final_loss = float(np.asarray(lv).reshape(-1)[0])
                done = step + 1
                if done % args.ckpt_every == 0:
                    fluid.io.save_checkpoint(
                        exe, os.path.join(args.ckpt_dir,
                                          f"checkpoint_{done}"),
                        main, scope=scope, meta={"step": done}, mesh=mesh)
            result = {
                "start_step": start,
                "resumed_from_serial": serial,
                "skipped_checkpoints": skipped,
                "final_step": args.total_steps,
                "final_loss": final_loss,
                "params_sha256": _digest_scope(scope),
                "n_devices": len(mesh.devices.flat),
            }
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1)
    return 0


def run_verify_worker(args) -> int:
    """Elastic-restore verifier: a fresh process (possibly with a
    DIFFERENT device count) rebuilds the model, loads the newest verified
    checkpoint through the recovery walk — the sharded reassembly IS the
    full-gather restore — and digests the loaded state."""
    import paddle_tpu as fluid
    from paddle_tpu import resilience

    import jax

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        _mc_build()
        main = fluid.default_main_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            meta, serial, skipped = resilience.load_latest_checkpoint(
                exe, args.ckpt_dir, main_program=main, scope=scope)
            result = {
                "loaded": meta is not None,
                "serial": serial,
                "step": int(meta.get("step", -1)) if meta else None,
                "params_sha256": _digest_scope(scope),
                "n_devices": jax.device_count(),
            }
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1)
    return 0


# ---------------------------------------------------------------------------
# elastic worker: contrib.Trainer end-to-end (device loss -> rescale ->
# deterministic resume). The Trainer IS the wired recovery path, so the
# gate exercises exactly what production runs.
# ---------------------------------------------------------------------------

EL_STEPS = 12                 # batches in the single epoch
EL_CKPT_EVERY = 4             # trainer step_interval -> serials at 4, 8, 12
EL_KILL_HIT = 7               # device_lost on the 7th parallel dispatch
EL_RESUME_STEP = 4            # last verified serial before the loss
EL_ROWS = 16                  # global batch rows (divisible by dp=8 and 4)


def _el_batch(step: int):
    import numpy as np

    rng = np.random.RandomState(7000 + step)
    x = rng.rand(EL_ROWS, 16).astype(np.float32)
    w = (np.arange(1, 17, dtype=np.float32).reshape(16, 1)) / 16.0
    return x, (x @ w).astype(np.float32)


def run_elastic_worker(args) -> int:
    """One deterministic parallel Trainer run (dp = all visible devices,
    ZeRO Adam, sharded checkpoints, data cursor on). ``EL_SURVIVORS``
    (env, comma list) scripts what the device probe reports per call —
    the CPU-sim stand-in for the runtime's post-loss enumeration."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu import monitor

    def train_func():
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 16)
        pred = fluid.layers.fc(h, 1)
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    def reader():
        for i in range(args.total_steps):
            x, y = _el_batch(i)
            yield [(x[j], y[j]) for j in range(x.shape[0])]

    survivors = [int(s) for s in
                 os.environ.get("EL_SURVIVORS", "").split(",") if s]
    calls = {"n": 0}

    def devices_fn():
        k = survivors[min(calls["n"], len(survivors) - 1)]
        calls["n"] += 1
        return jax.devices()[:k]

    bs = fluid.BuildStrategy()
    bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    ckpt = fluid.contrib.CheckpointConfig(
        args.ckpt_dir, max_num_checkpoints=0,
        step_interval=args.ckpt_every, sharded=True)
    trainer = fluid.contrib.Trainer(
        train_func, lambda: fluid.optimizer.Adam(learning_rate=0.01),
        checkpoint_config=ckpt, parallel=True, build_strategy=bs)
    if survivors:
        trainer.elastic_devices_fn = devices_fn
    start_step = trainer._step
    # batch-id trace: EndStepEvent.step IS the batch index (one epoch,
    # batches are f(step)); the third field counts rescales so far, so
    # the gate can split the trace at the recovery point
    trace = []

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            trace.append([ev.epoch, ev.step,
                          len(trainer.elastic_events)])

    trainer.train(num_epochs=1, event_handler=handler, reader=reader,
                  feed_order=["x", "y"])
    result = {
        "start_step": start_step,
        "final_step": trainer._step,
        "trace": trace,
        "elastic_events": trainer.elastic_events,
        "params_sha256": _digest_scope(trainer.scope),
        "final_mesh": ({k: int(v) for k, v in
                        dict(trainer._train_mesh.shape).items()}
                       if trainer._train_mesh is not None else None),
        "fastforward_batches": monitor.metric_value(
            "elastic_data_fastforward_batches_total", default=0.0),
        "n_devices": jax.device_count(),
    }
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1)
    return 0


def _spawn_el(ckpt_dir: str, result: str, extra_env: dict,
              n_devices: int = 8, timeout=240):
    """Spawn an elastic worker; returns (rc, elapsed_s, stderr_tail)."""
    import time

    env = dict(os.environ)
    for leak in ("FLAGS_fault_plan", "FLAGS_fault_seed",
                 "FLAGS_retry_max_attempts", "FLAGS_retry_timeout",
                 "FLAGS_nan_inf_policy", "FLAGS_monitor",
                 "FLAGS_step_timeout_s", "FLAGS_replica_check_interval",
                 "FLAGS_watchdog_hard_exit", "FLAGS_elastic",
                 "FLAGS_elastic_max_rescales",
                 "FLAGS_elastic_upscale_after_steps", "EL_SURVIVORS",
                 "XLA_FLAGS"):
        env.pop(leak, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["FLAGS_retry_base_delay"] = "0.01"
    env.update(extra_env)
    cmd = [sys.executable, os.path.abspath(__file__), "--el-worker",
           "--ckpt-dir", ckpt_dir, "--result", result,
           "--total-steps", str(EL_STEPS),
           "--ckpt-every", str(EL_CKPT_EVERY)]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                              stderr=subprocess.PIPE)
        rc = proc.returncode
        err = (proc.stderr or b"").decode(errors="replace")
    except subprocess.TimeoutExpired as e:
        rc = None
        err = (e.stderr or b"").decode(errors="replace") if e.stderr else ""
    return rc, time.monotonic() - t0, err[-65536:]


def run_elastic_gate(args) -> int:
    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    checks = []
    report = {"mode": "elastic", "phases": {}}

    def check(name, ok, detail=""):
        checks.append((name, bool(ok), detail))
        print(f"  [{'ok' if ok else 'MISS'}] {name}"
              + (f": {detail}" if detail else ""))

    fault = f"device_lost:@{EL_KILL_HIT}:RuntimeError"
    remaining = list(range(EL_RESUME_STEP, EL_STEPS))

    def post_resume(res):
        return [s for _e, s, k in res["trace"] if k >= 1] if res else None

    # -- phase 1: victim — dp=8, injected device loss, must self-heal
    print(f"== phase 1: dp=8 victim (FLAGS_fault_plan={fault}, survivors "
          f"report 4 devices; divergence sweep armed across the rescale)")
    victim_dir = os.path.join(work, "victim_ckpts")
    rc, el1, err = _spawn_el(
        victim_dir, os.path.join(work, "victim.json"),
        {"FLAGS_fault_plan": fault, "EL_SURVIVORS": "4",
         "FLAGS_replica_check_interval": "3"})
    vic = _load(os.path.join(work, "victim.json"))
    report["phases"]["victim"] = {"rc": rc, "result": vic,
                                  "elapsed_s": el1}
    check("victim_completed", rc == 0 and vic
          and vic["final_step"] == EL_STEPS,
          f"rc={rc}" + (f" stderr: …{err[-200:]}" if rc else ""))
    ev = (vic or {}).get("elastic_events") or []
    check("victim_rescaled_8_to_4",
          len(ev) == 1 and ev[0]["old"] == "dp=8"
          and ev[0]["new"] == "dp=4" and ev[0]["direction"] == "down",
          f"events: {ev}")
    check("restored_from_last_verified_serial",
          ev and ev[0]["step"] == EL_RESUME_STEP
          and ev[0]["serial"] is not None,
          f"event: {ev[0] if ev else None}")
    check("rescale_logged_with_serial",
          "restored from checkpoint_" in err and "rescaled dp=8 -> dp=4"
          in err, "recovery is never silent")
    check("post_resume_batches_exact",
          vic is not None and post_resume(vic) == remaining,
          f"post-resume trace {post_resume(vic)} want {remaining} "
          f"(no duplicates, no gaps)")
    check("divergence_sweep_silent_across_rescale",
          rc == 0 and "ReplicaDivergenceError" not in err)
    check("final_mesh_is_dp4", vic and vic["final_mesh"] == {"dp": 4},
          f"final mesh: {vic and vic['final_mesh']}")

    # -- phase 2: uninterrupted dp=4 baseline from a COPY of the same
    # serial, fed the same post-resume data -> bit-identical digest
    print("== phase 2: uninterrupted dp=4 baseline from the same serial")
    base_dir = os.path.join(work, "baseline_ckpts")
    os.makedirs(base_dir, exist_ok=True)
    serial = ev[0]["serial"] if ev else 0
    src = os.path.join(victim_dir, f"checkpoint_{serial}")
    if os.path.isdir(src):
        shutil.copytree(src, os.path.join(base_dir,
                                          f"checkpoint_{serial}"))
    rc, _, err2 = _spawn_el(base_dir, os.path.join(work, "baseline.json"),
                            {}, n_devices=4)
    base = _load(os.path.join(work, "baseline.json"))
    report["phases"]["baseline"] = {"rc": rc, "result": base}
    check("baseline_resumed_at_cursor",
          rc == 0 and base and base["start_step"] == EL_RESUME_STEP
          and [s for _e, s, _k in base["trace"]] == remaining,
          f"rc={rc} start={base and base['start_step']}")
    check("final_params_digest_matches_dp4_baseline",
          vic and base
          and vic["params_sha256"] == base["params_sha256"],
          "rescaled resume == uninterrupted dp=4 run, bit for bit")

    # -- phase 3: negative control — FLAGS_elastic=0 must die typed
    print("== phase 3: negative control (FLAGS_elastic=0 -> typed death)")
    rc, _, err3 = _spawn_el(
        os.path.join(work, "neg_ckpts"), os.path.join(work, "neg.json"),
        {"FLAGS_fault_plan": fault, "EL_SURVIVORS": "4",
         "FLAGS_elastic": "0"})
    report["phases"]["negative"] = {"rc": rc,
                                    "stderr_tail": err3[-1500:]}
    check("elastic_disabled_dies", rc not in (0, None), f"rc={rc}")
    check("death_is_typed_DeviceLostError", "DeviceLostError" in err3,
          "typed error on stderr")
    check("no_silent_recovery_attempted", "rescaled" not in err3)

    # -- phase 4: retry must never absorb a DeviceLostError (in-process)
    print("== phase 4: retry-absorption control (in-process)")
    from paddle_tpu.resilience import elastic as _el
    from paddle_tpu.resilience.retry import call_with_retry
    attempts = {"n": 0}

    def dead_chip():
        attempts["n"] += 1
        raise _el.DeviceLostError("chip gone", site="parallel_step")

    typed = False
    try:
        call_with_retry("step", dead_chip)
    except _el.DeviceLostError:
        typed = True
    except Exception:
        pass
    check("retry_never_absorbs_device_loss",
          typed and attempts["n"] == 1,
          f"typed={typed} attempts={attempts['n']} (must be exactly 1)")
    report["phases"]["retry_control"] = {"typed": typed,
                                         "attempts": attempts["n"]}

    # -- phase 5: capacity returns — rescale back up, no restore
    print("== phase 5: upscale (survivors report 4 then 8, "
          "FLAGS_elastic_upscale_after_steps=2)")
    rc, _, err5 = _spawn_el(
        os.path.join(work, "up_ckpts"), os.path.join(work, "up.json"),
        {"FLAGS_fault_plan": fault, "EL_SURVIVORS": "4,8",
         "FLAGS_elastic_upscale_after_steps": "2"})
    up = _load(os.path.join(work, "up.json"))
    report["phases"]["upscale"] = {"rc": rc, "result": up}
    uev = (up or {}).get("elastic_events") or []
    check("upscale_completed", rc == 0 and up
          and up["final_step"] == EL_STEPS, f"rc={rc}")
    check("upscaled_4_to_8_when_capacity_returned",
          len(uev) == 2 and uev[1]["direction"] == "up"
          and uev[1]["old"] == "dp=4" and uev[1]["new"] == "dp=8",
          f"events: {uev}")
    check("upscale_kept_batch_sequence_exact",
          up is not None and post_resume(up) == remaining,
          f"post-resume trace {post_resume(up)}")

    ok = all(c[1] for c in checks)
    report["checks"] = [{"name": n, "ok": o, "detail": d}
                        for n, o, d in checks]
    report["status"] = "ok" if ok else "fail"
    print(f"chaos elastic gate: "
          f"{len([c for c in checks if c[1]])}/{len(checks)} checks -> "
          f"{'ok' if ok else 'FAIL'}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print(f"chaos elastic artifact written to {args.json}")
    if not args.keep_workdir and ok:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if (not args.check or ok) else 1


# ---------------------------------------------------------------------------
# parent: phase orchestration + gate
# ---------------------------------------------------------------------------

def _spawn(ckpt_dir: str, result: str, extra_env: dict) -> int:
    env = dict(os.environ)
    # resilience/monitor flags leaking in from the caller's environment
    # would corrupt the phase semantics (FLAGS_monitor=0 would zero the
    # retry counters the gate asserts on) — each phase sets exactly the
    # flags it needs
    for leak in ("FLAGS_fault_plan", "FLAGS_fault_seed",
                 "FLAGS_retry_max_attempts", "FLAGS_retry_timeout",
                 "FLAGS_nan_inf_policy", "FLAGS_monitor"):
        env.pop(leak, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["FLAGS_retry_base_delay"] = "0.01"
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--ckpt-dir", ckpt_dir, "--result", result,
         "--total-steps", str(TOTAL_STEPS),
         "--ckpt-every", str(CKPT_EVERY)],
        env=env, cwd=REPO)
    return proc.returncode


def _load(path: str):
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _spawn_mc(mode: str, ckpt_dir: str, result: str, extra_env: dict,
              n_devices: int = 8, timeout=None):
    """Spawn a multichip worker on ``n_devices`` virtual CPU devices.
    Returns (rc, elapsed_s, stderr_tail); rc is None when the subprocess
    outlived ``timeout`` and was killed (the hung-run detector)."""
    import time

    env = dict(os.environ)
    for leak in ("FLAGS_fault_plan", "FLAGS_fault_seed",
                 "FLAGS_retry_max_attempts", "FLAGS_retry_timeout",
                 "FLAGS_nan_inf_policy", "FLAGS_monitor",
                 "FLAGS_step_timeout_s", "FLAGS_replica_check_interval",
                 "FLAGS_watchdog_hard_exit", "XLA_FLAGS"):
        env.pop(leak, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["FLAGS_retry_base_delay"] = "0.01"
    env.update(extra_env)
    cmd = [sys.executable, os.path.abspath(__file__), f"--{mode}",
           "--ckpt-dir", ckpt_dir, "--result", result,
           "--total-steps", str(MC_STEPS),
           "--ckpt-every", str(MC_CKPT_EVERY)]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, env=env, cwd=REPO, timeout=timeout,
                              stderr=subprocess.PIPE)
        rc = proc.returncode
        err = (proc.stderr or b"").decode(errors="replace")
    except subprocess.TimeoutExpired as e:
        rc = None
        err = (e.stderr or b"").decode(errors="replace") \
            if e.stderr else ""
    # generous tail: the watchdog's whole-process stack dump runs to
    # kilobytes and must not push earlier markers (fault_plan HANG) out
    return rc, time.monotonic() - t0, err[-65536:]


def run_multichip_gate(args) -> int:
    from paddle_tpu import resilience

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    checks = []
    report = {"mode": "multichip", "phases": {}}

    def check(name, ok, detail=""):
        checks.append((name, bool(ok), detail))
        print(f"  [{'ok' if ok else 'MISS'}] {name}"
              + (f": {detail}" if detail else ""))

    # -- phase 1: uninterrupted dp=8 baseline (divergence sweep armed)
    print("== phase 1: uninterrupted dp=8 ZeRO baseline "
          "(sharded checkpoints, FLAGS_replica_check_interval=5)")
    rc, _, err = _spawn_mc("mc-worker", os.path.join(work, "base_ckpts"),
                           os.path.join(work, "baseline.json"), {},
                           timeout=240)
    base = _load(os.path.join(work, "baseline.json"))
    check("baseline_clean", rc == 0 and base
          and base["final_step"] == MC_STEPS,
          f"rc={rc}" + (f" stderr: …{err[-200:]}" if rc else ""))
    check("divergence_sweep_stayed_silent", rc == 0 and
          "ReplicaDivergenceError" not in err)
    report["phases"]["baseline"] = base

    # -- phase 2: kill INSIDE one shard's write of the 2nd checkpoint
    print(f"== phase 2: kill inside shard write #{MC_KILL_SHARD_HIT} "
          f"(checkpoint_{MC_KILL_SERIAL}, "
          f"FLAGS_fault_plan=shard_write:@{MC_KILL_SHARD_HIT}:kill)")
    ckpt_dir = os.path.join(work, "chaos_ckpts")
    rc, _, _ = _spawn_mc(
        "mc-worker", ckpt_dir, os.path.join(work, "victim.json"),
        {"FLAGS_fault_plan": f"shard_write:@{MC_KILL_SHARD_HIT}:kill"},
        timeout=240)
    check("victim_killed", rc == 137, f"rc={rc} (137 = injected kill)")
    serials = [s for s, _ in resilience.iter_serials(ckpt_dir)]
    check("kill_left_serial_unpublished",
          serials == [MC_RESUME_SERIAL],
          f"published serials after kill: {serials}")
    torn_tmp = sorted(glob.glob(
        os.path.join(ckpt_dir, f".checkpoint_{MC_KILL_SERIAL}.tmp.*")))
    check("torn_shard_write_is_temp_dir", len(torn_tmp) == 1,
          f"temp dirs: {[os.path.basename(t) for t in torn_tmp]}")
    report["phases"]["kill"] = {"rc": rc, "serials_after_kill": serials}

    # -- phase 3: resume in the same dir, finish bit-identical
    print("== phase 3: resume from the last verified sharded serial")
    rc, _, _ = _spawn_mc("mc-worker", ckpt_dir,
                         os.path.join(work, "resume.json"), {},
                         timeout=240)
    res = _load(os.path.join(work, "resume.json"))
    report["phases"]["resume"] = {"rc": rc, "result": res}
    check("resume_completed", rc == 0 and res
          and res["final_step"] == MC_STEPS, f"rc={rc}")
    if res:
        check("resumed_from_last_verified",
              res["resumed_from_serial"] == MC_RESUME_SERIAL,
              f"resumed from {res['resumed_from_serial']}, want "
              f"{MC_RESUME_SERIAL}")
    if base and res:
        check("final_params_bit_identical_to_baseline",
              res["params_sha256"] == base["params_sha256"])

    # -- phase 4: elastic restore of the final dp=8 checkpoint on 4 and 1
    # devices — byte-equal to the state the baseline saved (= full gather)
    print("== phase 4: elastic restore (dp=8 checkpoint -> 4 devices, "
          "1 device)")
    for n_dev in (4, 1):
        rc, _, _ = _spawn_mc(
            "mc-verify", os.path.join(work, "base_ckpts"),
            os.path.join(work, f"elastic_{n_dev}.json"), {},
            n_devices=n_dev, timeout=240)
        ver = _load(os.path.join(work, f"elastic_{n_dev}.json"))
        report["phases"][f"elastic_{n_dev}"] = ver
        check(f"elastic_restore_on_{n_dev}_devices",
              rc == 0 and ver and ver["loaded"]
              and ver["n_devices"] == n_dev
              and base and ver["params_sha256"] == base["params_sha256"],
              f"rc={rc}, digest match="
              f"{bool(base and ver and ver.get('params_sha256') == base['params_sha256'])}")

    # -- phase 5: watchdog — injected hang must die diagnosed, fast
    # generous deadline: the SAME flag also arms the compile sections, and
    # a cold dp=8 XLA CPU compile on a loaded CI host must not trip the
    # watchdog before the injected step hang gets its chance to fire
    wd_timeout = 20.0
    print(f"== phase 5: watchdog (hang:@3:hang under "
          f"FLAGS_step_timeout_s={wd_timeout:g})")
    rc, elapsed, err = _spawn_mc(
        "mc-worker", os.path.join(work, "wd_ckpts"),
        os.path.join(work, "wd.json"),
        {"FLAGS_fault_plan": "hang:@3:hang",
         "FLAGS_step_timeout_s": str(wd_timeout),
         "FLAGS_watchdog_hard_exit": "1"},
        timeout=180)
    report["phases"]["watchdog"] = {"rc": rc, "elapsed_s": elapsed,
                                    "stderr_tail": err[-1500:]}
    check("watchdog_converted_hang_to_failure",
          rc not in (0, None), f"rc={rc} after {elapsed:.1f}s")
    # the dump must name the STEP section and the fault must actually have
    # fired — a slow dp=8 compile tripping the deadline would otherwise
    # fake all three checks and void the step-hang coverage
    check("watchdog_diagnosis_dumped",
          "section 'parallel_step'" in err and "hung section" in err,
          "parallel_step dump present" if "hung section" in err
          else f"stderr tail: …{err[-200:]}")
    check("injected_hang_actually_fired",
          "HANG at site 'hang'" in err,
          "fault_plan hang marker in stderr")
    # the hang fires on step 3 — well after compile — so expiry must come
    # within the armed timeout plus scheduling slack, not a CI eternity
    check("watchdog_fired_within_deadline", elapsed < 120,
          f"{elapsed:.1f}s")

    # negative control: the SAME hang with the watchdog disabled must
    # still be hanging when the harness stops waiting
    print("== phase 5b: negative control (watchdog disabled -> the run "
          "must still be hanging at harness timeout)")
    rc, elapsed, _ = _spawn_mc(
        "mc-worker", os.path.join(work, "wd_neg_ckpts"),
        os.path.join(work, "wd_neg.json"),
        {"FLAGS_fault_plan": "hang:@3:hang",
         "FLAGS_step_timeout_s": "0"},
        timeout=45)
    check("hang_without_watchdog_never_finishes", rc is None,
          f"rc={rc} after {elapsed:.1f}s (None = killed by harness)")
    report["phases"]["watchdog_negative"] = {"rc": rc,
                                             "elapsed_s": elapsed}

    ok = all(c[1] for c in checks)
    report["checks"] = [{"name": n, "ok": o, "detail": d}
                        for n, o, d in checks]
    report["status"] = "ok" if ok else "fail"
    print(f"chaos multichip gate: "
          f"{len([c for c in checks if c[1]])}/{len(checks)} checks -> "
          f"{'ok' if ok else 'FAIL'}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print(f"chaos multichip artifact written to {args.json}")
    if not args.keep_workdir and ok:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if (not args.check or ok) else 1


def run_gate(args) -> int:
    from paddle_tpu import resilience

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    checks = []          # (name, ok, detail)
    report = {"mode": "negative-control" if args.negative_control
              else "chaos", "phases": {}}

    def check(name, ok, detail=""):
        checks.append((name, bool(ok), detail))
        print(f"  [{'ok' if ok else 'MISS'}] {name}"
              + (f": {detail}" if detail else ""))

    # -- phase 1: uninterrupted baseline (skipped in negative control:
    # the control only needs to prove the gate trips on a failed resume)
    base = None
    if not args.negative_control:
        print("== phase 1: uninterrupted baseline")
        rc = _spawn(os.path.join(work, "baseline_ckpts"),
                    os.path.join(work, "baseline.json"), {})
        base = _load(os.path.join(work, "baseline.json"))
        check("baseline_clean", rc == 0 and base
              and base["final_step"] == TOTAL_STEPS,
              f"rc={rc}")
        report["phases"]["baseline"] = base

    # -- phase 2: kill during the 2nd checkpoint write
    print(f"== phase 2: kill inside checkpoint_{KILL_SERIAL} write "
          f"(FLAGS_fault_plan=ckpt_write:@2:kill)")
    ckpt_dir = os.path.join(work, "chaos_ckpts")
    rc = _spawn(ckpt_dir, os.path.join(work, "victim.json"),
                {"FLAGS_fault_plan": "ckpt_write:@2:kill"})
    check("victim_killed", rc == 137, f"rc={rc} (137 = injected kill)")
    serials = [s for s, _ in resilience.iter_serials(ckpt_dir)]
    check("kill_left_only_verified_checkpoints",
          serials == [RESUME_SERIAL] and _verifies(
              resilience, ckpt_dir, RESUME_SERIAL),
          f"published serials after kill: {serials}")
    torn_tmp = sorted(glob.glob(
        os.path.join(ckpt_dir, f".checkpoint_{KILL_SERIAL}.tmp.*")))
    check("torn_write_is_temp_dir", len(torn_tmp) == 1,
          f"temp dirs: {[os.path.basename(t) for t in torn_tmp]}")
    report["phases"]["kill"] = {"rc": rc, "serials_after_kill": serials,
                                "torn_tmp": torn_tmp}

    # -- phase 3: promote the torn temp dir to a live serial (simulates a
    # pre-resilience non-atomic writer dying mid-write)
    if torn_tmp:
        os.rename(torn_tmp[0],
                  os.path.join(ckpt_dir, f"checkpoint_{KILL_SERIAL}"))
        print(f"== phase 3: torn temp promoted to checkpoint_{KILL_SERIAL}")

    # -- phase 4: resume
    if args.negative_control:
        print("== phase 4 (negative control): resume with retries DISABLED "
              "under a persistent compile fault")
        extra = {"FLAGS_fault_plan": "compile:99:RuntimeError",
                 "FLAGS_retry_max_attempts": "1"}
    else:
        print("== phase 4: resume under 2 transient compile faults "
              "(FLAGS_fault_plan=compile:2:RuntimeError)")
        extra = {"FLAGS_fault_plan": "compile:2:RuntimeError"}
    rc = _spawn(ckpt_dir, os.path.join(work, "resume.json"), extra)
    res = _load(os.path.join(work, "resume.json"))
    report["phases"]["resume"] = {"rc": rc, "result": res}
    check("resume_completed", rc == 0 and res
          and res["final_step"] == TOTAL_STEPS, f"rc={rc}")
    if res:
        check("resumed_from_last_verified",
              res["resumed_from_serial"] == RESUME_SERIAL,
              f"resumed from {res['resumed_from_serial']}, want "
              f"{RESUME_SERIAL}")
        torn_reports = [s for s in res["skipped_checkpoints"]
                        if s["serial"] == KILL_SERIAL]
        check("torn_checkpoint_reported_not_loaded",
              len(torn_reports) == 1 and str(
                  torn_reports[0]["code"]).startswith("PT6"),
              f"skipped: {res['skipped_checkpoints']}")
        if not args.negative_control:
            check("transient_faults_absorbed",
                  res["retries"] == 2 and res["giveups"] == 0,
                  f"retries={res['retries']} giveups={res['giveups']}")
    if base and res:
        dl = abs(res["final_loss"] - base["final_loss"])
        check("final_loss_matches_uninterrupted_run", dl < 1e-6,
              f"|Δloss|={dl:.3g} at step {TOTAL_STEPS}")
        check("final_params_bit_identical",
              res["params_sha256"] == base["params_sha256"])

    ok = all(c[1] for c in checks)
    report["checks"] = [{"name": n, "ok": o, "detail": d}
                        for n, o, d in checks]
    report["status"] = "ok" if ok else "fail"
    print(f"chaos gate: {len([c for c in checks if c[1]])}/{len(checks)} "
          f"checks -> {'ok' if ok else 'FAIL'}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print(f"chaos artifact written to {args.json}")
    if not args.keep_workdir and ok:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if (not args.check or ok) else 1


def _verifies(resilience, ckpt_dir: str, serial: int) -> bool:
    try:
        resilience.verify_checkpoint(
            os.path.join(ckpt_dir, f"checkpoint_{serial}"))
        return True
    except Exception:
        return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every phase assertion holds")
    ap.add_argument("--json", metavar="PATH",
                    help="write the chaos report artifact as JSON")
    ap.add_argument("--negative-control", action="store_true",
                    help="resume with retries disabled — the gate must "
                         "FAIL (proves the tripwire trips)")
    ap.add_argument("--multichip", action="store_true",
                    help="distributed leg: dp=8 ZeRO run with SHARDED "
                         "checkpoints — kill inside one shard write, "
                         "elastic 8->4->1 restore, watchdog-vs-hang "
                         "(resilience.distributed)")
    ap.add_argument("--elastic", action="store_true",
                    help="preemption-tolerance leg: injected device loss "
                         "at dp=8 must auto-rescale to dp=4, resume from "
                         "the last verified serial with an exact batch "
                         "trace and a digest equal to an uninterrupted "
                         "dp=4 baseline; FLAGS_elastic=0 must die typed "
                         "(resilience.elastic)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir for checkpoints/results "
                         "(default: .chaos_check / .chaos_check_dist)")
    ap.add_argument("--keep-workdir", action="store_true")
    # internal worker protocol
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--mc-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--mc-verify", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--el-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--result", help=argparse.SUPPRESS)
    ap.add_argument("--total-steps", type=int, default=TOTAL_STEPS,
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-every", type=int, default=CKPT_EVERY,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.workdir is None:
        args.workdir = os.path.join(
            REPO, ".chaos_check_elastic" if args.elastic
            else ".chaos_check_dist" if args.multichip
            else ".chaos_check")
    if args.worker:
        return run_worker(args)
    if args.mc_worker:
        return run_multichip_worker(args)
    if args.mc_verify:
        return run_verify_worker(args)
    if args.el_worker:
        return run_elastic_worker(args)
    if args.multichip:
        return run_multichip_gate(args)
    if args.elastic:
        return run_elastic_gate(args)
    return run_gate(args)


if __name__ == "__main__":
    sys.exit(main())
