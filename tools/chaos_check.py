#!/usr/bin/env python
"""Chaos CI gate for ``paddle_tpu.resilience`` (sibling of
tools/metrics_report.py, docs/RESILIENCE.md for the failure model).

Proves, end to end with REAL process kills, that restart-after-failure is a
working path and not an accident:

1. **baseline** — a short deterministic training loop runs uninterrupted in
   a subprocess; final step / loss / param digest are recorded.
2. **kill mid-checkpoint** — the same loop runs under
   ``FLAGS_fault_plan="ckpt_write:@2:kill"``: the process is killed
   (``os._exit(137)``) inside the SECOND checkpoint write, after the blobs
   hit disk but before manifest + atomic rename. The gate asserts the live
   checkpoint dir holds only verified checkpoints plus a torn TEMP dir —
   the crash-safe write can not tear a published checkpoint.
3. **torn promotion** — the torn temp dir is renamed to ``checkpoint_10``,
   simulating a pre-resilience (non-atomic) writer dying mid-write.
4. **resume under compile faults** — the worker restarts in the same dir
   under ``FLAGS_fault_plan="compile:2:RuntimeError"``. It must: skip the
   torn checkpoint_10 with a PT6xx diagnostic (reported, never loaded),
   resume from the last VERIFIED checkpoint, absorb both transient compile
   faults via retry/backoff, and finish with the exact final loss + param
   digest of the uninterrupted baseline.

Usage:
  python tools/chaos_check.py                 # run + print the phase table
  python tools/chaos_check.py --check --json ci_chaos_report.json
      CI gate: exit 1 unless every phase assertion holds.
  python tools/chaos_check.py --check --negative-control
      Kill + torn-promotion as above, but the resume runs with retries
      DISABLED (FLAGS_retry_max_attempts=1) under a persistent compile
      fault plan: resume must fail and the gate must FAIL (non-zero exit)
      — CI runs this once to prove the gate actually trips.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

TOTAL_STEPS = 30
CKPT_EVERY = 5
KILL_SERIAL = 2 * CKPT_EVERY       # the save the kill interrupts
RESUME_SERIAL = KILL_SERIAL - CKPT_EVERY  # last verified checkpoint


# ---------------------------------------------------------------------------
# worker: one deterministic training run (invoked as a subprocess so a
# fault-plan `kill` takes out a real process, not the gate)
# ---------------------------------------------------------------------------

def _batch(step: int):
    import numpy as np

    rng = np.random.RandomState(1234 + step)
    w = np.arange(1, 5, dtype=np.float32).reshape(4, 1) / 4.0
    x = rng.rand(8, 4).astype(np.float32)
    return {"x": x, "y": (x @ w).astype(np.float32)}


def run_worker(args) -> int:
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import monitor, resilience

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        main = fluid.default_main_program()
        startup = fluid.default_startup_program()

        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            meta, serial, skipped = resilience.load_latest_checkpoint(
                exe, args.ckpt_dir, main_program=main, scope=scope)
            start = int(meta.get("step", 0)) if meta else 0
            final_loss = None
            for step in range(start, args.total_steps):
                (lv,) = exe.run(main, feed=_batch(step), fetch_list=[loss])
                final_loss = float(np.asarray(lv).reshape(-1)[0])
                done = step + 1
                if done % args.ckpt_every == 0:
                    fluid.io.save_checkpoint(
                        exe, os.path.join(args.ckpt_dir,
                                          f"checkpoint_{done}"),
                        main, scope=scope, meta={"step": done})
            import hashlib

            digest = hashlib.sha256()
            for name in sorted(scope.vars):
                digest.update(name.encode())
                digest.update(np.ascontiguousarray(
                    np.asarray(scope.find_var(name))).tobytes())
    result = {
        "start_step": start,
        "resumed_from_serial": serial,
        "skipped_checkpoints": skipped,
        "final_step": args.total_steps,
        "final_loss": final_loss,
        "params_sha256": digest.hexdigest(),
        "retries": monitor.metric_value("resilience_retries_total",
                                        default=0.0, site="compile"),
        "giveups": monitor.metric_value("resilience_giveups_total",
                                        default=0.0, site="compile"),
        "fallbacks": len(skipped),
    }
    with open(args.result, "w") as f:
        json.dump(result, f, indent=1)
    return 0


# ---------------------------------------------------------------------------
# parent: phase orchestration + gate
# ---------------------------------------------------------------------------

def _spawn(ckpt_dir: str, result: str, extra_env: dict) -> int:
    env = dict(os.environ)
    # resilience/monitor flags leaking in from the caller's environment
    # would corrupt the phase semantics (FLAGS_monitor=0 would zero the
    # retry counters the gate asserts on) — each phase sets exactly the
    # flags it needs
    for leak in ("FLAGS_fault_plan", "FLAGS_fault_seed",
                 "FLAGS_retry_max_attempts", "FLAGS_retry_timeout",
                 "FLAGS_nan_inf_policy", "FLAGS_monitor"):
        env.pop(leak, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["FLAGS_retry_base_delay"] = "0.01"
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--ckpt-dir", ckpt_dir, "--result", result,
         "--total-steps", str(TOTAL_STEPS),
         "--ckpt-every", str(CKPT_EVERY)],
        env=env, cwd=REPO)
    return proc.returncode


def _load(path: str):
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def run_gate(args) -> int:
    from paddle_tpu import resilience

    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    checks = []          # (name, ok, detail)
    report = {"mode": "negative-control" if args.negative_control
              else "chaos", "phases": {}}

    def check(name, ok, detail=""):
        checks.append((name, bool(ok), detail))
        print(f"  [{'ok' if ok else 'MISS'}] {name}"
              + (f": {detail}" if detail else ""))

    # -- phase 1: uninterrupted baseline (skipped in negative control:
    # the control only needs to prove the gate trips on a failed resume)
    base = None
    if not args.negative_control:
        print("== phase 1: uninterrupted baseline")
        rc = _spawn(os.path.join(work, "baseline_ckpts"),
                    os.path.join(work, "baseline.json"), {})
        base = _load(os.path.join(work, "baseline.json"))
        check("baseline_clean", rc == 0 and base
              and base["final_step"] == TOTAL_STEPS,
              f"rc={rc}")
        report["phases"]["baseline"] = base

    # -- phase 2: kill during the 2nd checkpoint write
    print(f"== phase 2: kill inside checkpoint_{KILL_SERIAL} write "
          f"(FLAGS_fault_plan=ckpt_write:@2:kill)")
    ckpt_dir = os.path.join(work, "chaos_ckpts")
    rc = _spawn(ckpt_dir, os.path.join(work, "victim.json"),
                {"FLAGS_fault_plan": "ckpt_write:@2:kill"})
    check("victim_killed", rc == 137, f"rc={rc} (137 = injected kill)")
    serials = [s for s, _ in resilience.iter_serials(ckpt_dir)]
    check("kill_left_only_verified_checkpoints",
          serials == [RESUME_SERIAL] and _verifies(
              resilience, ckpt_dir, RESUME_SERIAL),
          f"published serials after kill: {serials}")
    torn_tmp = sorted(glob.glob(
        os.path.join(ckpt_dir, f".checkpoint_{KILL_SERIAL}.tmp.*")))
    check("torn_write_is_temp_dir", len(torn_tmp) == 1,
          f"temp dirs: {[os.path.basename(t) for t in torn_tmp]}")
    report["phases"]["kill"] = {"rc": rc, "serials_after_kill": serials,
                                "torn_tmp": torn_tmp}

    # -- phase 3: promote the torn temp dir to a live serial (simulates a
    # pre-resilience non-atomic writer dying mid-write)
    if torn_tmp:
        os.rename(torn_tmp[0],
                  os.path.join(ckpt_dir, f"checkpoint_{KILL_SERIAL}"))
        print(f"== phase 3: torn temp promoted to checkpoint_{KILL_SERIAL}")

    # -- phase 4: resume
    if args.negative_control:
        print("== phase 4 (negative control): resume with retries DISABLED "
              "under a persistent compile fault")
        extra = {"FLAGS_fault_plan": "compile:99:RuntimeError",
                 "FLAGS_retry_max_attempts": "1"}
    else:
        print("== phase 4: resume under 2 transient compile faults "
              "(FLAGS_fault_plan=compile:2:RuntimeError)")
        extra = {"FLAGS_fault_plan": "compile:2:RuntimeError"}
    rc = _spawn(ckpt_dir, os.path.join(work, "resume.json"), extra)
    res = _load(os.path.join(work, "resume.json"))
    report["phases"]["resume"] = {"rc": rc, "result": res}
    check("resume_completed", rc == 0 and res
          and res["final_step"] == TOTAL_STEPS, f"rc={rc}")
    if res:
        check("resumed_from_last_verified",
              res["resumed_from_serial"] == RESUME_SERIAL,
              f"resumed from {res['resumed_from_serial']}, want "
              f"{RESUME_SERIAL}")
        torn_reports = [s for s in res["skipped_checkpoints"]
                        if s["serial"] == KILL_SERIAL]
        check("torn_checkpoint_reported_not_loaded",
              len(torn_reports) == 1 and str(
                  torn_reports[0]["code"]).startswith("PT6"),
              f"skipped: {res['skipped_checkpoints']}")
        if not args.negative_control:
            check("transient_faults_absorbed",
                  res["retries"] == 2 and res["giveups"] == 0,
                  f"retries={res['retries']} giveups={res['giveups']}")
    if base and res:
        dl = abs(res["final_loss"] - base["final_loss"])
        check("final_loss_matches_uninterrupted_run", dl < 1e-6,
              f"|Δloss|={dl:.3g} at step {TOTAL_STEPS}")
        check("final_params_bit_identical",
              res["params_sha256"] == base["params_sha256"])

    ok = all(c[1] for c in checks)
    report["checks"] = [{"name": n, "ok": o, "detail": d}
                        for n, o, d in checks]
    report["status"] = "ok" if ok else "fail"
    print(f"chaos gate: {len([c for c in checks if c[1]])}/{len(checks)} "
          f"checks -> {'ok' if ok else 'FAIL'}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print(f"chaos artifact written to {args.json}")
    if not args.keep_workdir and ok:
        shutil.rmtree(work, ignore_errors=True)
    return 0 if (not args.check or ok) else 1


def _verifies(resilience, ckpt_dir: str, serial: int) -> bool:
    try:
        resilience.verify_checkpoint(
            os.path.join(ckpt_dir, f"checkpoint_{serial}"))
        return True
    except Exception:
        return False


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every phase assertion holds")
    ap.add_argument("--json", metavar="PATH",
                    help="write the chaos report artifact as JSON")
    ap.add_argument("--negative-control", action="store_true",
                    help="resume with retries disabled — the gate must "
                         "FAIL (proves the tripwire trips)")
    ap.add_argument("--workdir", default=os.path.join(
        REPO, ".chaos_check"), help="scratch dir for checkpoints/results")
    ap.add_argument("--keep-workdir", action="store_true")
    # internal worker protocol
    ap.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-dir", help=argparse.SUPPRESS)
    ap.add_argument("--result", help=argparse.SUPPRESS)
    ap.add_argument("--total-steps", type=int, default=TOTAL_STEPS,
                    help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-every", type=int, default=CKPT_EVERY,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.worker:
        return run_worker(args)
    return run_gate(args)


if __name__ == "__main__":
    sys.exit(main())
