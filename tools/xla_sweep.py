#!/usr/bin/env python
"""XLA compile-option sweep over chained-scan probes (the perf-round lever
VERDICT r5 names next to remat).

``FLAGS_xla_options`` reaches ``jax.jit(compiler_options=...)`` on every
executor path and is part of the compile-cache key, so flipping options
recompiles rather than silently reusing an executable. This tool turns that
knob into a search, TVM-style (PAPERS.md "Learning to Optimize Tensor
Programs": treat the compiler configuration as a tunable, measure, rank):
each candidate option set is timed with the repo's honest chained-scan
protocol (``Executor.run_chained`` differencing — docs/PERF_NOTES.md) on
short ResNet / BERT probes, and the ranked results land in a JSON artifact
whose best entry can be fed straight back via
``FLAGS_xla_options='<json>'``.

Usage:
  python tools/xla_sweep.py [--model resnet --model bert] [--json out.json]
  python tools/xla_sweep.py --ci --json ci_xla_sweep.json
      CI mode: tiny probes (MLP + BERT-tiny), short chains, backend-
      appropriate option sets; exits non-zero if the sweep could not rank
      (baseline failed or every option set errored out).
  python tools/xla_sweep.py --options-file my_sets.json
      Sweep user option sets (a JSON list of objects) instead of the
      built-ins.

Option sets that XLA rejects (unknown flag for the backend) are recorded as
failed trials, not fatal: the artifact shows exactly which sets are legal
on this backend. Methodology notes: docs/PERF_NOTES.md "XLA option
sweeps".

This tool is now the CLI of the PERSISTENT tuning loop
(``paddle_tpu.tuning`` — docs/PERF_NOTES.md "Persistent autotuner"): with
``FLAGS_autotune=measure`` every successful trial is also recorded into
the durable cost database (keyed by program content fingerprint, shape
bucket, backend), so the next process with ``FLAGS_autotune=use`` compiles
straight to the best-known options with zero re-trials. Without the flag
the behaviour is the original one-shot sweep."""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# candidate sets live in paddle_tpu.tuning now (the persistent loop and
# this CLI sweep the same space); re-exported here for script compat
from paddle_tpu.tuning import CPU_OPTION_SETS, TPU_OPTION_SETS  # noqa: E402


def _probe_mlp(width=256, depth=4, batch=64):
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = x
            for _ in range(depth):
                h = fluid.layers.fc(h, width, act="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, width).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}
    return main, startup, loss.name, feed


def _probe_resnet(ci: bool):
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.resnet import build_resnet

    depth, batch, hw = (18, 8, 64) if ci else (50, 128, 224)
    with un.guard():
        model = build_resnet(depth=depth, class_num=100 if ci else 1000,
                             image_shape=(3, hw, hw), amp=not ci)
    rng = np.random.RandomState(0)
    feed = {"img": rng.rand(batch, 3, hw, hw).astype(np.float32),
            "label": rng.randint(0, 100 if ci else 1000,
                                 (batch, 1)).astype(np.int64)}
    return model["main"], model["startup"], model["loss"].name, feed


def _probe_bert(ci: bool):
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    cfg = BertConfig.tiny() if ci else BertConfig.base()
    seq, batch = (32, 4) if ci else (512, 32)
    with un.guard():
        model = build_bert_pretrain(cfg, seq_len=seq, amp=not ci)
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "pos_ids": np.tile(np.arange(seq), (batch, 1)),
        "sent_ids": np.zeros((batch, seq)),
        "input_mask": np.ones((batch, seq), np.float32),
        "mask_label": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "next_sent_label": rng.randint(0, 2, (batch, 1)),
    }
    for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
              "next_sent_label"):
        feed[k] = feed[k].astype(np.int64)
    return model["main"], model["startup"], model["loss"].name, feed


PROBES = {"mlp": lambda ci: _probe_mlp(),
          "resnet": _probe_resnet,
          "bert": _probe_bert}


def time_one(main, startup, loss_name, feed, k_short, k_long, repeats):
    """Per-step seconds in a fresh executor/scope, timed through the one
    shared chained-differencing implementation (tuning.chained_step_seconds)."""
    import paddle_tpu as fluid
    from paddle_tpu import tuning

    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        return tuning.chained_step_seconds(
            exe, main, feed, [loss_name], scope,
            k_short=k_short, k_long=k_long, repeats=repeats)


def sweep(models, option_sets, ci: bool, k_short, k_long, repeats) -> dict:
    import jax

    import paddle_tpu as fluid

    from paddle_tpu import tuning

    persist = tuning.autotune_mode() == "measure"
    report = {"backend": jax.default_backend(),
              "protocol": "run_chained differencing: "
                          f"(T({k_long})-T({k_short}))/{k_long - k_short}, "
                          f"min over {repeats} repeats",
              "autotune_db": tuning.default_db_path() if persist else None,
              "models": {}}
    prev = fluid.get_flags(["FLAGS_xla_options"])
    # one shared DB handle, one durable write per model (record_trial
    # save=False memoizes in the handle; per-trial saves would pay a
    # flock + merge + fsync + atomic-rewrite cycle for every candidate)
    database = tuning.get_database() if persist else None
    try:
        for mname in models:
            main, startup, loss_name, feed = PROBES[mname](ci)
            trials = []
            for opts in option_sets:
                fluid.set_flags({"FLAGS_xla_options": json.dumps(opts)})
                label = json.dumps(opts, sort_keys=True)
                t0 = time.time()
                try:
                    # trial_guard: the executor must compile exactly these
                    # options — in measure mode it would otherwise fill
                    # unset knobs (gemm blocks, and the {} baseline's
                    # options) from the DB's best-known entry
                    with tuning.trial_guard():
                        per_step = time_one(main, startup, loss_name, feed,
                                            k_short, k_long, repeats)
                    trials.append({"options": opts, "status": "ok",
                                   "per_step_s": per_step,
                                   "sweep_s": round(time.time() - t0, 2)})
                    if persist:
                        # the durable loop: this measurement feeds the next
                        # process's compile path (FLAGS_autotune=use). A
                        # failed DB write degrades to a warning — the
                        # timing above succeeded, so the artifact keeps
                        # exactly one 'ok' row for this candidate
                        batch = max([1] + [np.asarray(v).shape[0]
                                           for v in feed.values()])
                        try:
                            tuning.record_trial(
                                main, batch, tuning.TunedConfig.make(opts),
                                per_step, db=database, save=False)
                        except Exception as e:
                            print(f"[{mname}] {label}: DB record failed "
                                  f"({type(e).__name__}: {e})", flush=True)
                    print(f"[{mname}] {label}: "
                          f"{per_step * 1e3:.3f} ms/step", flush=True)
                except Exception as e:
                    trials.append({"options": opts, "status": "error",
                                   "error": f"{type(e).__name__}: {e}"[:300]})
                    print(f"[{mname}] {label}: FAILED "
                          f"({type(e).__name__})", flush=True)
            ok = sorted((t for t in trials if t["status"] == "ok"),
                        key=lambda t: t["per_step_s"])
            base = next((t["per_step_s"] for t in trials
                         if t["status"] == "ok" and not t["options"]), None)
            for rank, t in enumerate(ok):
                t["rank"] = rank
                if base:
                    t["speedup_vs_default"] = round(
                        base / t["per_step_s"], 4)
            report["models"][mname] = {
                "trials": trials,
                "best_options": ok[0]["options"] if ok else None,
                "best_per_step_s": ok[0]["per_step_s"] if ok else None,
            }
            if database is not None:
                # one durable write per model: a crash mid-sweep keeps
                # every completed model's trials
                try:
                    database.save()
                except Exception as e:
                    print(f"[{mname}] DB save failed "
                          f"({type(e).__name__}: {e})", flush=True)
    finally:
        fluid.set_flags(prev)
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", action="append", choices=sorted(PROBES),
                    help="probe model(s); default: resnet + bert "
                         "(mlp + bert under --ci)")
    ap.add_argument("--ci", action="store_true",
                    help="tiny probes + short chains (the CI artifact run)")
    ap.add_argument("--options-file", metavar="PATH",
                    help="JSON list of option objects to sweep instead of "
                         "the built-ins")
    ap.add_argument("--json", metavar="PATH",
                    help="write the ranked report (the CI artifact)")
    ap.add_argument("--k-short", type=int, default=None)
    ap.add_argument("--k-long", type=int, default=None)
    ap.add_argument("--repeats", type=int, default=None)
    args = ap.parse_args(argv)

    import jax

    models = args.model or (["mlp", "bert"] if args.ci
                            else ["resnet", "bert"])
    if args.options_file:
        with open(args.options_file, "r", encoding="utf-8") as f:
            option_sets = json.load(f)
        if not isinstance(option_sets, list):
            print("--options-file must hold a JSON list of objects",
                  file=sys.stderr)
            return 2
    else:
        option_sets = (TPU_OPTION_SETS if jax.default_backend() == "tpu"
                       else CPU_OPTION_SETS)
    k_short = args.k_short or (2 if args.ci else 4)
    k_long = args.k_long or (6 if args.ci else 16)
    repeats = args.repeats or (1 if args.ci else 3)

    report = sweep(models, option_sets, args.ci, k_short, k_long, repeats)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")

    ranked = all(m["best_options"] is not None
                 for m in report["models"].values())
    for mname, m in report["models"].items():
        if m["best_per_step_s"]:
            print(f"{mname}: best {m['best_per_step_s'] * 1e3:.3f} ms/step "
                  f"with {json.dumps(m['best_options'])}")
    if not ranked:
        print("sweep failed to rank (no option set succeeded)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
