#!/usr/bin/env python
"""Peak-memory planner CLI (the reporting face of analysis/liveness.py).

Usage:
  python tools/mem_report.py
      Plan the test-book programs (mnist-mlp and seq2seq train, plus the
      lint_program.py --builtin suite): per program, print the estimated
      peak live bytes and the top-10 live-range hot spots with build sites.
  python tools/mem_report.py prog.json [prog2.json ...]
      Plan serialized programs (Program.to_json output).
  python tools/mem_report.py --check [--json report.json]
      CI gate: also run the liveness verifier pass (PT5xx) over every
      program and exit 1 on any *error*-severity PT5xx finding; --json
      writes the full machine-readable report (the CI artifact).

Options: --batch N (resolve -1 dims, default 64), --top K (hot spots).
Methodology note: docs/PERF_NOTES.md "Peak-memory planning".
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.analysis import Severity, verify_program  # noqa: E402


def _book_programs():
    """(name, program, feed_names, fetch_names) for the book models the
    test suite trains (tests/test_mnist_mlp.py, tests/test_seq2seq.py)."""
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.mlp import build_mnist_mlp
    from paddle_tpu.models.seq2seq import build_seq2seq_train

    out = []
    with un.guard():
        m = build_mnist_mlp()
        out.append(("mnist_mlp/main", m["main"], list(m["feeds"]),
                    [m["loss"].name, m["acc"].name]))
        out.append(("mnist_mlp/startup", m["startup"], [], []))
    with un.guard():
        s = build_seq2seq_train(src_vocab=50, tgt_vocab=50)
        out.append(("seq2seq/main", s["main"], list(s["feeds"]),
                    [s["loss"].name]))
        out.append(("seq2seq/startup", s["startup"], [], []))

    import tools.lint_program as lint

    for name, prog, fetches in lint._builtin_programs():
        feeds = [v.name for v in prog.global_block.vars.values()
                 if v.is_data]
        out.append((name, prog, feeds, fetches))
    return out


def _report_one(name, program, feed_names, fetch_names, batch, top,
                check: bool):
    plan = program.memory_plan(feed_names=feed_names,
                               fetch_names=fetch_names, batch_size=batch)
    entry = {"name": name, "feeds": list(feed_names),
             "fetches": list(fetch_names), "plan": plan.to_dict()}
    gate_errors = []
    if check:
        diags = verify_program(program, fetch_names=fetch_names,
                               passes=("liveness",))
        entry["diagnostics"] = [
            {"code": d.code, "severity": d.severity, "message": d.message,
             "block": d.block_idx, "op": d.op_idx, "op_type": d.op_type}
            for d in diags]
        gate_errors = [d for d in diags
                       if d.code.startswith("PT5")
                       and d.severity == Severity.ERROR]
    status = "FAIL" if gate_errors else "ok"
    print(f"[{status}] {name}")
    print("  " + plan.format(top).replace("\n", "\n  "))
    if check:
        n = len(entry["diagnostics"])
        print(f"  liveness findings: {n} "
              f"({len(gate_errors)} error-severity PT5xx)")
        for d in gate_errors:
            print(f"    {d}")
    return entry, not gate_errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("programs", nargs="*",
                    help="serialized Program JSON files (default: the "
                         "test-book programs)")
    ap.add_argument("--check", action="store_true",
                    help="run the PT5xx liveness pass; exit 1 on "
                         "error-severity findings (the CI gate)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report as JSON (CI artifact)")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch size substituted for -1 dims (default 64)")
    ap.add_argument("--top", type=int, default=10,
                    help="hot spots to print per program (default 10)")
    args = ap.parse_args(argv)

    targets = []
    if args.programs:
        for path in args.programs:
            with open(path, "r", encoding="utf-8") as f:
                prog = fluid.Program.from_json(f.read())
            feeds = [v.name for v in prog.global_block.vars.values()
                     if v.is_data]
            targets.append((path, prog, feeds, []))
    else:
        targets = _book_programs()

    ok = True
    report = {"batch_size": args.batch, "programs": []}
    for name, prog, feeds, fetches in targets:
        entry, good = _report_one(name, prog, feeds, fetches, args.batch,
                                  args.top, args.check)
        report["programs"].append(entry)
        ok = ok and good
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
