#!/usr/bin/env python
"""Peak-memory planner CLI (the reporting face of analysis/liveness.py +
analysis/sharding_check.py).

Usage:
  python tools/mem_report.py
      Plan the test-book programs (mnist-mlp and seq2seq train, plus the
      lint_program.py --builtin suite): per program, print the estimated
      peak live bytes and the top-10 live-range hot spots with build sites.
  python tools/mem_report.py prog.json [prog2.json ...]
      Plan serialized programs (Program.to_json output).
  python tools/mem_report.py --check [--json report.json]
      CI gate: also run the liveness verifier pass (PT5xx) over every
      program and exit 1 on any *error*-severity PT5xx finding; --json
      writes the full machine-readable report (the CI artifact).
  python tools/mem_report.py --mesh dp=8 --specs zero1
      PER-CHIP mode: plan every program under the mesh + layout
      (analysis.sharding_check spec propagation; layouts from
      parallel.sharding.extract_param_specs — "zero1" applies the
      BuildStrategy.ReduceStrategy.Reduce optimizer-state sharding,
      "allreduce" replicates state, or pass a JSON file of
      name -> [axis|null, ...] specs). Each JSON entry gains a
      "per_chip" section: the per-chip plan, the collective wire volumes
      and the predicted comms-vs-compute ratio.
  ... --mesh dp=8 --check --hbm-budget-mb 15872
      Per-chip budget gate: FAIL any program whose per-chip peak exceeds
      the budget (default: off).
  ... --mesh dp=8 --specs zero1 --check --validate-live
      Multichip dryrun gate: train one dp-sharded zoo model (mnist-mlp +
      Adam under ZeRO-1) LIVE on the current device set, measure the
      state bytes actually resident per chip from the jax shardings, and
      FAIL unless the static per-chip estimate matches within
      --tolerance (default 0.1). Requires >= mesh devices
      (CI runs it under XLA_FLAGS=--xla_force_host_platform_device_count=8).

Options: --batch N (resolve -1 dims, default 64), --top K (hot spots).
Methodology note: docs/PERF_NOTES.md "Peak-memory planning" and
"Per-chip memory under a sharding assignment".
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.analysis import Severity, verify_program  # noqa: E402


def _book_programs():
    """(name, program, feed_names, fetch_names) for the book models the
    test suite trains (tests/test_mnist_mlp.py, tests/test_seq2seq.py)."""
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.mlp import build_mnist_mlp
    from paddle_tpu.models.seq2seq import build_seq2seq_train

    out = []
    with un.guard():
        m = build_mnist_mlp()
        out.append(("mnist_mlp/main", m["main"], list(m["feeds"]),
                    [m["loss"].name, m["acc"].name]))
        out.append(("mnist_mlp/startup", m["startup"], [], []))
    with un.guard():
        s = build_seq2seq_train(src_vocab=50, tgt_vocab=50)
        out.append(("seq2seq/main", s["main"], list(s["feeds"]),
                    [s["loss"].name]))
        out.append(("seq2seq/startup", s["startup"], [], []))

    import tools.lint_program as lint

    for name, prog, fetches in lint._builtin_programs():
        feeds = [v.name for v in prog.global_block.vars.values()
                 if v.is_data]
        out.append((name, prog, feeds, fetches))
    return out


def _parse_mesh(s):
    """'dp=8,tp=2' -> {'dp': 8, 'tp': 2}"""
    mesh = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        mesh[k.strip()] = int(v)
    if not mesh:
        raise ValueError(f"empty mesh spec {s!r}")
    return mesh


def _specs_for(program, mesh, specs_mode):
    """Resolve --specs for one program: a layout name or a JSON file.
    Anything else is an ERROR — a typo'd spec file silently degrading to
    the replicated layout would make the gate validate the wrong thing."""
    from paddle_tpu.parallel.sharding import extract_param_specs

    mode = (specs_mode or "allreduce").lower()
    if mode not in ("zero1", "allreduce"):
        if not os.path.exists(specs_mode):
            raise SystemExit(
                f"--specs {specs_mode!r} is neither 'zero1', 'allreduce' "
                f"nor an existing JSON spec file")
        with open(specs_mode, "r", encoding="utf-8") as f:
            raw = json.load(f)
        return {k: tuple(v) for k, v in raw.items()}
    specs, _feed = extract_param_specs(program, mesh, zero=mode == "zero1")
    return specs


def _per_chip_entry(program, feeds, fetches, batch, mesh, specs_mode):
    """The per-chip section of one program's JSON entry."""
    from paddle_tpu.analysis.cost_model import (comms_compute_ratio,
                                                estimate_comms,
                                                estimate_cost)

    specs = _specs_for(program, mesh, specs_mode)
    plan = program.memory_plan(feed_names=feeds, fetch_names=fetches,
                               batch_size=batch, mesh=mesh, specs=specs)
    analysis = plan.sharding
    comms = estimate_comms(analysis)
    cost = estimate_cost(program, batch_size=batch)
    section = {
        "mesh": dict(analysis.mesh),
        "specs_mode": specs_mode or "allreduce",
        "plan": plan.to_dict(),
        "sharding": analysis.to_dict(),
        "comms": comms.to_dict(),
        "comms_compute_ratio": round(
            comms_compute_ratio(comms, cost), 4),
    }
    return plan, section


def _static_state_bytes_per_chip(program, analysis, batch):
    """Static per-chip bytes of the persistable state under the analysis'
    propagated specs — the quantity the live validation measures."""
    from paddle_tpu.analysis.liveness import _var_bytes
    from paddle_tpu.analysis.sharding_check import spec_divisor

    total = 0
    seen = set()
    for blk in program.blocks:
        for v in blk.vars.values():
            if not v.persistable or v.is_data or v.name in seen:
                continue
            seen.add(v.name)
            nbytes = _var_bytes(v, batch)[0]
            spec = analysis.var_specs.get(v.name, ())
            total += nbytes // spec_divisor(spec, analysis.mesh, v.shape,
                                            batch)
    return total


def validate_live(mesh, specs_mode, batch, tolerance):
    """Train one dp-sharded zoo model live under ZeRO-1 and compare the
    measured per-chip resident state bytes against the static estimate.
    Returns the JSON section; raises RuntimeError on mismatch."""
    import jax
    import numpy as np

    import paddle_tpu.unique_name as un
    from paddle_tpu.models.mlp import build_mnist_mlp

    n_mesh = 1
    for v in mesh.values():
        n_mesh *= v
    if jax.device_count() < n_mesh:
        raise RuntimeError(
            f"--validate-live needs {n_mesh} devices, have "
            f"{jax.device_count()} (run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_mesh})")

    with un.guard():
        m = build_mnist_mlp(optimizer="adam")
    prog, startup = m["main"], m["startup"]
    feeds = list(m["feeds"])
    fetches = [m["loss"].name]

    specs = _specs_for(prog, mesh, specs_mode)
    plan = prog.memory_plan(feed_names=feeds, fetch_names=fetches,
                            batch_size=batch, mesh=mesh, specs=specs)
    static_bytes = _static_state_bytes_per_chip(prog, plan.sharding, batch)

    bs = fluid.BuildStrategy()
    if (specs_mode or "").lower() == "zero1":
        bs.reduce_strategy = fluid.BuildStrategy.ReduceStrategy.Reduce
    compiled = fluid.CompiledProgram(prog).with_data_parallel(
        loss_name=m["loss"].name, build_strategy=bs)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(7)
    with fluid.scope_guard(scope):
        exe.run(startup)
        xb = rng.rand(batch, 784).astype(np.float32)
        yb = rng.randint(0, 10, (batch, 1)).astype(np.int64)
        exe.run(compiled, feed={"img": xb, "label": yb},
                fetch_list=fetches)
        # measured: bytes of each persistable's shards RESIDENT on chip 0
        dev0 = jax.devices()[0]
        measured = 0
        per_var = {}
        persistable = {v.name for blk in prog.blocks
                       for v in blk.vars.values()
                       if v.persistable and not v.is_data}
        for name in sorted(persistable):
            v = scope.find_var(name)
            if v is None:
                continue
            if isinstance(v, jax.Array):
                nbytes = sum(int(s.data.nbytes)
                             for s in v.addressable_shards
                             if s.device == dev0)
            else:
                nbytes = int(np.asarray(v).nbytes)
            measured += nbytes
            per_var[name] = nbytes
    rel = abs(measured - static_bytes) / max(measured, 1)
    section = {
        "model": "mnist_mlp/adam",
        "mesh": dict(mesh),
        "specs_mode": specs_mode or "allreduce",
        "batch": batch,
        "static_state_bytes_per_chip": static_bytes,
        "measured_state_bytes_per_chip": measured,
        # per-var measured bytes so a tolerance failure names the var
        # whose layout drifted without re-instrumenting
        "measured_per_var": per_var,
        "relative_error": round(rel, 5),
        "tolerance": tolerance,
        "ok": rel <= tolerance,
    }
    status = "ok" if section["ok"] else "FAIL"
    print(f"[{status}] live validation ({section['model']}, mesh "
          f"{mesh}, {specs_mode or 'allreduce'}): static "
          f"{static_bytes} B/chip vs measured {measured} B/chip "
          f"(rel err {rel:.2%}, tolerance {tolerance:.0%})")
    return section


def _report_one(name, program, feed_names, fetch_names, batch, top,
                check: bool, mesh=None, specs_mode=None,
                hbm_budget_mb: float = 0.0):
    plan = program.memory_plan(feed_names=feed_names,
                               fetch_names=fetch_names, batch_size=batch)
    entry = {"name": name, "feeds": list(feed_names),
             "fetches": list(fetch_names), "plan": plan.to_dict()}
    gate_errors = []
    budget_fail = None
    chip_plan = None
    if mesh:
        chip_plan, section = _per_chip_entry(
            program, feed_names, fetch_names, batch, mesh, specs_mode)
        entry["per_chip"] = section
        if check and hbm_budget_mb > 0 \
                and chip_plan.peak_bytes > hbm_budget_mb * 2**20:
            budget_fail = (f"per-chip peak "
                           f"{chip_plan.peak_bytes / 2**20:.1f} MiB "
                           f"exceeds --hbm-budget-mb {hbm_budget_mb:g}")
            entry["budget_fail"] = budget_fail
    if check:
        diags = verify_program(program, fetch_names=fetch_names,
                               passes=("liveness",))
        entry["diagnostics"] = [
            {"code": d.code, "severity": d.severity, "message": d.message,
             "block": d.block_idx, "op": d.op_idx, "op_type": d.op_type}
            for d in diags]
        gate_errors = [d for d in diags
                       if d.code.startswith("PT5")
                       and d.severity == Severity.ERROR]
    status = "FAIL" if (gate_errors or budget_fail) else "ok"
    print(f"[{status}] {name}")
    print("  " + plan.format(top).replace("\n", "\n  "))
    if chip_plan is not None:
        print("  " + chip_plan.format(top).replace("\n", "\n  "))
        comms = entry["per_chip"]["comms"]
        print(f"  collectives: {comms['gbytes_per_step'] * 1000:.3f} "
              f"MB/chip/step on the wire, predicted comms/compute "
              f"{entry['per_chip']['comms_compute_ratio']:.3f}")
    if budget_fail:
        print(f"    {budget_fail}")
    if check:
        n = len(entry["diagnostics"])
        print(f"  liveness findings: {n} "
              f"({len(gate_errors)} error-severity PT5xx)")
        for d in gate_errors:
            print(f"    {d}")
    return entry, not (gate_errors or budget_fail)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("programs", nargs="*",
                    help="serialized Program JSON files (default: the "
                         "test-book programs)")
    ap.add_argument("--check", action="store_true",
                    help="run the PT5xx liveness pass; exit 1 on "
                         "error-severity findings (the CI gate)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report as JSON (CI artifact)")
    ap.add_argument("--batch", type=int, default=64,
                    help="batch size substituted for -1 dims (default 64)")
    ap.add_argument("--top", type=int, default=10,
                    help="hot spots to print per program (default 10)")
    ap.add_argument("--mesh", default=None,
                    help="per-chip mode: mesh shape like dp=8 or dp=4,tp=2")
    ap.add_argument("--specs", default=None,
                    help="layout under --mesh: zero1 | allreduce "
                         "(default) | path to a JSON spec file")
    ap.add_argument("--hbm-budget-mb", type=float, default=0.0,
                    help="with --check and --mesh: FAIL programs whose "
                         "per-chip peak exceeds this many MiB")
    ap.add_argument("--validate-live", action="store_true",
                    help="with --mesh: train a dp-sharded zoo model live "
                         "and FAIL unless measured per-chip state bytes "
                         "match the static estimate within --tolerance")
    ap.add_argument("--tolerance", type=float, default=0.1,
                    help="relative tolerance for --validate-live "
                         "(default 0.1)")
    args = ap.parse_args(argv)

    mesh = _parse_mesh(args.mesh) if args.mesh else None

    targets = []
    if args.programs:
        for path in args.programs:
            with open(path, "r", encoding="utf-8") as f:
                prog = fluid.Program.from_json(f.read())
            feeds = [v.name for v in prog.global_block.vars.values()
                     if v.is_data]
            targets.append((path, prog, feeds, []))
    else:
        targets = _book_programs()

    ok = True
    report = {"batch_size": args.batch, "programs": []}
    if mesh:
        report["mesh"] = dict(mesh)
        report["specs_mode"] = args.specs or "allreduce"
    for name, prog, feeds, fetches in targets:
        entry, good = _report_one(name, prog, feeds, fetches, args.batch,
                                  args.top, args.check, mesh=mesh,
                                  specs_mode=args.specs,
                                  hbm_budget_mb=args.hbm_budget_mb)
        report["programs"].append(entry)
        ok = ok and good
    if args.validate_live:
        if not mesh:
            print("--validate-live requires --mesh", file=sys.stderr)
            return 2
        section = validate_live(mesh, args.specs, args.batch,
                                args.tolerance)
        report["live_validation"] = section
        ok = ok and section["ok"]
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
        print(f"report written to {args.json}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
