#!/usr/bin/env python
"""Numerics static-analysis linter CLI (CI face of
paddle_tpu.analysis.numerics).

Runs the interval/precision-flow analysis over the model zoo — including
QAT-transformed (``quant_aware``) resnet/bert/gpt variants — and reports
the PT900 family:

  PT900  broken fake-quant/dequant pairing                ERROR
  PT901  dead / non-persistable moving-average scale      WARNING
  PT902  statically-proven overflowing cast               ERROR
  PT903  reduction accumulated in low precision           WARNING
  PT904  AMP loss-scale coverage gap                      WARNING
  PT905  nonfinite-producing op on a proven interval      WARNING
  PT906  quantizable GEMM/conv site (the int8 work-list)  INFO

ALL of PT900-PT905 gate regardless of severity (a wrong-by-2^N gradient
does not become acceptable by being a warning); a finding is either
fixed or allowlisted below with the reason on record — the same contract
as tools/lint_concurrency.py. PT906 never gates: it is the work-list the
int8 epilogue-lowering PR consumes, carried in the JSON artifact.

Usage:
  python tools/lint_numerics.py
      Lint the zoo + QAT variants (the ci/run_ci.sh gate).
  --witness            ALSO run a short train+infer of mnist_mlp /
                       resnet / bert / gpt under FLAGS_numerics_witness=1
                       and cross-check every observed value against its
                       statically-proven interval, tolerance-free
                       (monitor.numwitness.containment_violations — any
                       escape is an analysis soundness bug and fails
                       CI). Observed abs-max feeds back into the PT906
                       report as calibration data.
  --json PATH          machine-readable report (the
                       ci_numerics_report.json CI artifact): findings,
                       the PT906 quantizability work-list, bounded
                       intervals, witness observations + violations.
  --negative-control   analyze the intentionally-broken fixtures under
                       tests/fixtures/numerics with an EMPTY allowlist;
                       the gate must trip on ALL of PT900-PT905 (proves
                       every detector can fail).

Exit status (stable, for CI):
  0  clean — no gating findings (and no containment violations)
  1  findings — PT900-PT905 not covered by the allowlist, or a witness
     containment violation
  2  internal error — the linter itself failed (never conflate a linter
     crash with a lint finding)

See docs/ANALYSIS.md for the code table and the transfer-rule authoring
guide; docs/OBSERVABILITY.md for the witness metrics.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.analysis.numerics import analyze_numerics  # noqa: E402

# Findings the zoo gate accepts, with the reason on record. Matched on
# (code, key) where key is "<program>:<op_type>" — stable across line
# numbers and var renames.
ALLOWLIST: dict = {
}

# every PT900-PT905 finding gates unless allowlisted; PT906 is the
# info-level work-list and never gates
GATING_CODES = ("PT900", "PT901", "PT902", "PT903", "PT904", "PT905")

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "..",
                           "tests", "fixtures", "numerics")

# (name, steps) of the zoo programs the --witness leg trains + infers;
# must stay a subset of _zoo_targets() names
WITNESS_RUNS = (("zoo/mnist_mlp", 3), ("zoo/resnet18", 2),
                ("zoo/bert_tiny", 2), ("zoo/gpt_tiny/prefill", 2))


def _zoo_targets():
    """(name, main, startup_or_None, fetch_names, feed_fn_or_None)
    tuples over the models the gate lints. feed_fn(rng) builds one batch
    for the witness leg (None = static-only target)."""
    import paddle_tpu.unique_name as un
    from paddle_tpu.contrib.slim.quantization import quant_aware
    from paddle_tpu.models import (BertConfig, GptConfig,
                                   build_bert_pretrain,
                                   build_gpt_generative, build_mnist_mlp,
                                   build_resnet)

    out = []

    def mlp_feed(rng):
        x = rng.randn(16, 784).astype(np.float32)
        return {"img": x,
                "label": rng.randint(0, 10, (16, 1)).astype(np.int64)}

    with un.guard():
        m = build_mnist_mlp(hidden=(64,))
        out.append(("zoo/mnist_mlp", m["main"], m["startup"],
                    [m["loss"].name, m["acc"].name], mlp_feed))

    def resnet_feed(rng):
        return {"img": rng.randn(4, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}

    with un.guard():
        m = build_resnet(depth=18, class_num=10, image_shape=(3, 32, 32))
        out.append(("zoo/resnet18", m["main"], m["startup"],
                    [m["loss"].name, m["acc"].name], resnet_feed))

    def bert_feed(rng):
        B, S = 2, 32
        ids = rng.randint(0, 100, (B, S)).astype(np.int64)
        mask_label = np.full((B, S), -100, np.int64)
        mask_label[:, :4] = rng.randint(0, 100, (B, 4))
        return {"src_ids": ids,
                "pos_ids": np.tile(np.arange(S, dtype=np.int64), (B, 1)),
                "sent_ids": np.zeros((B, S), np.int64),
                "input_mask": np.ones((B, S), np.float32),
                "mask_label": mask_label,
                "next_sent_label": rng.randint(0, 2, (B, 1)).astype(
                    np.int64)}

    with un.guard():
        m = build_bert_pretrain(BertConfig.tiny(), seq_len=32)
        out.append(("zoo/bert_tiny", m["main"], m["startup"],
                    [m["loss"].name], bert_feed))

    def gpt_feed(rng):
        B, S = 2, 16
        ids = np.zeros((B, S), np.int64)
        ids[:, :5] = rng.randint(1, 50, (B, 5))
        mask = np.zeros((B, S), np.float32)
        mask[:, :5] = 1.0
        return {"prompt_ids": ids, "prompt_mask": mask,
                "prompt_pos": np.tile(np.arange(S, dtype=np.int64),
                                      (B, 1)),
                "prompt_len": np.full((B, 1), 5, np.int64),
                "slot_mask": np.ones((B, 1), np.float32)}

    with un.guard():
        g = build_gpt_generative(GptConfig.tiny(), batch_slots=2,
                                 max_seq=32, page_size=8,
                                 prompt_buckets=(16,))
        pf = g["prefill"][16]
        out.append(("zoo/gpt_tiny/prefill", pf["main"], g["startup"],
                    [pf["first_token"].name], gpt_feed))
        out[-1] = out[-1] + (g,)   # state_vars needed by the witness run
        out.append(("zoo/gpt_tiny/decode", g["decode"]["main"], None,
                    [g["decode"]["next_token"].name], None))

    # QAT-transformed variants: quant_aware over fresh builds — the gate
    # proves the PT900/PT901 contract holds on the slim pass's own output
    with un.guard():
        m = build_resnet(depth=18, class_num=10, image_shape=(3, 32, 32),
                         build_optimizer=False)
        quant_aware(m["main"], m["startup"])
        out.append(("zoo/resnet18+qat", m["main"], None,
                    [m["loss"].name, m["acc"].name], None))
    with un.guard():
        m = build_bert_pretrain(BertConfig.tiny(), seq_len=32,
                                build_optimizer=False)
        quant_aware(m["main"], m["startup"])
        out.append(("zoo/bert_tiny+qat", m["main"], None,
                    [m["loss"].name], None))
    with un.guard():
        g = build_gpt_generative(GptConfig.tiny(), batch_slots=2,
                                 max_seq=32, page_size=8,
                                 prompt_buckets=(16,))
        pf = g["prefill"][16]
        quant_aware(pf["main"], g["startup"])
        out.append(("zoo/gpt_tiny/prefill+qat", pf["main"], None,
                    [pf["first_token"].name], None))
    return out


def _diag_dict(d) -> dict:
    return {"code": d.code, "severity": d.severity, "op_type": d.op_type,
            "block": d.block_idx, "op_idx": d.op_idx,
            "message": d.message, "site": d.site}


def _lint(name, program, fetch_names, allowlist, json_report,
          calibration=None) -> bool:
    rep = analyze_numerics(program, fetch_names=fetch_names,
                           calibration=calibration)
    gating, allow_hits = [], []
    for d in rep.diagnostics:
        if d.code not in GATING_CODES:
            continue
        reason = allowlist.get((d.code, f"{name}:{d.op_type or ''}"), "")
        if reason:
            allow_hits.append((d, reason))
        else:
            gating.append(d)
    by_code: dict = {}
    for d in rep.diagnostics:
        by_code[d.code] = by_code.get(d.code, 0) + 1
    status = "FAIL" if gating else "ok"
    sites = len(rep.quant_sites)
    print(f"[{status}] {name}: "
          f"{sum(len(b.ops) for b in program.blocks)} ops, "
          f"{len(rep.bounded_intervals(proven_only=False))} bounded "
          f"interval(s), {sites} quantizable site(s), findings "
          f"{by_code or '{}'}, {len(allow_hits)} allowlisted")
    for d in gating:
        print(f"  {d.code} [{d.severity}] op '{d.op_type}' "
              f"(block {d.block_idx} op {d.op_idx}): {d.message}")
    json_report["targets"].append({
        "name": name, "status": "fail" if gating else "ok",
        "report": rep.to_dict(),
        "gating": [_diag_dict(d) for d in gating],
        "allowlisted": [dict(_diag_dict(d), reason=r)
                        for d, r in allow_hits],
    })
    if gating:
        print(f"numerics gate -> FAIL ({name}: {len(gating)} "
              f"non-allowlisted finding(s))")
    return not gating


def _negative_control(json_report: dict) -> int:
    """Fixtures must trip every PT900-PT905 with the allowlist OFF."""
    sys.path.insert(0, FIXTURE_DIR)
    fixture_modules = sorted(
        f[:-3] for f in os.listdir(FIXTURE_DIR)
        if f.endswith(".py") and f != "__init__.py")

    tripped = set()
    ok_all = True
    for modname in fixture_modules:
        mod = importlib.import_module(modname)
        main, _startup, fetch = mod.build()
        ok = _lint(f"negative-control({modname})", main, fetch, {},
                   json_report)
        ok_all = ok_all and ok
        tripped |= set(json_report["targets"][-1]["report"]
                       .get("findings_by_code", {}))
    missing = [c for c in GATING_CODES if c not in tripped]
    if missing:
        # a control that cannot trip every family is a broken control,
        # not a gate failure — exit 2 so CI's "-> FAIL" grep flags it
        print(f"negative control did NOT produce {', '.join(missing)} "
              f"on the fixtures — the analysis lost coverage",
              file=sys.stderr)
        return 2
    if ok_all:
        print("negative control found nothing gating on intentionally "
              "broken fixtures", file=sys.stderr)
        return 0   # CI inverts the exit status: 0 here fails the build
    return 1


def _witness_run(name, main, startup, fetch_names, feed_fn, steps,
                 net=None):
    """Short train (or infer) loop under FLAGS_numerics_witness=1;
    returns the merged observed ranges {var: {...}}."""
    from paddle_tpu.flags import set_flags
    from paddle_tpu.monitor import numwitness

    numwitness.reset_numerics_witness()
    set_flags({"numerics_witness": True})
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe.run(startup)
            if net is not None:     # generative state (paged KV, slots)
                from paddle_tpu.core.types import np_dtype

                for vn, (shape, dt) in net["state_vars"].items():
                    scope.set_var(vn, np.zeros(shape, np_dtype(dt)))
            for _ in range(steps):
                exe.run(main, feed=feed_fn(rng), fetch_list=fetch_names)
            # the infer leg: forward-only clone over the trained params
            # (same var names, same static intervals)
            if net is None:
                infer = main.clone(for_test=True)
                feed = feed_fn(rng)
                infer_fetch = [n for n in fetch_names
                               if infer.global_block.has_var(n)]
                exe.run(infer, feed=feed, fetch_list=infer_fetch)
        return numwitness.numerics_witness_vars()
    finally:
        set_flags({"numerics_witness": False})


def _witness_leg(targets, json_report: dict) -> bool:
    """The lock-witness idiom for numerics: every observed value must lie
    inside its statically-proven interval, tolerance-free."""
    from paddle_tpu.monitor import numwitness

    by_name = {t[0]: t for t in targets}
    ok = True
    for name, steps in WITNESS_RUNS:
        t = by_name[name]
        net = t[5] if len(t) > 5 else None
        _, main, startup, fetch_names, feed_fn = t[:5]
        observed = _witness_run(name, main, startup, fetch_names,
                                feed_fn, steps, net=net)
        rep = analyze_numerics(main, fetch_names=fetch_names)
        static = rep.bounded_intervals(proven_only=True)
        checked = sorted(set(static) & set(observed))
        violations = numwitness.containment_violations(static, observed)
        status = "FAIL" if violations else "ok"
        print(f"[{status}] witness {name}: {steps} step(s), "
              f"{len(observed)} var(s) observed, {len(checked)} "
              f"interval(s) cross-checked, "
              f"{len(violations)} containment violation(s)")
        for v in violations:
            print(f"  ESCAPE {v['var']}: {v['detail']}")
        # feed observed abs-max back into PT906 as calibration
        calib = {n: o["absmax"] for n, o in observed.items()}
        calibrated = analyze_numerics(main, fetch_names=fetch_names,
                                      calibration=calib)
        json_report["witness"].append({
            "name": name, "steps": steps,
            "status": "fail" if violations else "ok",
            "observed": observed,
            "checked_vars": checked,
            "violations": violations,
            "quant_sites_calibrated": calibrated.quant_sites,
        })
        if violations:
            print(f"numerics gate -> FAIL (witness {name}: "
                  f"{len(violations)} observed value(s) escaped their "
                  f"static interval — analysis soundness bug)")
            ok = False
    return ok


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable report here "
                         "(ci_numerics_report.json)")
    ap.add_argument("--witness", action="store_true",
                    help="also run the runtime-witness containment "
                         "cross-check over the zoo")
    ap.add_argument("--negative-control", action="store_true",
                    help="analyze the broken fixtures with an empty "
                         "allowlist; must FAIL")
    args = ap.parse_args(argv)

    json_report = {
        "targets": [], "witness": [],
        "allowlist": [{"code": c, "key": k, "reason": r}
                      for (c, k), r in sorted(ALLOWLIST.items())],
    }
    if args.negative_control:
        code = _negative_control(json_report)
        json_report["status"] = "negative-control"
    else:
        targets = _zoo_targets()
        ok = True
        for t in targets:
            name, main, _startup, fetch_names = t[0], t[1], t[2], t[3]
            ok = _lint(name, main, fetch_names, ALLOWLIST,
                       json_report) and ok
        if args.witness:
            ok = _witness_leg(targets, json_report) and ok
        json_report["status"] = "ok" if ok else "fail"
        code = 0 if ok else 1
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(json_report, f, indent=2, sort_keys=True)
        print(f"report -> {args.json}")
    return code


def main(argv=None) -> int:
    """Stable CI exit codes: 0 clean, 1 findings, 2 internal error."""
    try:
        return run(argv)
    except SystemExit as e:  # argparse error: also an internal error
        code = e.code if isinstance(e.code, int) else 2
        return code if code in (0, 1) else 2
    except Exception:
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
