"""On-chip perf probes behind the round-4 MFU work (docs/PERF_NOTES.md).

Each probe times a jitted computation on the real chip (compile excluded)
and prints achieved TFLOP/s. Random inputs (constant inputs let remote
execution caches / folding produce fantasy numbers — observed 43k TF/s).
Run on TPU:  python tools/perf_probe.py
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                      # noqa: E402
import jax.numpy as jnp         # noqa: E402

V5E_PEAK = 197.0
RNG = np.random.RandomState(0)


def rnd(shape, dtype=jnp.bfloat16):
    return jnp.asarray(RNG.randn(*shape).astype(np.float32)).astype(dtype)


def timeit(fn, *args, iters=10):
    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def probe_matmul(n=4096):
    a, b = rnd((n, n)), rnd((n, n))
    f = jax.jit(lambda a, b: a @ b)
    dt = timeit(f, a, b)
    tf = 2 * n ** 3 / dt / 1e12
    print(f"matmul {n}^3 bf16: {dt*1e3:.2f} ms, {tf:.1f} TF/s "
          f"({100*tf/V5E_PEAK:.0f}% peak)")


def _conv(layout, B, C_in, C_out, HW, k, stride):
    pad = k // 2
    if layout == "NCHW":
        x = rnd((B, C_in, HW, HW))
        w = rnd((C_out, C_in, k, k))
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        x = rnd((B, HW, HW, C_in))
        w = rnd((k, k, C_in, C_out))
        dn = ("NHWC", "HWIO", "NHWC")

    def f(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=dn)

    out_hw = (HW + 2 * pad - k) // stride + 1
    flops = 2 * B * out_hw * out_hw * C_out * C_in * k * k
    return f, (x, w), flops


def probe_conv_train(tag, B, C_in, C_out, HW, k, stride):
    for layout in ("NCHW", "NHWC"):
        f, (x, w), flops = _conv(layout, B, C_in, C_out, HW, k, stride)
        g = jax.jit(jax.grad(
            lambda x, w: jnp.sum(f(x, w).astype(jnp.float32)),
            argnums=(0, 1)))
        dt = timeit(g, x, w)
        tf = 3 * flops / dt / 1e12
        print(f"{tag} fwd+bwd {layout}: {dt*1e3:.2f} ms, ~{tf:.1f} TF/s "
              f"({100*tf/V5E_PEAK:.0f}% peak)")


def probe_resnet_step(nhwc: str):
    from paddle_tpu import flags

    flags.set_flags({"FLAGS_conv_use_nhwc": nhwc})
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.resnet import build_resnet

    with un.guard():
        model = build_resnet(depth=50, class_num=1000, amp=True)
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        dev = fluid.TPUPlace().jax_device()
        feed = {"img": jax.device_put(
                    RNG.rand(128, 3, 224, 224).astype(np.float32), dev),
                "label": jax.device_put(
                    RNG.randint(0, 1000, (128, 1)).astype(np.int64), dev)}
        with fluid.scope_guard(scope):
            exe.run(model["startup"])

            def step():
                return exe.run(model["main"], feed=feed,
                               fetch_list=[model["loss"]],
                               return_numpy=False)

            step()
            jax.block_until_ready(list(scope.vars.values()))
            t0 = time.perf_counter()
            for _ in range(10):
                out = step()
            jax.block_until_ready(out)
            jax.block_until_ready(list(scope.vars.values()))
            dt = (time.perf_counter() - t0) / 10
    tf = 128 * 3 * 4.1e9 / dt / 1e12
    print(f"resnet50 bf16 train bs=128 [nhwc={nhwc}]: {dt*1e3:.1f} ms "
          f"({128/dt:.0f} img/s, ~{tf:.1f} TF/s, {100*tf/V5E_PEAK:.0f}% peak)")
    flags.set_flags({"FLAGS_conv_use_nhwc": "auto"})


if __name__ == "__main__":
    print("backend:", jax.default_backend())
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "micro"):
        probe_matmul()
        # ResNet-50 shape census: stem, early 3x3, mid 3x3, 1x1 bottleneck,
        # strided transition, last-stage small-spatial
        probe_conv_train("stem 7x7/2 3->64 @224", 128, 3, 64, 224, 7, 2)
        probe_conv_train("stage1 3x3 64ch @56", 128, 64, 64, 56, 3, 1)
        probe_conv_train("stage3 3x3 256ch @14", 128, 256, 256, 14, 3, 1)
        probe_conv_train("1x1 256->1024 @14", 128, 256, 1024, 14, 1, 1)
        probe_conv_train("stage4 3x3 512ch @7", 128, 512, 512, 7, 3, 1)
    if which in ("all", "resnet"):
        probe_resnet_step("never")
        probe_resnet_step("always")
