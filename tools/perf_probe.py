"""On-chip perf probes behind the round-4 MFU work (docs/PERF_NOTES.md).

Measurement protocol for the axon dev tunnel (hard-won, do not "simplify"):
- timing must run over a DATA-DEPENDENT chain of iterations (carry the
  output into the next step). Independent dispatches complete out of order
  behind the tunnel; blocking on the last one does NOT drain the others —
  that both fakes the timed section (>1000% "peak" observed) and leaves a
  backlog that poisons whatever is timed next.
- finish with a host fetch (float(...)) — the only hard sync point.
- subtract the ~70-100 ms round-trip by differencing two chain lengths.

Run on TPU:  python tools/perf_probe.py [micro|resnet|all]
"""
from __future__ import annotations

import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                      # noqa: E402
import jax.numpy as jnp         # noqa: E402

V5E_PEAK = 197.0
RNG = np.random.RandomState(0)


def rnd(shape, dtype=jnp.bfloat16):
    return jax.device_put(RNG.randn(*shape).astype(np.float32)).astype(dtype)


def chain_time(make_fn, k_short=4, k_long=16, iters=3):
    """Median per-iteration seconds of make_fn(k)'s chained body, RTT
    removed by (T_long - T_short) / (k_long - k_short)."""
    def run(k):
        f = make_fn(k)
        float(f())            # compile + warm
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            float(f())
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    return (run(k_long) - run(k_short)) / (k_long - k_short)


def probe_matmul(n=4096):
    a, b = rnd((n, n)), rnd((n, n))

    def make(k):
        @jax.jit
        def f():
            x = a
            for _ in range(k):
                x = x @ b * (1.0 / n)
            return x.astype(jnp.float32).sum()
        return f

    dt = chain_time(make, 20, 200)
    tf = 2 * n ** 3 / dt / 1e12
    print(f"matmul {n}^3 bf16: {dt*1e3:.3f} ms, {tf:.1f} TF/s "
          f"({100*tf/V5E_PEAK:.0f}% peak)")


def probe_conv_train(tag, B, C, HW, k, layout):
    """fwd+bwd of one CxC kxk conv at BxHWxHW, chained through a dummy
    SGD update so iterations serialize."""
    pad = k // 2
    if layout == "NCHW":
        x = rnd((B, C, HW, HW))
        w0 = rnd((C, C, k, k), jnp.float32)
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        x = rnd((B, HW, HW, C))
        w0 = rnd((k, k, C, C), jnp.float32)
        dn = ("NHWC", "HWIO", "NHWC")

    def loss(w):
        y = jax.lax.conv_general_dilated(
            x, w.astype(jnp.bfloat16), (1, 1), [(pad, pad), (pad, pad)],
            dimension_numbers=dn)
        return jnp.sum(y.astype(jnp.float32) ** 2) * 1e-12

    def make(kk):
        @jax.jit
        def f():
            def body(w, _):
                g = jax.grad(loss)(w)
                return w - 1e-20 * g, None
            w, _ = jax.lax.scan(body, w0, None, length=kk)
            return w.sum()
        return f

    dt = chain_time(make, 2, 10)
    flops = 3 * 2 * B * HW * HW * C * C * k * k
    tf = flops / dt / 1e12
    print(f"{tag} fwd+bwd {layout}: {dt*1e3:.2f} ms, ~{tf:.1f} TF/s "
          f"({100*tf/V5E_PEAK:.0f}% peak)")


def probe_resnet_step(nhwc: str, iters=10):
    from paddle_tpu import flags

    flags.set_flags({"FLAGS_conv_use_nhwc": nhwc})
    import paddle_tpu as fluid
    import paddle_tpu.unique_name as un
    from paddle_tpu.models.resnet import build_resnet

    with un.guard():
        model = build_resnet(depth=50, class_num=1000, amp=True)
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        dev = fluid.TPUPlace().jax_device()
        feed = {"img": jax.device_put(
                    RNG.rand(128, 3, 224, 224).astype(np.float32), dev),
                "label": jax.device_put(
                    RNG.randint(0, 1000, (128, 1)).astype(np.int64), dev)}
        with fluid.scope_guard(scope):
            exe.run(model["startup"])

            def step():
                return exe.run(model["main"], feed=feed,
                               fetch_list=[model["loss"]],
                               return_numpy=False)

            # warm + hard sync (host fetch) so timing starts quiescent
            out = step()
            float(np.asarray(out[0]).reshape(-1)[0])
            t0 = time.perf_counter()
            for _ in range(iters):
                out = step()   # state donation chains the iterations
            float(np.asarray(out[0]).reshape(-1)[0])
            dt = (time.perf_counter() - t0) / iters
    tf = 128 * 3 * 4.1e9 / dt / 1e12
    print(f"resnet50 bf16 train bs=128 [nhwc={nhwc}]: {dt*1e3:.1f} ms "
          f"({128/dt:.0f} img/s, ~{tf:.1f} TF/s, {100*tf/V5E_PEAK:.0f}% peak)")
    flags.set_flags({"FLAGS_conv_use_nhwc": "auto"})


if __name__ == "__main__":
    print("backend:", jax.default_backend())
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "micro"):
        probe_matmul()
        for layout in ("NCHW", "NHWC"):
            probe_conv_train("stage1 3x3 64ch @56", 128, 64, 56, 3, layout)
            probe_conv_train("stage3 3x3 256ch @14", 128, 256, 14, 3, layout)
            probe_conv_train("stage4 3x3 512ch @7", 128, 512, 7, 3, layout)
    if which in ("all", "resnet"):
        probe_resnet_step("never")
        probe_resnet_step("always")
