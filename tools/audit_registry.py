#!/usr/bin/env python
"""Op-registry conformance/coverage audit CLI.

Dumps one row per registered op: infer_shape source (explicit/auto), lower
rule presence, grad story (auto-vjp / custom / none), rng & raw flags, and
whether any test file references the op. Makes registry gaps visible instead
of latent.

  python tools/audit_registry.py              # table to stdout
  python tools/audit_registry.py --json       # machine-readable to stdout
  python tools/audit_registry.py --json-file audit.json   # CI artifact
  python tools/audit_registry.py --strict     # exit 1 if any op lacks a
                                              # lower rule (CI gate)
  python tools/audit_registry.py --untested   # only ops no test mentions

Exit status (stable, for CI): 0 clean, 1 findings under --strict (an op
without a lower rule), 2 internal error (the auditor itself failed).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu  # noqa: F401,E402  (registers all ops)
from paddle_tpu.analysis import (audit_registry, coverage_summary,  # noqa: E402
                                 format_audit)

TESTS_DIR = os.path.join(os.path.dirname(__file__), "..", "tests")


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--json-file", metavar="PATH", default=None,
                    help="also write the machine-readable report here "
                         "(the CI artifact)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when an op has no lower rule")
    ap.add_argument("--untested", action="store_true",
                    help="only show ops never referenced by a test file")
    ap.add_argument("--no-tests", action="store_true",
                    help="skip the test-reference scan")
    args = ap.parse_args(argv)

    test_dir = None if args.no_tests else os.path.abspath(TESTS_DIR)
    rows = audit_registry(test_dir=test_dir)
    if args.untested:
        rows = [r for r in rows if r["tested"] is False]
    missing_lower = [r["op"] for r in rows if not r["lower"]]
    report = {"ops": rows, "summary": coverage_summary(rows),
              "missing_lower": missing_lower,
              "status": "fail" if (missing_lower and args.strict) else "ok"}
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(format_audit(rows))
    if args.json_file:
        with open(args.json_file, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)

    if missing_lower:
        print(f"\nops without a lower rule: {missing_lower}",
              file=sys.stderr)
        if args.strict:
            return 1
    return 0


def main(argv=None) -> int:
    """Stable CI exit codes: 0 clean, 1 findings, 2 internal error."""
    try:
        return run(argv)
    except SystemExit as e:  # argparse error: also an internal error
        code = e.code if isinstance(e.code, int) else 2
        return code if code in (0, 1) else 2
    except Exception:
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
