#!/usr/bin/env python
"""Executor runtime-metrics report + CI recompile gate (the reporting face
of ``paddle_tpu.monitor``, sibling of tools/mem_report.py).

Runs a built-in model suite through the executor (run / run_chained /
inference-clone paths), collects the monitor's counters per scenario, and
dumps the full metrics snapshot (registry + compile/recompile events) as a
JSON artifact for CI.

Usage:
  python tools/metrics_report.py
      Run the suite, print the per-scenario metric summary.
  python tools/metrics_report.py --json report.json
      Also write the machine-readable artifact (the CI companion of
      ci_mem_report.json).
  python tools/metrics_report.py --check
      CI gate: exit 1 if any scenario misses its expected compile/cache
      behaviour or if recompiles exceed --recompile-threshold (default 0 —
      the suite is steady-state by construction, ANY recompile is a
      regression in the cache keying or the lowering).
  python tools/metrics_report.py --check --force-recompile 3
      Negative control: appends a scenario that alternates feed shapes to
      force 3 recompiles; the gate must then FAIL (non-zero exit). CI runs
      this once to prove the tripwire trips.

Metric semantics: docs/OBSERVABILITY.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import monitor  # noqa: E402

# the (metric, labels) scalars each scenario reports as deltas
_TRACKED = {
    "run_hits": ("executor_cache_lookups_total",
                 {"path": "run", "result": "hit"}),
    "run_misses": ("executor_cache_lookups_total",
                   {"path": "run", "result": "miss"}),
    "run_compiles": ("executor_compiles_total", {"path": "run"}),
    "chained_hits": ("executor_cache_lookups_total",
                     {"path": "chained", "result": "hit"}),
    "chained_misses": ("executor_cache_lookups_total",
                       {"path": "chained", "result": "miss"}),
    "chained_compiles": ("executor_compiles_total", {"path": "chained"}),
    "chained_iterations": ("executor_chained_iterations_total", {}),
    "donated_buffers": ("executor_donated_buffers_total", {}),
    "kept_buffers": ("executor_kept_buffers_total", {}),
    "feed_bytes": ("executor_feed_bytes_total", {}),
    "fetch_bytes": ("executor_fetch_bytes_total", {}),
}


def _counters_now() -> dict:
    vals = {}
    for key, (name, labels) in _TRACKED.items():
        v = monitor.metric_value(name, default=0.0, **labels)
        vals[key] = float(v)
    vals["recompiles"] = float(monitor.recompile_count())
    return vals


def _delta(before: dict, after: dict) -> dict:
    return {k: int(after[k] - before[k]) for k in after}


def _build_regression():
    x = fluid.layers.data("x", shape=[13], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.02).minimize(loss)
    return loss


def _feed(batch=8, dtype=np.float32):
    rng = np.random.RandomState(0)
    return {"x": rng.rand(batch, 13).astype(dtype),
            "y": rng.rand(batch, 1).astype(dtype)}


def scenario_run_repeat():
    """Two exe.run of the same program/feed: exactly 1 compile + 1 cache
    hit (the acceptance bar for the compile cache)."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_regression()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = _feed()
        with fluid.scope_guard(scope):
            exe.run(startup)                     # outside the window
            before = _counters_now()
            exe.run(main, feed=feed, fetch_list=[loss])
            exe.run(main, feed=feed, fetch_list=[loss])
    got = _delta(before, _counters_now())
    expect = {"run_compiles": 1, "run_hits": 1, "run_misses": 1,
              "recompiles": 0}
    return {"name": "run_repeat", "metrics": got, "expect": expect}


def scenario_chained_kept_state():
    """run_chained twice with a fetched param: 1 chained compile + 1 hit,
    donated AND kept buffers both reported (the PR 2 kept-state split)."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_regression()
            param = next(v.name for v in main.global_block.vars.values()
                         if type(v).__name__ == "Parameter"
                         and v.name.endswith(".w_0"))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = _feed()
        with fluid.scope_guard(scope):
            exe.run(startup)
            before = _counters_now()
            exe.run_chained(main, feed=feed, fetch_list=[loss, param],
                            steps=3)
            exe.run_chained(main, feed=feed, fetch_list=[loss, param],
                            steps=3)
    got = _delta(before, _counters_now())
    expect = {"chained_compiles": 1, "chained_hits": 1,
              "chained_misses": 1, "chained_iterations": 6,
              "recompiles": 0}
    ok_extra = got["donated_buffers"] > 0 and got["kept_buffers"] > 0
    return {"name": "chained_kept_state", "metrics": got, "expect": expect,
            "extra_ok": ok_extra,
            "extra_why": "donated>0 and kept>0 (fetched param is "
                         "donation-unsafe but threads the carry)"}


def scenario_infer_clone():
    """Inference clone run twice: its own single compile, then cache."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            pred = fluid.layers.fc(x, 4, act="softmax")
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": np.random.RandomState(1).rand(8, 13)
                .astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            before = _counters_now()
            exe.run(infer, feed=feed, fetch_list=[pred.name])
            exe.run(infer, feed=feed, fetch_list=[pred.name])
    got = _delta(before, _counters_now())
    expect = {"run_compiles": 1, "run_hits": 1, "run_misses": 1,
              "recompiles": 0}
    return {"name": "infer_clone_repeat", "metrics": got, "expect": expect}


def scenario_forced_recompile(n: int):
    """Negative control: grow the feed batch size every run so each run
    after the first misses the cache with a fresh signature — n recompiles,
    each diagnosed with changed=('feed_signature',). The --check gate must
    fail on this. (Alternating two sizes would NOT recompile: both steps
    stay cached — exactly the bucketed-shape advice in
    docs/OBSERVABILITY.md.)"""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_regression()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            before = _counters_now()
            for i in range(n + 1):
                exe.run(main, feed=_feed(batch=8 * (i + 1)),
                        fetch_list=[loss])
    got = _delta(before, _counters_now())
    evs = monitor.recompile_events()
    return {"name": f"forced_recompile_x{n}", "metrics": got,
            "expect": {"recompiles": n}, "forced": True,
            "diagnostic": (evs[-1].to_dict() if evs else None)}


SCENARIOS = [scenario_run_repeat, scenario_chained_kept_state,
             scenario_infer_clone]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on missed expectations or recompiles "
                         "above --recompile-threshold (the CI gate)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the metrics snapshot artifact as JSON")
    ap.add_argument("--recompile-threshold", type=int, default=0,
                    help="max tolerated recompiles across the suite "
                         "(default 0)")
    ap.add_argument("--force-recompile", type=int, default=0, metavar="N",
                    help="append a scenario that forces N recompiles "
                         "(negative control: --check must then fail)")
    args = ap.parse_args(argv)

    monitor.reset()
    results = [fn() for fn in SCENARIOS]
    if args.force_recompile > 0:
        results.append(scenario_forced_recompile(args.force_recompile))

    suite_ok = True
    for r in results:
        missed = {k: (v, r["metrics"].get(k))
                  for k, v in r["expect"].items()
                  if r["metrics"].get(k) != v}
        r["ok"] = not missed and r.get("extra_ok", True)
        r["missed"] = {k: {"want": w, "got": g}
                       for k, (w, g) in missed.items()}
        if not r.get("forced"):
            suite_ok = suite_ok and r["ok"]
        status = "ok" if r["ok"] else "MISS"
        print(f"[{status}] {r['name']}: " + ", ".join(
            f"{k}={v}" for k, v in sorted(r["metrics"].items()) if v))
        for k, wg in r["missed"].items():
            print(f"       expected {k}={wg['want']}, got {wg['got']}")

    # histogram SLO summary: the registry snapshots now carry estimated
    # p50/p99 (serving latency reads the same fields in load_check)
    for fam in monitor.get_registry().families():
        if fam.kind != "histogram":
            continue
        # only *_seconds histograms are durations; ratio histograms
        # (e.g. serving_batch_occupancy) print their raw values
        in_ms = fam.name.endswith("_seconds")

        def _fmt(v):
            return f"{v * 1e3:.2f}ms" if in_ms else f"{v:.4g}"

        for labels, child in fam.children():
            snap = child.snapshot()
            if not snap["count"]:
                continue
            lbl = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            print(f"  {fam.name}{{{lbl}}}: n={snap['count']} "
                  f"p50={_fmt(snap['p50'])} p99={_fmt(snap['p99'])} "
                  f"max={_fmt(snap['max'])}")

    recompiles = monitor.recompile_count()
    gate_ok = suite_ok and recompiles <= args.recompile_threshold
    check = {"recompile_threshold": args.recompile_threshold,
             "recompiles": recompiles, "suite_ok": suite_ok,
             "status": "ok" if gate_ok else "fail"}
    print(f"recompiles across suite: {recompiles} "
          f"(threshold {args.recompile_threshold}) -> "
          f"{'ok' if gate_ok else 'FAIL'}")
    for ev in monitor.recompile_events():
        print(f"  recompile[{ev.path}] program {ev.program_serial} "
              f"built at {ev.build_site}: changed {list(ev.changed)} — "
              f"{ev.detail}")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({"scenarios": results,
                       "snapshot": monitor.snapshot(),
                       "check": check}, f, indent=2, default=str)
        print(f"metrics artifact written to {args.json}")
    return 0 if (not args.check or gate_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
