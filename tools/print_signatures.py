"""API-freeze signature dump (reference tools/print_signatures.py).

Usage:
    python tools/print_signatures.py paddle_tpu > tools/api_signatures.txt

Walks the public API surface (modules re-exported from the root package,
plus fluid.layers / optimizer / dygraph / contrib namespaces) and prints
one stable line per callable: qualified name + argspec. The committed
tools/api_signatures.txt is the freeze; tests/test_api_freeze.py fails
when a signature changes without regenerating the file — the reference's
CI gate against accidental API breaks (tools/check_api_compatible.py).
"""
from __future__ import annotations

import importlib
import inspect
import os
import sys

# python puts the SCRIPT's dir on sys.path; the package lives one up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SKIP_PREFIXES = ("_",)


def signature_of(member):
    try:
        if inspect.isclass(member):
            try:
                sig = str(inspect.signature(member.__init__))
            except (ValueError, TypeError):
                sig = "(...)"
            return f"class{sig}"
        sig = str(inspect.signature(member))
        return sig
    except (ValueError, TypeError):
        return "(...)"


def walk(module_name):
    mod = importlib.import_module(module_name)
    lines = {}

    def visit(mod, prefix, depth):
        if depth > 3:
            return
        for name in dir(mod):
            if name.startswith(SKIP_PREFIXES):
                continue
            try:
                member = getattr(mod, name)
            except Exception:
                continue
            # typing re-exports (Any, Optional, ...) repr differently
            # across interpreter versions; they are not API surface
            if getattr(member, "__module__", "") == "typing":
                continue
            qual = f"{prefix}.{name}"
            if inspect.ismodule(member):
                # only descend into our own package
                if getattr(member, "__name__", "").startswith(module_name) \
                        and "." not in name:
                    visit(member, qual, depth + 1)
            elif callable(member):
                lines[qual] = signature_of(member)
    visit(mod, module_name, 0)
    return lines


def main():
    module_name = sys.argv[1] if len(sys.argv) > 1 else "paddle_tpu"
    lines = walk(module_name)
    for name in sorted(lines):
        print(f"{name} {lines[name]}")


if __name__ == "__main__":
    main()
