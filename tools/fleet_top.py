#!/usr/bin/env python
"""fleet_top — a one-shot / ``--watch`` fleet telemetry viewer.

Scrapes every named replica's ``/metrics.json`` through a
:class:`paddle_tpu.serving.fleet.FleetAggregator` and renders the
aggregator snapshot as a terminal table: per-replica pressure (up /
stale / scrape age / queue depth / request rate / p50 / p99 / SLO burn
state), the EXACT cross-replica latency merge as the fleet p50/p99, and
the busiest tenants by engine occupancy. Stdlib only — point it at any
running fleet:

  python tools/fleet_top.py r0=127.0.0.1:8000 r1=127.0.0.1:8001
  python tools/fleet_top.py --watch --interval 2 r0=127.0.0.1:8000

A replica that stops answering (or answers garbage) shows up stale with
its typed error and a growing age — exactly the degraded view the
aggregator publishes, never a crash. docs/OBSERVABILITY.md "Fleet
telemetry plane" documents the underlying metrics.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.serving.fleet import (AggregatorConfig,  # noqa: E402
                                      FleetAggregator)


def _fmt_ms(v) -> str:
    return f"{v * 1e3:8.1f}" if v is not None else f"{'-':>8}"


def _fmt(v, spec="8.1f") -> str:
    return f"{v:{spec}}" if v is not None else f"{'-':>{spec.split('.')[0]}}"


def _completed_rate(rec) -> float | None:
    rates = (rec.get("rates") or {}).get("serving_requests_total") or {}
    return rates.get("outcome=completed")


_BURN_ORDER = {"ok": 0, "warning": 1, "burning": 2}


def _class_burn(replicas: dict) -> dict:
    """Worst per-class SLO burn state across every scraped replica."""
    classes: dict = {}
    for rec in replicas.values():
        for name, cls in ((rec.get("slo") or {}).get("classes")
                          or {}).items():
            state = cls.get("state", "unknown")
            if _BURN_ORDER.get(state, -1) >= _BURN_ORDER.get(
                    classes.get(name), -1):
                classes[name] = state
    return classes


def render(snapshot: dict, clock: str, autoscaler: dict = None) -> str:
    """``autoscaler`` (optional) is a ``FleetAutoscaler.status()`` dict
    from an embedding process (the supervisor side); the scrape-only CLI
    renders everything else without it."""
    replicas = snapshot["replicas"]
    fleet = snapshot["fleet"]
    up = sum(1 for r in replicas.values() if r.get("up"))
    out = [f"fleet view @ {clock} — {len(replicas)} replicas, {up} up",
           f"{'REPLICA':10} {'UP':>3} {'STALE':>5} {'AGE_S':>6} "
           f"{'QUEUE':>5} {'REQ/S':>8} {'P50_MS':>8} {'P99_MS':>8} "
           f"{'SLO':>8}  ERR"]
    for rid in sorted(replicas):
        rec = replicas[rid]
        lat = rec.get("latency") or {}
        slo = (rec.get("slo") or {}).get("state", "unknown")
        out.append(
            f"{rid:10} {('yes' if rec.get('up') else 'no'):>3} "
            f"{('yes' if rec.get('stale') else 'no'):>5} "
            f"{_fmt(rec.get('scrape_age_s'), '6.1f')} "
            f"{_fmt(rec.get('queue_depth'), '5.0f')} "
            f"{_fmt(_completed_rate(rec), '8.2f')} "
            f"{_fmt_ms(lat.get('p50'))} {_fmt_ms(lat.get('p99'))} "
            f"{slo:>8}  {rec.get('error') or ''}")
    done = fleet["outcomes"].get("completed")
    out.append(
        f"fleet: p50 {_fmt_ms(fleet['p50']).strip()}ms  "
        f"p99 {_fmt_ms(fleet['p99']).strip()}ms  "
        f"completed {int(done) if done is not None else '-'}  "
        f"slo {fleet['slo_state']}")
    burn = _class_burn(replicas)
    if burn:
        out.append("slo burn: " + "  ".join(
            f"{name}={burn[name]}" for name in sorted(burn)))
    if autoscaler:
        sense = autoscaler.get("sense") or {}
        last = autoscaler.get("last_decision")
        decision = (f"{last['action']} ({last['reason']}) — "
                    f"{last['detail']}" if last else "none yet")
        out.append(
            f"autoscaler: replicas {sense.get('replicas', '-')} "
            f"(spawning {sense.get('spawning', 0)}, draining "
            f"{len(sense.get('draining') or [])})  "
            f"{'HOT' if sense.get('hot') else 'calm'}  "
            f"last: {decision}")
    tenants = sorted(fleet["tenants"].items(),
                     key=lambda kv: -kv[1]["occupancy_s"])
    if tenants:
        out.append(f"{'TENANT':12} {'REQS':>6} {'COMPLETED':>9} "
                   f"{'SHED':>6} {'QUOTA_SHED':>10} {'OCC_S':>8}")
        for name, t in tenants[:8]:
            outcomes = t.get("outcomes") or {}
            out.append(
                f"{name:12} {sum(outcomes.values()):>6} "
                f"{outcomes.get('completed', 0):>9} "
                f"{outcomes.get('shed', 0):>6} "
                f"{t.get('quota_sheds', 0):>10} "
                f"{t['occupancy_s']:>8.2f}")
    return "\n".join(out)


def parse_targets(specs) -> list:
    targets = []
    for spec in specs:
        rid, sep, addr = spec.partition("=")
        if not sep or ":" not in addr:
            raise SystemExit(f"bad target {spec!r} "
                             f"(want replica_id=host:port)")
        targets.append((rid, addr))
    return targets


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="+",
                    metavar="replica_id=host:port",
                    help="replicas to scrape")
    ap.add_argument("--watch", action="store_true",
                    help="refresh continuously instead of one shot")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-scrape timeout seconds")
    args = ap.parse_args(argv)

    # the viewer IS the telemetry plane's consumer: turn it on locally
    # (the scraped replicas carry their own flag)
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    agg = FleetAggregator(
        parse_targets(args.targets),
        AggregatorConfig(scrape_interval_s=max(args.interval, 0.1),
                         scrape_timeout_s=args.timeout))
    try:
        while True:
            agg.poll_now()
            clock = time.strftime("%H:%M:%S")
            text = render(agg.snapshot(), clock)
            if args.watch:
                sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
                sys.stdout.flush()
                time.sleep(max(args.interval, 0.1))
            else:
                print(text)
                return 0
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
