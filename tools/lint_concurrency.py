#!/usr/bin/env python
"""Concurrency static-analysis linter CLI (CI face of
paddle_tpu.analysis.concurrency).

Runs the source-level lock analysis over the whole ``paddle_tpu``
package (or explicit files): inventories every named lock, builds the
static lock-order graph, and reports the PT800 family —

  PT800  lock-order cycle (or non-reentrant self-acquisition)  ERROR
  PT801  blocking call while holding a lock                    WARNING
  PT802  cross-thread attribute with unguarded access          WARNING

ALL three codes gate (a deadlock does not become acceptable by being
a warning); a finding is either fixed or allowlisted below with the
reason on record — the same contract as tools/lint_program.py.

Usage:
  python tools/lint_concurrency.py
      Lint the paddle_tpu package (the ci/run_ci.sh gate).
  python tools/lint_concurrency.py path/to/file.py [more.py ...]
      Lint explicit files (fixtures, subsets).
  --json PATH          machine-readable report (the
                       ci_concurrency_report.json CI artifact): lock
                       inventory, static edge list, findings, allowlist
                       hits. tools/load_check.py --lock-witness merges
                       its runtime ``lock_witness`` section into the
                       same file.
  --negative-control   lint the intentionally-broken fixtures under
                       tests/fixtures/concurrency with an EMPTY
                       allowlist; the gate must trip on all three
                       codes (proves the linter can fail).

Exit status (stable, for CI):
  0  clean — no gating findings
  1  findings — PT800/PT801/PT802 not covered by the allowlist
  2  internal error — the linter itself failed (never conflate a
     linter crash with a lint finding)

See docs/ANALYSIS.md for the code table and the static-model notes.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paddle_tpu.analysis.concurrency import (analyze_package,  # noqa: E402
                                             analyze_paths)

# Findings the gate accepts, with the reason on record. Matched on
# (code, key) where key is the stable finding key: the PT801 key is
# ``<function qualname>+<blocking call>``, the PT802 key is
# ``<Class>.<attr>`` — both independent of line numbers.
ALLOWLIST = {
    ("PT801", "paddle_tpu.parallel.compiled_program.CompiledProgram."
              "_get_compiled+time.sleep"):
        "watchdog_section's interrupt-absorption path: after the deadline "
        "already expired, up to 4 x 20 ms sleeps absorb a pending "
        "watchdog interrupt so it lands here and not in user code — "
        "bounded, cold-path-only, and the lock must stay held so the "
        "interrupt cannot hit a half-updated cache entry",
    ("PT801", "paddle_tpu.executor.Executor._ensure_executable"
              "+time.sleep"):
        "transitive face of the _ensure_executable_locked entries below "
        "(the with-_aot_lock caller)",
    ("PT801", "paddle_tpu.executor.Executor._ensure_executable_locked"
              "+time.sleep"):
        "call_with_retry's exponential backoff between transient compile "
        "faults runs under the per-step _aot_lock BY DESIGN: the lock is "
        "what makes the compile happen once — contending threads need "
        "this step's executable and cannot progress until it exists, so "
        "releasing the lock to sleep would only let them re-fail the "
        "same compile (thundering herd)",
    ("PT801", "paddle_tpu.executor.Executor._ensure_executable_locked"
              ".<locals>._build+time.sleep"):
        "watchdog_section's bounded 4 x 20 ms interrupt-absorption path "
        "on the already-expired cold path (same pattern as the "
        "CompiledProgram._get_compiled entry), reached inside the "
        "compile-once _aot_lock region for the reason above",
    ("PT801", "paddle_tpu.serving.engine.ServingEngine._admit_locked"
              "+time.sleep"):
        "the admission fault_point ('overload') reaches FaultPlan._perform, "
        "whose 'hang' action sleeps in a loop ON PURPOSE: the fault "
        "simulates a stuck thread wherever the probe sits, engine lock "
        "included — the watchdog/chaos harness is what detects and "
        "recovers it; fires only under an explicit FLAGS_fault_plan",
    ("PT801", "paddle_tpu.serving.engine.ServingEngine._admit_and_enqueue"
              "+time.sleep"):
        "transitive face of the _admit_locked entry above (the "
        "with-_lock caller of the admission fault_point)",
    ("PT802", "ServingEngine._acct"):
        "_settle_error's locked= flag protocol: the locked=True branch is "
        "only reachable from callers already inside _lock (enforced by "
        "the call sites; the dispatch thread owns the other branch), so "
        "every _acct mutation is lock-serialized even though one access "
        "site is lexically outside a with-block",
    ("PT802", "ServingEngine._dispatched"):
        "same locked= flag protocol as ServingEngine._acct: the lexically "
        "unguarded write runs only on the locked=True path whose callers "
        "hold _lock",
    ("PT802", "ServingEngine._breakers"):
        "single-writer dict: only the dispatch thread creates/advances "
        "breaker entries, and each mutation happens under _lock so "
        "health() can snapshot a consistent view; the lexically unguarded "
        "sites are dispatch-thread reads of its own writes",
    ("PT802", "ServingEngine._quarantine"):
        "documented racy fast-path read (engine.py admission): a stale "
        "read only delays quarantine by one request; the race is closed "
        "by the authoritative re-check under _lock in _admit_locked",
    ("PT802", "FleetRouter.replicas"):
        "copy-on-write list (documented on the attribute): mutators "
        "replace the whole list under _lock, readers snapshot the "
        "reference — the unguarded reads are the design",
}

# every PT800-family finding gates unless allowlisted
GATING_CODES = ("PT800", "PT801", "PT802")

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "..",
                           "tests", "fixtures", "concurrency")


def _diag_dict(d) -> dict:
    return {"code": d.code, "severity": d.severity, "key": d.op_type,
            "message": d.message, "site": d.site}


def _lint(name: str, report, allowlist: dict, json_report: dict) -> bool:
    gating, allow_hits, findings = [], [], []
    for d in report.diagnostics:
        findings.append(d)
        if d.code not in GATING_CODES:
            continue
        reason = allowlist.get((d.code, d.op_type or ""), "")
        if reason:
            allow_hits.append((d, reason))
        else:
            gating.append(d)
    cycles = sum(d.code == "PT800" for d in findings)
    status = "FAIL" if gating else "ok"
    print(f"[{status}] {name}: {len(report.modules)} modules, "
          f"{report.functions} functions, {len(report.locks)} locks, "
          f"{len(report.edges)} lock-order edges, {cycles} PT800, "
          f"{len(findings)} finding(s), {len(allow_hits)} allowlisted")
    for d in gating:
        print(f"  {d.code} [{d.severity}] {d.site}: {d.message}")
    summary = report.to_dict()
    summary.pop("diagnostics")       # carried (keyed) in "findings" below
    json_report["targets"].append({
        "name": name,
        "status": "fail" if gating else "ok",
        "summary": summary,
        "findings": [_diag_dict(d) for d in findings],
        "gating": [_diag_dict(d) for d in gating],
        "allowlisted": [dict(_diag_dict(d), reason=r)
                        for d, r in allow_hits],
    })
    if gating:
        print(f"concurrency gate -> FAIL ({name}: {len(gating)} "
              f"non-allowlisted finding(s))")
    return not gating


def _negative_control(json_report: dict) -> int:
    """Fixtures must trip all three codes with the allowlist OFF."""
    paths = sorted(os.path.join(FIXTURE_DIR, f)
                   for f in os.listdir(FIXTURE_DIR) if f.endswith(".py"))
    report = analyze_paths(paths, root=FIXTURE_DIR)
    ok = _lint("negative-control(fixtures)", report, {}, json_report)
    tripped = {d.code for d in report.diagnostics}
    missing = [c for c in GATING_CODES if c not in tripped]
    if missing:
        # a control that cannot trip every family is a broken control,
        # not a gate failure — exit 2 so CI's "-> FAIL" grep flags it
        print(f"negative control did NOT produce {', '.join(missing)} "
              f"on the fixtures — the linter lost coverage", file=sys.stderr)
        return 2
    if ok:
        print("negative control found nothing gating on intentionally "
              "broken fixtures", file=sys.stderr)
        return 0   # CI inverts the exit status: 0 here fails the build
    return 1


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("files", nargs="*",
                    help="explicit .py files (default: the whole "
                         "paddle_tpu package)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable report here "
                         "(ci_concurrency_report.json)")
    ap.add_argument("--negative-control", action="store_true",
                    help="lint the broken fixtures with an empty "
                         "allowlist; must FAIL")
    args = ap.parse_args(argv)

    json_report = {
        "targets": [],
        "allowlist": [{"code": c, "key": k, "reason": r}
                      for (c, k), r in sorted(ALLOWLIST.items())],
    }
    if args.negative_control:
        rc = _negative_control(json_report)
        json_report["status"] = "negative-control"
        code = rc
    else:
        if args.files:
            ok = _lint("files", analyze_paths(args.files), ALLOWLIST,
                       json_report)
        else:
            ok = _lint("paddle_tpu", analyze_package(), ALLOWLIST,
                       json_report)
        json_report["status"] = "ok" if ok else "fail"
        code = 0 if ok else 1
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(json_report, f, indent=2, sort_keys=True)
        print(f"report -> {args.json}")
    return code


def main(argv=None) -> int:
    """Stable CI exit codes: 0 clean, 1 findings, 2 internal error."""
    try:
        return run(argv)
    except SystemExit as e:  # argparse error: also an internal error
        code = e.code if isinstance(e.code, int) else 2
        return code if code in (0, 1) else 2
    except Exception:
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
