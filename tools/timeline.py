#!/usr/bin/env python
"""Convert a profiler dump to Chrome tracing JSON (chrome://tracing /
Perfetto).

Reference: tools/timeline.py:21-25 — there the input is the C++ profiler's
profiler.proto; here it is the host_events.json span dump that
``fluid.profiler.profiler(profile_path=...)`` writes next to the XPlane
trace (the XPlane dump itself opens directly in TensorBoard/Perfetto; this
tool covers the host-side RecordEvent timeline).

Usage:
    python tools/timeline.py --profile_path /tmp/profile \
                             --timeline_path /tmp/timeline.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def convert(profile_path: str, timeline_path: str) -> int:
    src = profile_path
    if os.path.isdir(src):
        src = os.path.join(src, "host_events.json")
    if not os.path.exists(src):
        print(f"no host_events.json under {profile_path} — run under "
              f"fluid.profiler.profiler(profile_path=...)", file=sys.stderr)
        return 1
    with open(src) as f:
        spans = json.load(f)
    # an empty profile (no RecordEvent fired while tracing) is still a
    # valid run: emit a well-formed empty trace rather than NameError-ing
    # on the unbound base timestamp
    base = min(s["t0"] for s in spans) if spans else 0.0
    events = [{
        "name": s["name"],
        "ph": "X",
        "ts": (s["t0"] - base) * 1e6,   # microseconds, chrome convention
        "dur": (s["t1"] - s["t0"]) * 1e6,
        "pid": 0,
        "tid": s.get("tid", 0),
        "cat": "host",
    } for s in spans]
    with open(timeline_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    print(f"wrote {len(events)} events to {timeline_path}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", required=True)
    ap.add_argument("--timeline_path", required=True)
    args = ap.parse_args(argv)
    return convert(args.profile_path, args.timeline_path)


if __name__ == "__main__":
    sys.exit(main())
