#!/usr/bin/env python
"""Convert profiler/trace dumps to ONE Chrome tracing JSON
(chrome://tracing / Perfetto).

Reference: tools/timeline.py:21-25 — there the input is the C++ profiler's
profiler.proto; here it is two host-side sources sharing one wall-clock
anchor:

* ``host_events.json`` — the ``fluid.profiler.profiler(profile_path=...)``
  RecordEvent span dump (next to the XPlane trace, which itself opens
  directly in TensorBoard/Perfetto). Each span carries an ``epoch``
  anchor recorded at ``__enter__`` (spans written before that field
  existed fall back to a relative timeline).
* a ``paddle_tpu.trace`` span dump — JSONL from ``trace.export_jsonl``
  (``--trace_path``). Spans carry ``t0_epoch`` natively.

Both map onto the epoch clock, so a serving request's trace spans line up
against the executor's RecordEvent intervals in one merged timeline:
profiler rows under pid 0, trace spans under pid 1 (grouped per thread),
with trace/span ids in each event's ``args``.

Usage:
    python tools/timeline.py --profile_path /tmp/profile \
                             --timeline_path /tmp/timeline.json
    python tools/timeline.py --trace_path spans.jsonl \
                             --timeline_path /tmp/timeline.json
    python tools/timeline.py --profile_path /tmp/profile \
                             --trace_path spans.jsonl \
                             --timeline_path /tmp/merged.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _load_host_spans(profile_path: str) -> Optional[list]:
    src = profile_path
    if os.path.isdir(src):
        src = os.path.join(src, "host_events.json")
    if not os.path.exists(src):
        print(f"no host_events.json under {profile_path} — run under "
              f"fluid.profiler.profiler(profile_path=...)", file=sys.stderr)
        return None
    with open(src) as f:
        return json.load(f)


def _load_trace_spans(trace_path: str) -> Optional[list]:
    if not os.path.exists(trace_path):
        print(f"no trace span dump at {trace_path} — write one with "
              f"paddle_tpu.trace.export_jsonl(path)", file=sys.stderr)
        return None
    spans = []
    with open(trace_path) as f:
        for line in f:
            line = line.strip()
            if line:
                spans.append(json.loads(line))
    return spans


def convert(profile_path: Optional[str], timeline_path: str,
            trace_path: Optional[str] = None) -> int:
    host = _load_host_spans(profile_path) if profile_path else []
    if host is None:
        return 1
    tspans = _load_trace_spans(trace_path) if trace_path else []
    if tspans is None:
        return 1

    events: List[dict] = []
    # ---- profiler host events (pid 0) ---------------------------------
    # pre-anchor dumps (no 'epoch' field) only carry perf_counter deltas;
    # those get a relative timeline exactly as before — an empty profile
    # is still a valid run (the PR 3 fix), so base defaults to 0.0
    have_epoch = bool(host) and all("epoch" in s for s in host)
    if have_epoch:
        def host_ts(s):
            return s["epoch"] * 1e6
    else:
        base = min((s["t0"] for s in host), default=0.0)

        def host_ts(s):
            return (s["t0"] - base) * 1e6
    for s in host:
        events.append({
            "name": s["name"],
            "ph": "X",
            "ts": host_ts(s),
            "dur": (s["t1"] - s["t0"]) * 1e6,
            "pid": 0,
            "tid": s.get("tid", 0),
            "cat": "host",
        })
    # ---- trace spans (pid 1), same epoch clock ------------------------
    # NOTE: this mapping mirrors paddle_tpu.trace.to_chrome_events over
    # the to_dict() span shape — kept as a stdlib copy ON PURPOSE so
    # converting a JSON dump never imports the framework (and jax).
    # Change the event schema in BOTH places.
    if tspans and host and not have_epoch:
        print("warning: host_events.json predates the epoch anchor — "
              "profiler rows are on a RELATIVE clock and will not line "
              "up with the trace spans", file=sys.stderr)
    for s in tspans:
        if s.get("duration_s") is None:
            continue
        args = {"trace_id": s.get("trace_id", ""),
                "span_id": s.get("span_id", ""),
                "status": s.get("status", "")}
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        if s.get("error"):
            args["error"] = s["error"]
        args.update(s.get("attrs") or {})
        events.append({
            "name": s["name"],
            "ph": "X",
            "ts": s["t0_epoch"] * 1e6,
            "dur": s["duration_s"] * 1e6,
            "pid": 1,
            "tid": s.get("thread", 0),
            "cat": "trace",
            "args": args,
        })
    with open(timeline_path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    print(f"wrote {len(events)} events to {timeline_path} "
          f"({len(host)} profiler, "
          f"{len(events) - len(host)} trace)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path",
                    help="profiler dump dir (host_events.json)")
    ap.add_argument("--trace_path",
                    help="paddle_tpu.trace JSONL span dump to merge")
    ap.add_argument("--timeline_path", required=True)
    args = ap.parse_args(argv)
    if not args.profile_path and not args.trace_path:
        ap.error("need --profile_path and/or --trace_path")
    return convert(args.profile_path, args.timeline_path,
                   trace_path=args.trace_path)


if __name__ == "__main__":
    sys.exit(main())
