#!/usr/bin/env python
"""Static program linter CLI (CI face of paddle_tpu.analysis).

Usage:
  python tools/lint_program.py prog.json [prog2.json ...]
      Lint serialized programs (Program.to_json / save_inference_model's
      __model__ file).
  python tools/lint_program.py --builtin
      Build the built-in model suite (the tests/test_book.py programs:
      fit-a-line, recognize-digits MLP, word2vec) with backward + optimizer
      and lint main+startup of each — the CI gate that keeps the layer
      stack, backward pass and registry schemas conformant.

Exit status: 1 when any error-severity diagnostic is found (warnings and
infos are printed but do not gate). See docs/ANALYSIS.md for the code table.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.analysis import Severity, format_diagnostics, verify_program  # noqa: E402


def _builtin_programs():
    """(name, program, fetch_names) triples mirroring tests/test_book.py."""
    import paddle_tpu.unique_name as un

    out = []
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.02).minimize(loss)
        out.append(("fit_a_line/main", main, [loss.name]))
        out.append(("fit_a_line/startup", startup, []))

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[784], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, 64, act="relu")
            logits = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(logits, label)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        out.append(("recognize_digits/main", main, [loss.name, acc.name]))
        out.append(("recognize_digits/startup", startup, []))
        out.append(("recognize_digits/test_clone", test_prog,
                    [acc.name, logits.name]))

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w1 = fluid.layers.data("w1", shape=[1], dtype="int64")
            w2 = fluid.layers.data("w2", shape=[1], dtype="int64")
            nxt = fluid.layers.data("next", shape=[1], dtype="int64")
            embs = [fluid.layers.embedding(
                w, size=[1000, 32],
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in (w1, w2)]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, 64, act="sigmoid")
            logits = fluid.layers.fc(hidden, 1000)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, nxt))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        out.append(("word2vec/main", main, [loss.name]))
        out.append(("word2vec/startup", startup, []))
    return out


def _lint(name, program, fetch_names, show_info: bool) -> bool:
    diags = verify_program(program, fetch_names=fetch_names)
    shown = [d for d in diags
             if show_info or d.severity != Severity.INFO]
    errors = [d for d in diags if d.severity == Severity.ERROR]
    n_ops = sum(len(b.ops) for b in program.blocks)
    status = "FAIL" if errors else "ok"
    print(f"[{status}] {name}: {n_ops} ops, "
          f"{len(errors)} error(s), "
          f"{sum(d.severity == Severity.WARNING for d in diags)} warning(s),"
          f" {sum(d.severity == Severity.INFO for d in diags)} info(s)")
    if shown:
        print(format_diagnostics(shown))
    return not errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("programs", nargs="*",
                    help="serialized Program JSON files")
    ap.add_argument("--builtin", action="store_true",
                    help="lint the built-in model suite instead of files")
    ap.add_argument("--show-info", action="store_true",
                    help="also print info-severity findings (dead outputs)")
    args = ap.parse_args(argv)
    if not args.builtin and not args.programs:
        ap.error("pass program JSON files or --builtin")

    ok = True
    if args.builtin:
        for name, prog, fetches in _builtin_programs():
            ok = _lint(name, prog, fetches, args.show_info) and ok
    for path in args.programs:
        try:
            with open(path, "r", encoding="utf-8") as f:
                prog = fluid.Program.from_json(f.read())
        except Exception as e:  # malformed beyond parsing: still a lint fail
            print(f"[FAIL] {path}: cannot load program: "
                  f"{type(e).__name__}: {e}")
            ok = False
            continue
        ok = _lint(path, prog, [], args.show_info) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
