#!/usr/bin/env python
"""Static program linter CLI (CI face of paddle_tpu.analysis).

Drives the FULL pass-manager pipeline (the five verifier passes plus the
PT700s dtype/shape-consistency, PT710s donation-race and PT720s dead-code
families) over serialized programs, the built-in test_book suite, or the
whole model zoo.

Usage:
  python tools/lint_program.py prog.json [prog2.json ...]
      Lint serialized programs (Program.to_json / save_inference_model's
      __model__ file).
  python tools/lint_program.py --builtin
      The test_book.py program builders (fit-a-line, recognize-digits MLP,
      word2vec) with backward + optimizer — main+startup of each.
  python tools/lint_program.py --zoo
      --builtin plus every paddle_tpu.models builder (MLP, ResNet, BERT,
      DeepFM, seq2seq) linted against its full declared fetch surface —
      the ci/run_ci.sh gate.
  --json PATH     machine-readable report (the ci_lint_report.json CI
                  artifact): per-program findings, allowlist hits, pass
                  timings from the monitor registry.
  --passes a,b,c  restrict the pipeline (default: every analysis pass).
  --show-info     also print info-severity findings.

Exit status (stable, for CI):
  0  clean — no gating findings
  1  findings — error-severity diagnostics, or dead-code findings
     (PT720/PT721/PT722) not covered by the allowlist below
  2  internal error — the linter itself failed (never conflate a linter
     crash with a lint finding)

See docs/ANALYSIS.md for the code table and the pass table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import traceback
from typing import Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.analysis import (ALL_ANALYSIS_PASSES, Severity,  # noqa: E402
                                 default_pass_manager, format_diagnostics)

# Findings the zoo gate accepts, with the reason on record (the satellite
# contract: every dead-code finding is either fixed or allowlisted here).
# Matched on (code, op_type).
ALLOWLIST = {
    ("PT721", "accuracy"):
        "accuracy's Correct/Total outputs are reference-schema state "
        "slots; the layers.accuracy API surfaces only the Accuracy scalar",
    ("PT721", "reshape2"):
        "XShape is the grad-side shape echo the reference schema requires; "
        "inference/forward-only consumers never read it",
    ("PT721", "transpose2"):
        "XShape grad-side shape echo (see reshape2)",
    ("PT721", "squeeze2"):
        "XShape grad-side shape echo (see reshape2)",
    ("PT721", "unsqueeze2"):
        "XShape grad-side shape echo (see reshape2)",
    ("PT721", "flatten2"):
        "XShape grad-side shape echo (see reshape2)",
    ("PT721", "recurrent_grad"):
        "recurrent_grad emits an @GRAD slot for every forward input; the "
        "fill_constant_batch_size_like initial-state grad has no consumer "
        "by construction",
    ("PT721", "dropout"):
        "the Mask output is read only by dropout_grad; forward-only "
        "clones keep the slot per the reference schema",
    ("PT721", "softmax_with_cross_entropy"):
        "the Softmax output is read only by the grad op; forward-only "
        "clones keep the slot per the reference schema",
    ("PT721", "layer_norm"):
        "Mean/Variance are grad-side state slots read only by "
        "layer_norm_grad; inference-only programs (the GPT generative "
        "phases) never read them",
    ("PT743", ""):
        "prediction/eval fetch surfaces materialize per-example outputs; "
        "the fetch all-gather is the intended result delivery and is "
        "priced by the collective cost model, not a layout bug",
}

# dead-code findings gate the zoo unless allowlisted, and so do the
# sharding_check warnings under the dp=8 ZeRO assignment (the PT73x-clean
# contract — errors PT730-PT733 gate via severity on their own);
# everything else gates only at error severity
GATING_CODES = ("PT720", "PT721", "PT722",
                "PT734", "PT735", "PT736", "PT737", "PT738", "PT739",
                "PT741", "PT742", "PT743")

# the mesh + layout every *training* zoo program is linted under (the
# sharding_check pass input). The GPT generative phases are serving slot
# programs with a fixed tiny batch — a dp batch split does not apply, so
# they lint without a mesh (sharding_check no-ops).
ZOO_MESH = {"dp": 8}


def _sharding_options(name: str) -> dict:
    if name.startswith("zoo/gpt"):
        return {}
    return {"mesh": dict(ZOO_MESH), "zero": True}


def _builtin_programs():
    """(name, program, fetch_names) triples mirroring tests/test_book.py."""
    import paddle_tpu.unique_name as un

    out = []
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.02).minimize(loss)
        out.append(("fit_a_line/main", main, [loss.name]))
        out.append(("fit_a_line/startup", startup, []))

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[784], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, 64, act="relu")
            logits = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(logits, label)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        out.append(("recognize_digits/main", main, [loss.name, acc.name]))
        out.append(("recognize_digits/startup", startup, []))
        # the eval clone's full fetch surface includes the (un-optimized)
        # loss — fetching only acc would misreport the loss chain as dead
        out.append(("recognize_digits/test_clone", test_prog,
                    [loss.name, acc.name, logits.name]))

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w1 = fluid.layers.data("w1", shape=[1], dtype="int64")
            w2 = fluid.layers.data("w2", shape=[1], dtype="int64")
            nxt = fluid.layers.data("next", shape=[1], dtype="int64")
            embs = [fluid.layers.embedding(
                w, size=[1000, 32],
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in (w1, w2)]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, 64, act="sigmoid")
            logits = fluid.layers.fc(hidden, 1000)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, nxt))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
        out.append(("word2vec/main", main, [loss.name]))
        out.append(("word2vec/startup", startup, []))
    return out


def _zoo_programs():
    """The paddle_tpu.models builders, each against its full declared
    fetch surface (loss + metrics/predictions) — fetching less would
    misreport the metric heads as dead code."""
    import paddle_tpu.unique_name as un
    from paddle_tpu.models import (BertConfig, build_bert_pretrain,
                                   build_deepfm, build_mnist_mlp,
                                   build_resnet, build_seq2seq_train)

    out = []
    with un.guard():
        m = build_mnist_mlp()
        out.append(("zoo/mnist_mlp/main", m["main"],
                    [m["loss"].name, m["acc"].name]))
        out.append(("zoo/mnist_mlp/startup", m["startup"], []))
    with un.guard():
        m = build_resnet(depth=18, class_num=10, image_shape=(3, 32, 32))
        out.append(("zoo/resnet18/main", m["main"],
                    [m["loss"].name, m["acc"].name]))
        out.append(("zoo/resnet18/startup", m["startup"], []))
    with un.guard():
        m = build_bert_pretrain(BertConfig.tiny(), seq_len=32)
        out.append(("zoo/bert_tiny/main", m["main"],
                    [m["loss"].name, m["mlm_loss"].name,
                     m["nsp_loss"].name]))
        out.append(("zoo/bert_tiny/startup", m["startup"], []))
    with un.guard():
        m = build_deepfm()
        out.append(("zoo/deepfm/main", m["main"],
                    [m["loss"].name, m["pred"].name]))
        out.append(("zoo/deepfm/startup", m["startup"], []))
    with un.guard():
        m = build_seq2seq_train(src_vocab=50, tgt_vocab=50)
        out.append(("zoo/seq2seq/main", m["main"], [m["loss"].name]))
        out.append(("zoo/seq2seq/startup", m["startup"], []))
    with un.guard():
        from paddle_tpu.models import GptConfig, build_gpt_generative

        # both generative phases, incl. the PT710s donation-race pass
        # over the donated KV caches (the ISSUE 11 satellite contract)
        m = build_gpt_generative(GptConfig.tiny(), batch_slots=2,
                                 max_seq=32, page_size=8,
                                 prompt_buckets=(16,))
        pf = m["prefill"][16]
        out.append(("zoo/gpt_tiny/prefill", pf["main"],
                    [pf["first_token"].name]))
        out.append(("zoo/gpt_tiny/decode", m["decode"]["main"],
                    [m["decode"]["next_token"].name]))
        out.append(("zoo/gpt_tiny/startup", m["startup"], []))
    return out


def _allowlisted(d) -> str:
    """The allowlist reason covering diagnostic ``d``, or ''."""
    return ALLOWLIST.get((d.code, d.op_type or ""), "")


def _lint(name, program, fetch_names, passes, show_info: bool,
          report: dict, gate_dead_code: bool = True,
          options: Optional[dict] = None) -> bool:
    mgr = default_pass_manager()
    result = mgr.run_pipeline(program, passes, fetch_names=fetch_names,
                              verify="none", options=options or {})
    diags = result.diagnostics
    errors = [d for d in diags if d.severity == Severity.ERROR]
    gating = list(errors)
    allow_hits = []
    for d in diags:
        if (gate_dead_code and d.code in GATING_CODES
                and d.severity != Severity.ERROR):
            reason = _allowlisted(d)
            if reason:
                allow_hits.append((d, reason))
            else:
                gating.append(d)
    n_ops = sum(len(b.ops) for b in program.blocks)
    n_warn = sum(d.severity == Severity.WARNING for d in diags)
    n_info = sum(d.severity == Severity.INFO for d in diags)
    status = "FAIL" if gating else "ok"
    print(f"[{status}] {name}: {n_ops} ops, {len(errors)} error(s), "
          f"{n_warn} warning(s), {n_info} info(s), "
          f"{len(allow_hits)} allowlisted")
    shown = [d for d in diags
             if show_info or d.severity != Severity.INFO or d in gating]
    if shown:
        print(format_diagnostics(shown))
    report["programs"].append({
        "name": name,
        "ops": n_ops,
        "status": status.lower() if status == "FAIL" else "ok",
        "errors": len(errors),
        "warnings": n_warn,
        "infos": n_info,
        "gating": [_diag_dict(d) for d in gating],
        "allowlisted": [dict(_diag_dict(d), reason=r)
                        for d, r in allow_hits],
        "findings": [_diag_dict(d) for d in diags],
    })
    return not gating


def _diag_dict(d) -> dict:
    return {"code": d.code, "severity": d.severity, "message": d.message,
            "block": d.block_idx, "op": d.op_idx, "op_type": d.op_type,
            "site": d.site}


def _pass_timings() -> dict:
    """Per-pass run counts and wall time from the monitor registry (the
    acceptance-visible face of the pass-manager refactor)."""
    from paddle_tpu import monitor

    snap = monitor.get_registry().to_dict()
    out = {}
    for metric in ("pass_runs_total", "pass_duration_seconds"):
        fam = snap.get(metric)
        if fam:
            out[metric] = fam["values"]
    return out


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("programs", nargs="*",
                    help="serialized Program JSON files")
    ap.add_argument("--builtin", action="store_true",
                    help="lint the built-in test_book model suite")
    ap.add_argument("--zoo", action="store_true",
                    help="lint --builtin plus every paddle_tpu.models "
                         "builder (the CI gate)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the machine-readable report here "
                         "(ci_lint_report.json)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass names (default: the full "
                         "analysis pipeline)")
    ap.add_argument("--show-info", action="store_true",
                    help="also print info-severity findings")
    args = ap.parse_args(argv)
    if not args.builtin and not args.zoo and not args.programs:
        ap.error("pass program JSON files, --builtin or --zoo")

    passes = tuple(p.strip() for p in args.passes.split(",")
                   if p.strip()) if args.passes else ALL_ANALYSIS_PASSES
    report = {"passes": list(passes), "zoo_mesh": dict(ZOO_MESH),
              "programs": [],
              "allowlist": [{"code": c, "op_type": t, "reason": r}
                            for (c, t), r in sorted(ALLOWLIST.items())]}
    ok = True
    suites = []
    if args.builtin or args.zoo:
        suites.append(_builtin_programs())
    if args.zoo:
        suites.append(_zoo_programs())
    for suite in suites:
        for name, prog, fetches in suite:
            ok = _lint(name, prog, fetches, passes, args.show_info,
                       report, options=_sharding_options(name)) and ok
    for path in args.programs:
        try:
            with open(path, "r", encoding="utf-8") as f:
                prog = fluid.Program.from_json(f.read())
        except Exception as e:  # malformed beyond parsing: still a lint fail
            print(f"[FAIL] {path}: cannot load program: "
                  f"{type(e).__name__}: {e}")
            report["programs"].append({"name": path, "status": "fail",
                                       "load_error": str(e)})
            ok = False
            continue
        # file inputs carry no fetch surface: a dead-code verdict would be
        # guesswork, so files gate on error severity only
        ok = _lint(path, prog, [], passes, args.show_info, report,
                   gate_dead_code=False) and ok

    report["status"] = "ok" if ok else "fail"
    report["pass_timings"] = _pass_timings()
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"report -> {args.json}")
    return 0 if ok else 1


def main(argv=None) -> int:
    """Stable CI exit codes: 0 clean, 1 findings, 2 internal error."""
    try:
        return run(argv)
    except SystemExit as e:  # argparse error: also an internal error
        code = e.code if isinstance(e.code, int) else 2
        return code if code in (0, 1) else 2
    except Exception:
        traceback.print_exc()
        return 2


if __name__ == "__main__":
    sys.exit(main())
