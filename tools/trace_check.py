#!/usr/bin/env python
"""End-to-end tracing + cost-model CI gate (``paddle_tpu.trace``).

Runs a traced serving burst and a traced 3-step train, then chaos legs,
and proves the observability contract (docs/OBSERVABILITY.md "Tracing"):

* **complete traces** — every submitted request appears in EXACTLY ONE
  complete trace: one ``serving.request`` root per trace, no orphan
  spans (every parent id resolves inside the trace), every span closed,
  and the root closes at-or-after its children (parent closes after
  children); same for the trainer's per-step traces.
* **flight recorder** — an injected ``batch_dispatch`` fault and a
  watchdog-killed hang each produce an incident whose span dump contains
  the failed request's full chain (submit → enqueue → batch → dispatch
  → typed outcome). The ``--negative-control`` run disables the flight
  recorder (``FLAGS_flight_recorder_size=0``) and the gate must FAIL —
  proving the dump is what carries the fault context.
* **overhead guard** — with ``FLAGS_trace=0`` the span hot path must
  cost near-zero (no allocation; bounded ns/span measured here).
* **cost model** — per-program FLOPs from the ``cost_model`` pass agree
  with the hand-derived analytic counts for ResNet-50 and BERT-base
  within 10% (docs/PERF_NOTES.md "Cost model"), and the measured tiny
  legs report real ``executor_mfu`` / ``serving_bucket_mfu`` gauges in
  the ``ci_trace_report.json`` artifact.

Usage:
  python tools/trace_check.py --check --json ci_trace_report.json
  python tools/trace_check.py --check --negative-control   # must exit 1
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu import monitor, serving, trace  # noqa: E402
from paddle_tpu.resilience import fault_plan_guard  # noqa: E402


# ---------------------------------------------------------------------------
# probes
# ---------------------------------------------------------------------------

def _mlp_engine(config=None):
    import paddle_tpu.layers as layers
    import paddle_tpu.unique_name as un
    from paddle_tpu.framework import Program, program_guard

    with un.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            h = layers.fc(x, size=16, act="relu")
            y = layers.fc(h, size=4)
        infer = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
    eng = serving.ServingEngine(
        infer, feed_names=["x"], fetch_list=[y.name], scope=scope,
        executor=exe,
        config=config or serving.ServingConfig(max_batch=4, queue_depth=64))

    def feed(rows=1, seed=0):
        rng = np.random.RandomState(seed)
        return {"x": rng.rand(rows, 8).astype(np.float32)}

    return eng, feed


def _verify_trace(trace_id: str) -> dict:
    """Structural checks over one finished trace pulled from the
    collector. Returns per-check booleans."""
    tree = trace.trace_tree(trace_id)
    ids = {s.span_id for s in tree}
    roots = [s for s in tree if s.parent_id is None]
    closed = all(s.duration_s is not None for s in tree)
    no_orphans = all(s.parent_id is None or s.parent_id in ids
                     for s in tree)
    parent_after_children = True
    by_id = {s.span_id: s for s in tree}
    for s in tree:
        p = by_id.get(s.parent_id) if s.parent_id else None
        if p is None or p.duration_s is None or s.duration_s is None:
            continue
        if (p.t0_mono + p.duration_s) + 1e-6 < (s.t0_mono + s.duration_s):
            parent_after_children = False
    return {"spans": len(tree), "one_root": len(roots) == 1,
            "all_closed": closed, "no_orphans": no_orphans,
            "parent_closes_after_children": parent_after_children,
            "root_has_outcome": bool(roots)
            and roots[0].attrs.get("outcome") is not None}


def leg_serving_burst(n_requests=24, n_threads=3) -> dict:
    """Traced burst: every request -> exactly one complete trace."""
    trace.clear()
    eng, feed = _mlp_engine()
    futs, lock = [], threading.Lock()
    with eng:
        def submitter(tid):
            for i in range(tid, n_requests, n_threads):
                f = eng.submit(feed(rows=1 + i % 2, seed=i))
                with lock:
                    futs.append(f)
        ts = [threading.Thread(target=submitter, args=(t,))
              for t in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for f in futs:
            f.result(timeout=60)
    per_request = [_verify_trace(f.trace_id) for f in futs]
    unique_traces = len({f.trace_id for f in futs})
    # the dispatch span proves submit-thread -> dispatch-thread
    # propagation: it lives on the dispatch thread under the submit
    # thread's root
    cross_thread = 0
    for f in futs:
        tree = trace.trace_tree(f.trace_id)
        root = next(s for s in tree if s.parent_id is None)
        cross_thread += any(s.name == "serving.dispatch"
                            and s.thread != root.thread for s in tree)
    acct = eng.accounting()
    checks = {
        "all_submitted": len(futs) == n_requests,
        "one_trace_per_request": unique_traces == n_requests,
        "every_trace_complete": all(
            all(v for k, v in pr.items() if k != "spans")
            for pr in per_request),
        "chain_depth": all(pr["spans"] >= 4 for pr in per_request),
        "cross_thread_parentage": cross_thread == n_requests,
        "accounting_carries_trace_ids": all(
            r["trace_id"] for r in acct["recent_outcomes"]),
        "exact_accounting": acct["exact"],
    }
    return {"name": "serving_burst", "ok": all(checks.values()),
            "checks": checks, "requests": n_requests,
            "example_trace": per_request[0] if per_request else None}


def leg_trainer_steps(tmp_dir: str, steps=3) -> dict:
    """Traced 3-step train: one complete root trace per step with data +
    executor children and a checkpoint child on the saving step."""
    import paddle_tpu.unique_name as un

    trace.clear()

    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(steps):
            yield [(rng.rand(4).astype(np.float32),
                    rng.rand(1).astype(np.float32)) for _ in range(8)]

    import tempfile

    # fresh dir per run: a stale serial from a previous gate run would
    # resume past the epoch and train zero steps
    ckpt = fluid.contrib.CheckpointConfig(
        tempfile.mkdtemp(prefix="trace_ckpt_", dir=tmp_dir),
        step_interval=steps)
    with un.guard():
        tr = fluid.contrib.Trainer(train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
        tr.train(num_epochs=1, event_handler=lambda ev: None,
                 reader=lambda: reader(), feed_order=["x", "y"])
    step_roots = [s for s in trace.spans()
                  if s.name == "trainer.step" and s.parent_id is None]
    verified = [_verify_trace(s.trace_id) for s in step_roots]
    has_children = []
    ckpt_spans = 0
    for s in step_roots:
        tree = trace.trace_tree(s.trace_id)
        names = {t.name for t in tree}
        has_children.append("trainer.data" in names
                            and "executor.run" in names)
        ckpt_spans += "trainer.checkpoint" in names
    checks = {
        "step_traces": len(step_roots) == steps,
        "every_trace_complete": bool(verified) and all(
            all(v for k, v in pr.items() if k != "spans")
            for pr in verified),
        "data_and_dispatch_children": all(has_children),
        "checkpoint_span_present": ckpt_spans >= 1,
    }
    return {"name": "trainer_steps", "ok": all(checks.values()),
            "checks": checks, "steps": steps}


def _find_chain(incident: dict, trace_id: str) -> set:
    return {d["name"] for d in incident["recent_spans"]
            if d["trace_id"] == trace_id}


def leg_batch_fault_flight() -> dict:
    """Injected batch_dispatch fault: the BatchFailed incident must ship
    the failed request's full span chain."""
    trace.clear()
    trace.clear_incidents()
    eng, feed = _mlp_engine()
    err = None
    with eng, fault_plan_guard("batch_dispatch:1:RuntimeError"):
        fut = eng.submit(feed(rows=1, seed=0))
        try:
            fut.result(timeout=60)
        except serving.BatchFailed as e:
            err = e
    incs = [i for i in trace.incidents() if i["kind"] == "batch_failed"]
    chain = _find_chain(incs[-1], fut.trace_id) if incs else set()
    want = {"serving.request", "serving.submit", "serving.enqueue",
            "serving.dispatch"}
    batch_in_dump = any(d["name"] == "serving.batch"
                        for d in incs[-1]["recent_spans"]) if incs else False
    root = [d for d in (incs[-1]["recent_spans"] if incs else ())
            if d["trace_id"] == fut.trace_id
            and d["name"] == "serving.request"]
    checks = {
        "batch_failed_typed": err is not None,
        "error_carries_trace_id": getattr(err, "trace_id", "")
        == fut.trace_id,
        "incident_recorded": bool(incs),
        "full_chain_in_dump": want <= chain,
        "batch_span_in_dump": batch_in_dump,
        "typed_outcome_in_dump": bool(root)
        and root[0]["attrs"].get("outcome") == "failed",
    }
    return {"name": "batch_fault_flight", "ok": all(checks.values()),
            "checks": checks,
            "dumped_chain": sorted(chain),
            "flight_recorder_enabled":
                incs[-1]["flight_recorder_enabled"] if incs else None}


def leg_watchdog_flight() -> dict:
    """A watchdog-killed hang must dump the flight recorder with the
    hung request's span chain."""
    trace.clear()
    trace.clear_incidents()
    wd0 = monitor.metric_value("watchdog_timeouts_total", 0.0,
                               section="step")
    eng, feed = _mlp_engine()
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    err = None
    try:
        with eng, fault_plan_guard("hang:@1:hang"):
            fut = eng.submit(feed(rows=1, seed=0))
            try:
                fut.result(timeout=60)
            except serving.BatchFailed as e:
                err = e
    finally:
        fluid.set_flags({"FLAGS_step_timeout_s": 0.0,
                         "FLAGS_watchdog_hard_exit": 1})
    wd = monitor.metric_value("watchdog_timeouts_total", 0.0,
                              section="step") - wd0
    incs = [i for i in trace.incidents()
            if i["kind"] == "watchdog_timeout"]
    # the request chain at expiry: submit/enqueue closed; the root +
    # dispatch close AFTER the typed failure, so the batch_failed
    # incident (also fired) carries the terminal chain
    chain_at_expiry = _find_chain(incs[-1], fut.trace_id) if incs else set()
    batch_incs = [i for i in trace.incidents()
                  if i["kind"] == "batch_failed"]
    final_chain = _find_chain(batch_incs[-1], fut.trace_id) \
        if batch_incs else set()
    want = {"serving.request", "serving.submit", "serving.enqueue",
            "serving.dispatch"}
    checks = {
        "watchdog_fired": wd >= 1,
        "hang_failed_typed": err is not None,
        "watchdog_incident_recorded": bool(incs),
        "expiry_dump_has_request_context": bool(chain_at_expiry),
        "terminal_dump_full_chain": want <= final_chain,
    }
    return {"name": "watchdog_flight", "ok": all(checks.values()),
            "checks": checks,
            "watchdog_timeouts": wd,
            "chain_at_expiry": sorted(chain_at_expiry),
            "terminal_chain": sorted(final_chain)}


def leg_overhead(n=200_000, budget_ns=3000) -> dict:
    """FLAGS_trace=0 span hot path: bounded ns/span, no allocation
    (identity singleton)."""
    fluid.set_flags({"FLAGS_trace": 0})
    assert not trace.enabled()
    spans = [trace.span("bench") for _ in range(4)]
    t0 = time.perf_counter()
    for _ in range(n):
        with trace.span("bench"):
            pass
    disabled_ns = (time.perf_counter() - t0) / n * 1e9
    fluid.set_flags({"FLAGS_trace": 1})
    t0 = time.perf_counter()
    for _ in range(n // 20):
        with trace.span("bench"):
            pass
    enabled_ns = (time.perf_counter() - t0) / (n // 20) * 1e9
    trace.clear()
    checks = {
        "no_allocation_when_disabled": all(s is trace.NOOP_SPAN
                                           for s in spans),
        "disabled_under_budget": disabled_ns < budget_ns,
    }
    return {"name": "overhead_guard", "ok": all(checks.values()),
            "checks": checks,
            "disabled_ns_per_span": round(disabled_ns),
            "enabled_ns_per_span": round(enabled_ns),
            "budget_ns": budget_ns}


def leg_cost_model() -> dict:
    """Cost-model FLOPs vs hand-derived analytic counts (the
    docs/PERF_NOTES.md numbers), ±10%."""
    import paddle_tpu.unique_name as un
    from paddle_tpu.analysis.cost_model import estimate_cost
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain
    from paddle_tpu.models.resnet import build_resnet

    results = {}
    # ResNet-50 @224 train: analytic 2/MAC convention — fwd 2*4.089
    # GMAC ≈ 8.18 GF/img, backward ≈ 2x fwd => ~24.5 GF/img
    with un.guard():
        rn = build_resnet(depth=50, class_num=1000, amp=True)
    rep = estimate_cost(rn["main"], batch_size=128)
    per_img = rep.flops_total / 128
    results["resnet50_train"] = {
        "cost_model_gflops_per_img": round(per_img / 1e9, 2),
        "analytic_gflops_per_img": 24.55,
        "ratio": round(per_img / 24.55e9, 3)}
    with un.guard():
        rn_i = build_resnet(depth=50, class_num=1000,
                            build_optimizer=False)
    rep_i = estimate_cost(rn_i["main"].clone(for_test=True),
                          batch_size=128)
    per_img_i = rep_i.flops_total / 128
    results["resnet50_infer"] = {
        "cost_model_gflops_per_img": round(per_img_i / 1e9, 2),
        "analytic_gflops_per_img": 8.18,
        "ratio": round(per_img_i / 8.18e9, 3)}
    # BERT-base pretrain: 6ND + the attention-score term (bench.py's
    # analytic formula)
    cfg = BertConfig.base()
    B, S = 8, 128
    with un.guard():
        bm = build_bert_pretrain(cfg, seq_len=S, amp=True)
    rep_b = estimate_cost(bm["main"], batch_size=B)
    analytic_b = 6 * 110e6 * B * S \
        + 3 * 4 * B * S * S * cfg.hidden_size * cfg.num_layers
    results["bert_base_train"] = {
        "cost_model_gflops": round(rep_b.flops_total / 1e9, 1),
        "analytic_gflops": round(analytic_b / 1e9, 1),
        "ratio": round(rep_b.flops_total / analytic_b, 3)}
    checks = {f"{k}_within_10pct": abs(v["ratio"] - 1.0) <= 0.10
              for k, v in results.items()}
    # intensity sanity: training must move more FLOPs/byte than zero
    checks["arithmetic_intensity_positive"] = rep.flops_per_byte > 0
    return {"name": "cost_model", "ok": all(checks.values()),
            "checks": checks, "results": results}


def _mfu_figures() -> dict:
    """The measured MFU gauges the traced legs produced (tiny probes on
    CPU — the figures prove the plumbing; bench.py reports the real
    ones)."""
    out = {}
    snap = monitor.get_registry().to_dict()
    for name in ("executor_mfu", "serving_bucket_mfu",
                 "executor_achieved_tflops",
                 "serving_bucket_achieved_tflops",
                 "executor_model_gflops_per_step"):
        fam = snap.get(name)
        out[name] = [{"labels": s.get("labels", {}),
                      "value": s.get("value")}
                     for s in (fam or {}).get("values", [])][:12]
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="run the CI gate")
    ap.add_argument("--json", metavar="PATH",
                    help="write ci_trace_report.json")
    ap.add_argument("--negative-control", action="store_true",
                    help="disable the flight recorder; the gate must "
                         "FAIL (fault context lost)")
    ap.add_argument("--tmp", default="/tmp",
                    help="scratch dir for the trainer leg")
    args = ap.parse_args(argv)

    monitor.reset()
    trace.get_collector().reset()
    fluid.set_flags({"FLAGS_trace": 1})
    if args.negative_control:
        # trace stays ON but the ring is disabled: incidents then ship
        # WITHOUT span context and the flight-recorder legs must fail
        fluid.set_flags({"FLAGS_flight_recorder_size": 0})

    t0 = time.time()
    legs = []
    legs.append(leg_serving_burst())
    legs.append(leg_trainer_steps(args.tmp))
    legs.append(leg_batch_fault_flight())
    legs.append(leg_watchdog_flight())
    legs.append(leg_cost_model())
    mfu = _mfu_figures()
    legs.append(leg_overhead())          # flips FLAGS_trace off/on; last
    fluid.set_flags({"FLAGS_trace": 0,
                     "FLAGS_flight_recorder_size": 256})

    gate_ok = all(l["ok"] for l in legs)
    for l in legs:
        print(f"[{'ok' if l['ok'] else 'MISS'}] {l['name']}")
        for k, v in sorted(l.get("checks", {}).items()):
            if not v:
                print(f"       FAILED check: {k}")
    print(f"trace gate ({time.time() - t0:.1f}s) -> "
          f"{'ok' if gate_ok else 'FAIL'}")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({
                "legs": legs,
                "mfu_figures": mfu,
                "incidents": trace.incidents(),
                "check": {"status": "ok" if gate_ok else "fail",
                          "negative_control":
                              bool(args.negative_control)},
            }, f, indent=2, default=str)
        print(f"trace artifact written to {args.json}")
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
