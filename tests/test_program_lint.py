"""paddle_tpu.analysis: one test per diagnostic code, clean-program
baselines, the executor FLAGS_check_program hook, and the registry audit.

Malformed-graph fixtures mutate ops *after* append (direct field writes,
bypassing Operator.set_attr) — exactly the bug class the static verifier
exists to catch before a JAX trace turns it into an XLA-flavoured error.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis import (CODES, ProgramVerificationError, Severity,
                                 audit_registry, check_program,
                                 coverage_summary, format_audit,
                                 format_diagnostics, verify_program)
from paddle_tpu.core import registry


def codes_of(diags):
    return {d.code for d in diags}


def errors_of(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


def _mlp_program(fetch=True):
    """Small clean net: data -> fc -> relu -> fc -> mean, with backward+SGD."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# clean programs produce no error findings
# ---------------------------------------------------------------------------

def test_clean_program_no_findings():
    main, startup, loss = _mlp_program()
    for prog, fetches in ((main, [loss.name]), (startup, [])):
        diags = verify_program(prog, fetch_names=fetches)
        assert not errors_of(diags), format_diagnostics(diags)


def test_book_model_programs_verify_clean():
    """The tests/test_book.py model suite (built by tools/lint_program.py's
    --builtin mode) must verify clean — main, startup AND test clones."""
    import tools.lint_program as lint

    for name, prog, fetches in lint._builtin_programs():
        diags = verify_program(prog, fetch_names=fetches)
        assert not errors_of(diags), f"{name}:\n" + format_diagnostics(diags)


def test_every_code_is_documented_and_tested():
    # the CODES table is the single source of truth; this file must cover it
    import io
    import os

    here = os.path.abspath(__file__)
    with io.open(here, "r", encoding="utf-8") as f:
        me = f.read()
    assert len(CODES) >= 10
    for code in CODES:
        assert me.count(code) >= 1, f"diagnostic {code} lacks a test here"


# ---------------------------------------------------------------------------
# pass 1: schema conformance
# ---------------------------------------------------------------------------

def _tiny():
    """One relu op on a declared input; returns (program, block, op)."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.relu(x)
    blk = p.global_block
    op = next(o for o in blk.ops if o.type == "relu")
    return p, blk, op


def test_pt100_unregistered_op():
    p, blk, op = _tiny()
    op.type = "totally_not_an_op"
    assert "PT100" in codes_of(verify_program(p))


def test_pt100_grad_of_unregistered_forward():
    p, blk, op = _tiny()
    op.type = "totally_not_an_op_grad"
    assert "PT100" in codes_of(verify_program(p))


def test_pt101_missing_required_input():
    p, blk, op = _tiny()
    del op.inputs["X"]
    diags = verify_program(p)
    assert "PT101" in codes_of(diags)
    d = next(d for d in diags if d.code == "PT101")
    assert d.op_type == "relu" and d.severity == Severity.ERROR


def test_pt102_unknown_input_slot():
    p, blk, op = _tiny()
    op.inputs["Bogus"] = list(op.inputs["X"])
    assert "PT102" in codes_of(verify_program(p))


def test_pt103_missing_required_output():
    p, blk, op = _tiny()
    del op.outputs["Out"]
    assert "PT103" in codes_of(verify_program(p))


def test_pt104_unknown_output_slot():
    p, blk, op = _tiny()
    op.outputs["Bogus"] = list(op.outputs["Out"])
    assert "PT104" in codes_of(verify_program(p))


def test_pt105_missing_required_attr():
    if not registry.has_op("pt_lint_reqattr"):
        @registry.register_op("pt_lint_reqattr", inputs=["X"],
                              outputs=["Out"],
                              attrs={"k": registry.AttrSpec(
                                  "k", required=True)})
        def _lower(ctx, ins, attrs):  # pragma: no cover - never lowered
            return {"Out": ins["X"]}

    p, blk, op = _tiny()
    blk.append_op("pt_lint_reqattr", inputs={"X": ["x"]},
                  outputs={"Out": ["x2"]}, attrs={"k": 1})
    del blk.ops[-1].attrs["k"]
    assert "PT105" in codes_of(verify_program(p))


def test_pt106_unknown_attr_warns():
    p, blk, op = _tiny()
    op.attrs["mystery_knob"] = 7
    diags = verify_program(p)
    d = next(d for d in diags if d.code == "PT106")
    assert d.severity == Severity.WARNING  # does not gate execution
    check_program(p)  # no raise


def test_pt107_nonduplicable_slot_with_list():
    p, blk, op = _tiny()
    op.inputs["X"] = [op.inputs["X"][0], op.inputs["X"][0]]
    assert "PT107" in codes_of(verify_program(p))


def test_grad_op_layout_checked():
    # a hand-built grad op with a bogus slot is caught (PT102/PT104 via the
    # grad-specific schema path)
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.relu(x)
    blk = p.global_block
    blk.append_op("relu_grad",
                  inputs={"X": [x.name], "NotASlot": [x.name]},
                  outputs={"X@GRAD": [x.grad_name],
                           "Bogus@GRAD": [x.grad_name]},
                  attrs={"__fwd_type__": "relu"})
    codes = codes_of(verify_program(p))
    assert "PT102" in codes and "PT104" in codes


# ---------------------------------------------------------------------------
# pass 2: dataflow
# ---------------------------------------------------------------------------

def test_pt200_use_before_def():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)
        fluid.layers.sigmoid(h)
    blk = p.global_block
    # swap the two compute ops: sigmoid now reads relu's output first
    relu_i = next(i for i, o in enumerate(blk.ops) if o.type == "relu")
    sig_i = next(i for i, o in enumerate(blk.ops) if o.type == "sigmoid")
    blk.ops[relu_i], blk.ops[sig_i] = blk.ops[sig_i], blk.ops[relu_i]
    diags = verify_program(p)
    assert "PT200" in codes_of(diags)
    with pytest.raises(ProgramVerificationError):
        check_program(p)


def test_pt201_uninitialized_read():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.relu(x)
    blk = p.global_block
    blk.create_var(name="nowhere", shape=[4], dtype="float32")
    op = next(o for o in blk.ops if o.type == "relu")
    op.inputs["X"] = ["nowhere"]
    diags = verify_program(p)
    assert "PT201" in codes_of(diags)
    assert all(d.severity != Severity.ERROR for d in diags
               if d.code == "PT201")


def test_pt202_write_after_write():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="t", shape=[2], dtype="float32")
        for val in (0.0, 1.0):
            blk.append_op("fill_constant", outputs={"Out": ["t"]},
                          attrs={"shape": [2], "dtype": "float32",
                                 "value": val})
    assert "PT202" in codes_of(verify_program(p))


def test_pt203_dangling_output_is_info():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.relu(x)
    diags = verify_program(p)  # not fetched -> dangling
    assert "PT203" in codes_of(diags)
    # fetching it silences the finding
    assert "PT203" not in codes_of(verify_program(p, fetch_names=[out.name]))


# ---------------------------------------------------------------------------
# pass 3: lowerability
# ---------------------------------------------------------------------------

def test_pt300_missing_lower_rule():
    if not registry.has_op("pt_lint_nolower"):
        registry._OP_REGISTRY["pt_lint_nolower"] = registry.OpDef(
            type="pt_lint_nolower",
            inputs=[registry.IOSpec("X")],
            outputs=[registry.IOSpec("Out")])
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        p.global_block.append_op("pt_lint_nolower", inputs={"X": [x.name]},
                                 outputs={"Out": ["nl_out"]})
    assert "PT300" in codes_of(verify_program(p))


def test_pt301_grad_of_nondifferentiable():
    # psroi_pool registers grad=None; a hand-built psroi_pool_grad op is
    # suspicious (the generic vjp recomputation has no defined meaning)
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4, 4, 4], dtype="float32")
        blk = p.global_block
        blk.append_op("psroi_pool_grad",
                      inputs={"X": [x.name]},
                      outputs={"X@GRAD": [x.grad_name]},
                      attrs={"__fwd_type__": "psroi_pool"})
    diags = verify_program(p)
    assert "PT301" in codes_of(diags)


def test_pt302_rng_under_deterministic_flag():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.dropout(x, dropout_prob=0.5)
    assert "PT302" not in codes_of(verify_program(p))
    fluid.set_flags({"FLAGS_cudnn_deterministic": True})
    try:
        assert "PT302" in codes_of(verify_program(p))
    finally:
        fluid.set_flags({"FLAGS_cudnn_deterministic": False})


# ---------------------------------------------------------------------------
# pass 4: shape/dtype replay
# ---------------------------------------------------------------------------

def test_pt400_shape_drift():
    p, blk, op = _tiny()
    out_name = op.outputs["Out"][0]
    blk.var(out_name).shape = (7, 7, 7)  # recorded metadata now stale
    diags = verify_program(p)
    assert "PT400" in codes_of(diags)
    # the replay restores the recorded (wrong) metadata: verification is
    # read-only even when it disagrees
    assert blk.var(out_name).shape == (7, 7, 7)


def test_pt401_dtype_drift():
    p, blk, op = _tiny()
    out_name = op.outputs["Out"][0]
    blk.var(out_name).dtype = "int64"
    assert "PT401" in codes_of(verify_program(p))


def test_shape_replay_catches_raw_attr_mutation():
    """The motivating bug: op.attrs['k'] = v (bypassing set_attr) leaves
    recorded var shapes stale; the replay pass surfaces it."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[6], dtype="float32")
        out = fluid.layers.reshape(x, shape=[-1, 2, 3])
    op = next(o for o in p.global_block.ops if o.type == "reshape2")
    op.attrs["shape"] = [-1, 3, 2]  # raw write: no version bump, no re-infer
    assert "PT400" in codes_of(verify_program(p))


# ---------------------------------------------------------------------------
# executor hook (FLAGS_check_program)
# ---------------------------------------------------------------------------

def test_executor_hook_rejects_malformed_program():
    main, startup, loss = _mlp_program()
    blk = main.global_block
    op = next(o for o in blk.ops if o.type == "relu")
    del op.inputs["X"]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ProgramVerificationError) as ei:
            exe.run(main, feed={"x": np.zeros((2, 4), np.float32),
                                "y": np.zeros((2, 1), np.float32)},
                    fetch_list=[loss.name])
    assert "PT101" in str(ei.value)


def test_executor_hook_covers_compiled_program():
    """The CompiledProgram dispatch path must verify the wrapped program
    too — multi-device users get the same build-site diagnostics."""
    main, startup, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "relu")
    del op.inputs["X"]
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ProgramVerificationError, match="PT101"):
            exe.run(compiled,
                    feed={"x": np.zeros((8, 4), np.float32),
                          "y": np.zeros((8, 1), np.float32)},
                    fetch_list=[loss.name])


def test_executor_hook_verifies_once_per_version():
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((2, 4), np.float32),
            "y": np.zeros((2, 1), np.float32)}
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        n = len(exe._verified)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        assert len(exe._verified) == n  # cached: no re-verify per step


# ---------------------------------------------------------------------------
# pass 5: registry audit
# ---------------------------------------------------------------------------

def test_registry_audit_full_coverage():
    rows = audit_registry()
    assert len(rows) > 200
    summary = coverage_summary(rows)
    # every registered op must carry a lower rule (the PT300 invariant,
    # CI-gated via tools/audit_registry.py --strict)
    real = [r for r in rows if not r["op"].startswith("pt_lint_")]
    assert all(r["lower"] for r in real)
    assert summary["differentiable"] > 100
    table = format_audit(rows)
    assert "relu" in table and "infer_shape" in table


def test_registry_audit_test_references():
    import os

    rows = audit_registry(test_dir=os.path.dirname(__file__))
    by_op = {r["op"]: r for r in rows}
    assert by_op["relu"]["tested"] is True
    summary = coverage_summary(rows)
    assert summary["tested"] is not None and summary["tested"] > 100


def test_lint_cli_flags_errors(tmp_path, capsys):
    import tools.lint_program as lint

    main, startup, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "relu")
    del op.inputs["X"]  # survives serde (the ctor only checks op types)
    bad = tmp_path / "bad.json"
    bad.write_text(main.to_json())
    good = tmp_path / "good.json"
    good.write_text(startup.to_json())
    assert lint.main([str(good)]) == 0
    assert lint.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "PT101" in out
