"""paddle_tpu.analysis: one test per diagnostic code, clean-program
baselines, the executor FLAGS_check_program hook, and the registry audit.

Malformed-graph fixtures mutate ops *after* append (direct field writes,
bypassing Operator.set_attr) — exactly the bug class the static verifier
exists to catch before a JAX trace turns it into an XLA-flavoured error.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis import (CODES, ProgramVerificationError, Severity,
                                 audit_registry, check_program,
                                 coverage_summary, format_audit,
                                 format_diagnostics, liveness, verify_program)
from paddle_tpu.core import registry


def codes_of(diags):
    return {d.code for d in diags}


def errors_of(diags):
    return [d for d in diags if d.severity == Severity.ERROR]


def _mlp_program(fetch=True):
    """Small clean net: data -> fc -> relu -> fc -> mean, with backward+SGD."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# clean programs produce no error findings
# ---------------------------------------------------------------------------

def test_clean_program_no_findings():
    main, startup, loss = _mlp_program()
    for prog, fetches in ((main, [loss.name]), (startup, [])):
        diags = verify_program(prog, fetch_names=fetches)
        assert not errors_of(diags), format_diagnostics(diags)


def test_book_model_programs_verify_clean():
    """The tests/test_book.py model suite (built by tools/lint_program.py's
    --builtin mode) must verify clean — main, startup AND test clones."""
    import tools.lint_program as lint

    for name, prog, fetches in lint._builtin_programs():
        diags = verify_program(prog, fetch_names=fetches)
        assert not errors_of(diags), f"{name}:\n" + format_diagnostics(diags)


def test_every_code_is_documented_and_tested():
    # the CODES table is the single source of truth; this file (or
    # test_pass_manager.py, which owns the PT70x-PT72x pass-manager
    # families, test_sharding_check.py, which owns PT73x,
    # test_epilogue_fusion.py, which owns PT75x,
    # test_concurrency_lint.py, which owns the source-level PT80x
    # family, or test_numerics.py, which owns the PT90x numerics
    # family) must cover every code
    import io
    import os

    here = os.path.abspath(__file__)
    me = ""
    for fname in (here,
                  os.path.join(os.path.dirname(here),
                               "test_pass_manager.py"),
                  os.path.join(os.path.dirname(here),
                               "test_sharding_check.py"),
                  os.path.join(os.path.dirname(here),
                               "test_epilogue_fusion.py"),
                  os.path.join(os.path.dirname(here),
                               "test_concurrency_lint.py"),
                  os.path.join(os.path.dirname(here),
                               "test_numerics.py")):
        with io.open(fname, "r", encoding="utf-8") as f:
            me += f.read()
    assert len(CODES) >= 10
    for code in CODES:
        assert me.count(code) >= 1, f"diagnostic {code} lacks a test here"


# ---------------------------------------------------------------------------
# pass 1: schema conformance
# ---------------------------------------------------------------------------

def _tiny():
    """One relu op on a declared input; returns (program, block, op)."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.relu(x)
    blk = p.global_block
    op = next(o for o in blk.ops if o.type == "relu")
    return p, blk, op


def test_pt100_unregistered_op():
    p, blk, op = _tiny()
    op.type = "totally_not_an_op"
    assert "PT100" in codes_of(verify_program(p))


def test_pt100_grad_of_unregistered_forward():
    p, blk, op = _tiny()
    op.type = "totally_not_an_op_grad"
    assert "PT100" in codes_of(verify_program(p))


def test_pt101_missing_required_input():
    p, blk, op = _tiny()
    del op.inputs["X"]
    diags = verify_program(p)
    assert "PT101" in codes_of(diags)
    d = next(d for d in diags if d.code == "PT101")
    assert d.op_type == "relu" and d.severity == Severity.ERROR


def test_pt102_unknown_input_slot():
    p, blk, op = _tiny()
    op.inputs["Bogus"] = list(op.inputs["X"])
    assert "PT102" in codes_of(verify_program(p))


def test_pt103_missing_required_output():
    p, blk, op = _tiny()
    del op.outputs["Out"]
    assert "PT103" in codes_of(verify_program(p))


def test_pt104_unknown_output_slot():
    p, blk, op = _tiny()
    op.outputs["Bogus"] = list(op.outputs["Out"])
    assert "PT104" in codes_of(verify_program(p))


def test_pt105_missing_required_attr():
    if not registry.has_op("pt_lint_reqattr"):
        @registry.register_op("pt_lint_reqattr", inputs=["X"],
                              outputs=["Out"],
                              attrs={"k": registry.AttrSpec(
                                  "k", required=True)})
        def _lower(ctx, ins, attrs):  # pragma: no cover - never lowered
            return {"Out": ins["X"]}

    p, blk, op = _tiny()
    blk.append_op("pt_lint_reqattr", inputs={"X": ["x"]},
                  outputs={"Out": ["x2"]}, attrs={"k": 1})
    del blk.ops[-1].attrs["k"]
    assert "PT105" in codes_of(verify_program(p))


def test_pt106_unknown_attr_warns():
    p, blk, op = _tiny()
    op.attrs["mystery_knob"] = 7
    diags = verify_program(p)
    d = next(d for d in diags if d.code == "PT106")
    assert d.severity == Severity.WARNING  # does not gate execution
    check_program(p)  # no raise


def test_pt107_nonduplicable_slot_with_list():
    p, blk, op = _tiny()
    op.inputs["X"] = [op.inputs["X"][0], op.inputs["X"][0]]
    assert "PT107" in codes_of(verify_program(p))


def test_grad_op_layout_checked():
    # a hand-built grad op with a bogus slot is caught (PT102/PT104 via the
    # grad-specific schema path)
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.relu(x)
    blk = p.global_block
    blk.append_op("relu_grad",
                  inputs={"X": [x.name], "NotASlot": [x.name]},
                  outputs={"X@GRAD": [x.grad_name],
                           "Bogus@GRAD": [x.grad_name]},
                  attrs={"__fwd_type__": "relu"})
    codes = codes_of(verify_program(p))
    assert "PT102" in codes and "PT104" in codes


# ---------------------------------------------------------------------------
# pass 2: dataflow
# ---------------------------------------------------------------------------

def test_pt200_use_before_def():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)
        fluid.layers.sigmoid(h)
    blk = p.global_block
    # swap the two compute ops: sigmoid now reads relu's output first
    relu_i = next(i for i, o in enumerate(blk.ops) if o.type == "relu")
    sig_i = next(i for i, o in enumerate(blk.ops) if o.type == "sigmoid")
    blk.ops[relu_i], blk.ops[sig_i] = blk.ops[sig_i], blk.ops[relu_i]
    diags = verify_program(p)
    assert "PT200" in codes_of(diags)
    with pytest.raises(ProgramVerificationError):
        check_program(p)


def test_pt201_uninitialized_read():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.relu(x)
    blk = p.global_block
    blk.create_var(name="nowhere", shape=[4], dtype="float32")
    op = next(o for o in blk.ops if o.type == "relu")
    op.inputs["X"] = ["nowhere"]
    diags = verify_program(p)
    assert "PT201" in codes_of(diags)
    assert all(d.severity != Severity.ERROR for d in diags
               if d.code == "PT201")


def test_pt202_write_after_write():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="t", shape=[2], dtype="float32")
        for val in (0.0, 1.0):
            blk.append_op("fill_constant", outputs={"Out": ["t"]},
                          attrs={"shape": [2], "dtype": "float32",
                                 "value": val})
    assert "PT202" in codes_of(verify_program(p))


def test_pt203_dangling_output_is_info():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.relu(x)
    diags = verify_program(p)  # not fetched -> dangling
    assert "PT203" in codes_of(diags)
    # fetching it silences the finding
    assert "PT203" not in codes_of(verify_program(p, fetch_names=[out.name]))


# ---------------------------------------------------------------------------
# pass 3: lowerability
# ---------------------------------------------------------------------------

def test_pt300_missing_lower_rule():
    if not registry.has_op("pt_lint_nolower"):
        registry._OP_REGISTRY["pt_lint_nolower"] = registry.OpDef(
            type="pt_lint_nolower",
            inputs=[registry.IOSpec("X")],
            outputs=[registry.IOSpec("Out")])
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        p.global_block.append_op("pt_lint_nolower", inputs={"X": [x.name]},
                                 outputs={"Out": ["nl_out"]})
    assert "PT300" in codes_of(verify_program(p))


def test_pt301_grad_of_nondifferentiable():
    # psroi_pool registers grad=None; a hand-built psroi_pool_grad op is
    # suspicious (the generic vjp recomputation has no defined meaning)
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4, 4, 4], dtype="float32")
        blk = p.global_block
        blk.append_op("psroi_pool_grad",
                      inputs={"X": [x.name]},
                      outputs={"X@GRAD": [x.grad_name]},
                      attrs={"__fwd_type__": "psroi_pool"})
    diags = verify_program(p)
    assert "PT301" in codes_of(diags)


def test_pt302_rng_under_deterministic_flag():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.dropout(x, dropout_prob=0.5)
    assert "PT302" not in codes_of(verify_program(p))
    fluid.set_flags({"FLAGS_cudnn_deterministic": True})
    try:
        assert "PT302" in codes_of(verify_program(p))
    finally:
        fluid.set_flags({"FLAGS_cudnn_deterministic": False})


# ---------------------------------------------------------------------------
# pass 4: shape/dtype replay
# ---------------------------------------------------------------------------

def test_pt400_shape_drift():
    p, blk, op = _tiny()
    out_name = op.outputs["Out"][0]
    blk.var(out_name).shape = (7, 7, 7)  # recorded metadata now stale
    diags = verify_program(p)
    assert "PT400" in codes_of(diags)
    # the replay restores the recorded (wrong) metadata: verification is
    # read-only even when it disagrees
    assert blk.var(out_name).shape == (7, 7, 7)


def test_pt401_dtype_drift():
    p, blk, op = _tiny()
    out_name = op.outputs["Out"][0]
    blk.var(out_name).dtype = "int64"
    assert "PT401" in codes_of(verify_program(p))


def test_shape_replay_catches_raw_attr_mutation():
    """The motivating bug: op.attrs['k'] = v (bypassing set_attr) leaves
    recorded var shapes stale; the replay pass surfaces it."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[6], dtype="float32")
        out = fluid.layers.reshape(x, shape=[-1, 2, 3])
    op = next(o for o in p.global_block.ops if o.type == "reshape2")
    op.attrs["shape"] = [-1, 3, 2]  # raw write: no version bump, no re-infer
    assert "PT400" in codes_of(verify_program(p))


# ---------------------------------------------------------------------------
# executor hook (FLAGS_check_program)
# ---------------------------------------------------------------------------

def test_executor_hook_rejects_malformed_program():
    main, startup, loss = _mlp_program()
    blk = main.global_block
    op = next(o for o in blk.ops if o.type == "relu")
    del op.inputs["X"]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ProgramVerificationError) as ei:
            exe.run(main, feed={"x": np.zeros((2, 4), np.float32),
                                "y": np.zeros((2, 1), np.float32)},
                    fetch_list=[loss.name])
    assert "PT101" in str(ei.value)


def test_executor_hook_covers_compiled_program():
    """The CompiledProgram dispatch path must verify the wrapped program
    too — multi-device users get the same build-site diagnostics."""
    main, startup, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "relu")
    del op.inputs["X"]
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ProgramVerificationError, match="PT101"):
            exe.run(compiled,
                    feed={"x": np.zeros((8, 4), np.float32),
                          "y": np.zeros((8, 1), np.float32)},
                    fetch_list=[loss.name])


def test_executor_hook_verifies_once_per_version():
    main, startup, loss = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((2, 4), np.float32),
            "y": np.zeros((2, 1), np.float32)}
    fluid.set_flags({"FLAGS_check_program": True})
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        n = len(exe._verified)
        exe.run(main, feed=feed, fetch_list=[loss.name])
        assert len(exe._verified) == n  # cached: no re-verify per step


# ---------------------------------------------------------------------------
# pass 5: liveness & effects (PT50x) + donation + memory plan
# ---------------------------------------------------------------------------

def _while_program():
    """sum-loop program with two outer vars the body reads: ``step`` (read
    only inside the sub-block) and ``acc`` (read+written through the loop).
    Returns (main, startup, out_var)."""
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 4)
        step = layers.fill_constant([1], "float32", 2.5)
        acc = layers.fill_constant([1], "float32", 0.0)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.assign(layers.elementwise_add(acc, step), acc)
            layers.increment(i, value=1)
            layers.assign(layers.less_than(i, n), cond)
        out = layers.scale(acc, scale=1.0)
    return main, startup, out


def test_pt500_donation_unsafe_fetch():
    """Fetching a parameter the step updates in place excludes it from
    donation (the old state_in ∩ state_out heuristic donated it, so the
    fetch could observe a consumed buffer)."""
    main, startup, loss = _mlp_program()
    blk = main.global_block
    param = next(n for n in blk.vars if n.endswith(".w_0"))
    feeds = {"x", "y"}

    diags = verify_program(main, fetch_names=[loss.name, param])
    d = next(d for d in diags if d.code == "PT500")
    assert param in d.message and d.severity == Severity.WARNING
    check_program(main, fetch_names=[loss.name, param])  # warning: no raise

    safe = liveness.safe_donation_set(blk, feeds, [loss.name, param])
    assert param not in safe
    # without the fetch the same param IS proven donatable — the pass is
    # not blanket-conservative
    assert param in liveness.safe_donation_set(blk, feeds, [loss.name])
    assert "PT500" not in codes_of(
        verify_program(main, fetch_names=[loss.name]))


def test_pt500_excluded_from_analyze_block_io():
    from paddle_tpu.executor import analyze_block_io

    main, startup, loss = _mlp_program()
    blk = main.global_block
    param = next(n for n in blk.vars if n.endswith(".w_0"))
    io = analyze_block_io(blk, {"x", "y"}, [loss.name, param])
    assert param not in io["donated"] and param in io["ro"]
    # updates still flow back to the scope via state_out
    assert param in io["state_out"]
    io2 = analyze_block_io(blk, {"x", "y"}, [loss.name])
    assert param in io2["donated"]


def test_pt501_write_after_fetch():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.relu(x)
        blk = p.global_block
        blk.append_op("fetch", inputs={"X": [h.name]},
                      outputs={"Out": ["fetched_h"]}, attrs={})
        # rewrite h AFTER its fetch op: compiled steps fetch final values,
        # diverging from fetch-at-op-position semantics
        blk.append_op("scale", inputs={"X": [h.name]},
                      outputs={"Out": [h.name]}, attrs={"scale": 2.0})
    diags = verify_program(p, fetch_names=[h.name])
    d = next(d for d in diags if d.code == "PT501")
    assert h.name in d.message and d.severity == Severity.WARNING


def test_pt502_dead_op():
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        kept = fluid.layers.relu(x)
        fluid.layers.sigmoid(x)  # output never read, not fetched
    diags = verify_program(p, fetch_names=[kept.name])
    dead = [d for d in diags if d.code == "PT502"]
    assert len(dead) == 1 and dead[0].op_type == "sigmoid"
    assert dead[0].severity == Severity.INFO


def test_pt502_side_effect_op_is_not_dead():
    # a fetch op's output is observable outside the value graph (kind =
    # side_effect), so an unread output does not make the op dead
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        kept = fluid.layers.relu(x)
        p.global_block.append_op("fetch", inputs={"X": [kept.name]},
                                 outputs={"Out": ["fetch_sink"]}, attrs={})
    assert "PT502" not in codes_of(verify_program(p, fetch_names=[kept.name]))


def test_pt503_dead_var():
    p, blk, op = _tiny()
    blk.create_var(name="never_touched", shape=[3], dtype="float32")
    diags = verify_program(p)
    d = next(d for d in diags if d.code == "PT503")
    assert "never_touched" in d.message and d.severity == Severity.INFO


def test_pt504_persistable_rebound_in_sub_block():
    """A persistable written inside a sub-block that does NOT escape through
    the owning op's outputs: the compiled step's state threading only scans
    the global block, so the scope would silently never see the update."""
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        blk = p.global_block
        blk.create_var(name="stat", shape=[1], dtype="float32",
                       persistable=True)
        cv = fluid.layers.fill_constant([1], "bool", True)
        sub = p._create_block()
        sub.append_op("fill_constant", outputs={"Out": ["stat"]},
                      attrs={"shape": [1], "dtype": "float32", "value": 1.0})
        p._rollback()
        # owning while op does NOT list 'stat' in Out -> the write is lost
        blk.append_op("while", inputs={"X": [], "Condition": [cv.name]},
                      outputs={"Out": []},
                      attrs={"sub_block": sub.idx, "max_len": 1})
    diags = verify_program(p)
    d = next(d for d in diags if d.code == "PT504")
    assert "stat" in d.message and d.severity == Severity.ERROR
    with pytest.raises(ProgramVerificationError, match="PT504"):
        check_program(p)


def test_while_outer_var_stays_live_and_not_donatable():
    """Satellite: a while body reading an outer var must keep it live (no
    dead-op/dead-var false positive) and must never mark it donatable."""
    main, startup, out = _while_program()
    blk = main.global_block
    step_name = next(o.output_arg_names[0] for o in blk.ops
                     if o.type == "fill_constant"
                     and abs(o.attrs.get("value", 0) - 2.5) < 1e-9)

    diags = verify_program(main, fetch_names=[out.name])
    assert not errors_of(diags), format_diagnostics(diags)
    for d in diags:
        if d.code in ("PT502", "PT503"):
            assert step_name not in d.message, format_diagnostics([d])

    live = liveness.block_liveness(blk, (), [out.name])
    wi = next(i for i, o in enumerate(blk.ops) if o.type == "while")
    vl = live[step_name]
    # the sub-block read is charged at the while op's index
    assert wi in vl.uses
    assert vl.interval(len(blk.ops))[1] >= wi + 1
    assert step_name not in liveness.safe_donation_set(blk, (), [out.name])

    # the loop actually runs and agrees with the analysis: 4 * 2.5
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (res,) = exe.run(main, fetch_list=[out.name])
    assert float(res[0]) == 10.0


def test_effect_classification():
    main, startup, loss = _mlp_program()
    kinds = {op.type: liveness.classify_op_effects(op).kind
             for op in main.global_block.ops}
    assert kinds["sgd"] == liveness.INPLACE
    assert kinds["mul"] == liveness.PURE
    wmain, _, _ = _while_program()
    wop = next(o for o in wmain.global_block.ops if o.type == "while")
    eff = liveness.classify_op_effects(wop)
    assert eff.kind == liveness.CONTROL_FLOW and not eff.eliminable
    p = fluid.Program()
    with un.guard(), fluid.program_guard(p, fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.dropout(x, dropout_prob=0.5)
    dop = next(o for o in p.global_block.ops if o.type == "dropout")
    assert liveness.classify_op_effects(dop).kind == liveness.RNG


def test_safe_donation_subset_of_heuristic_on_builtin_programs():
    """Acceptance: donation decisions are identical or strictly safer than
    the old state_in ∩ state_out heuristic on every tier-1 program — and
    not vacuously so: the mnist training program still donates its params."""
    import tools.lint_program as lint
    from paddle_tpu.executor import analyze_block_io

    donated_somewhere = False
    for name, prog, fetches in lint._builtin_programs():
        blk = prog.global_block
        feeds = {v.name for v in blk.vars.values() if v.is_data}
        io = analyze_block_io(blk, feeds, fetches)
        old_heuristic = {n for n in io["state_in"] if n in io["state_out"]}
        assert set(io["donated"]) <= old_heuristic, name
        donated_somewhere = donated_somewhere or bool(io["donated"])
    assert donated_somewhere


def test_memory_plan_within_2x_of_actual_bytes():
    """Acceptance: plan peak bytes within 2x of actual live array bytes on a
    small traced program (feed + params + fetch, all fp32)."""
    main, startup = fluid.Program(), fluid.Program()
    with un.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[256], dtype="float32")
        h = fluid.layers.fc(x, 128, bias_attr=False)
        out = fluid.layers.scale(h, scale=2.0)
    batch = 64
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((batch, 256), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        fetched = exe.run(main, feed=feed, fetch_list=[out.name])
        actual = sum(np.asarray(scope.find_var(n)).nbytes
                     for n in scope.vars)
    actual += feed["x"].nbytes + fetched[0].nbytes
    actual += batch * 128 * 4  # the single live intermediate (h)
    plan = main.memory_plan(feed_names=["x"], fetch_names=[out.name],
                            batch_size=batch)
    assert actual / 2 <= plan.peak_bytes <= actual * 2, (
        f"plan {plan.peak_bytes} vs actual {actual}")
    # the breakdown classifies the fc weight as weight, the feed as
    # activation, and the hot-spot list leads with the largest buffer
    at_peak = plan.by_class_at(plan.peak_op_idx)
    assert at_peak.get("weight", 0) == 256 * 128 * 4
    hot = plan.top_hot_spots(3)
    assert hot and hot[0].bytes == max(e.bytes for e in plan.entries)


def test_memory_plan_while_subblock_charged():
    main, startup, out = _while_program()
    plan = main.memory_plan(fetch_names=[out.name], batch_size=1)
    assert plan.sub_plans, "while sub-block must be planned"
    wi = next(i for i, o in enumerate(main.global_block.ops)
              if o.type == "while")
    assert wi in plan.sub_plans
    assert plan.timeline[wi] >= plan.sub_plans[wi].peak_bytes


def test_fetch_updated_param_regression():
    """Satellite: Executor.run fetching a parameter the step updates must
    return the post-step value AND leave the scope consistent — under the
    old heuristic the param's buffer was donated while fetched."""
    main, startup, loss = _mlp_program()
    blk = main.global_block
    param = next(n for n in blk.vars if n.endswith(".w_0"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.random.RandomState(0).randn(8, 4).astype(np.float32),
            "y": np.ones((8, 1), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = scope.numpy(param).copy()
        loss1, w_fetched = exe.run(main, feed=feed,
                                   fetch_list=[loss.name, param])
        w_scope = scope.numpy(param)
        # the fetch observes the post-update value, same as the scope
        np.testing.assert_array_equal(w_fetched, w_scope)
        assert not np.array_equal(w_fetched, w0), "SGD must move the param"
        # second step: scope state chains, no consumed-buffer error
        loss2, w_fetched2 = exe.run(main, feed=feed,
                                    fetch_list=[loss.name, param])
        np.testing.assert_array_equal(w_fetched2, scope.numpy(param))
        assert float(np.ravel(loss2)[0]) < float(np.ravel(loss1)[0])


# ---------------------------------------------------------------------------
# pass 5: registry audit
# ---------------------------------------------------------------------------

def test_registry_audit_full_coverage():
    rows = audit_registry()
    assert len(rows) > 200
    summary = coverage_summary(rows)
    # every registered op must carry a lower rule (the PT300 invariant,
    # CI-gated via tools/audit_registry.py --strict)
    real = [r for r in rows if not r["op"].startswith("pt_lint_")]
    assert all(r["lower"] for r in real)
    assert summary["differentiable"] > 100
    table = format_audit(rows)
    assert "relu" in table and "infer_shape" in table


def test_registry_audit_test_references():
    import os

    rows = audit_registry(test_dir=os.path.dirname(__file__))
    by_op = {r["op"]: r for r in rows}
    assert by_op["relu"]["tested"] is True
    summary = coverage_summary(rows)
    assert summary["tested"] is not None and summary["tested"] > 100


def test_lint_cli_flags_errors(tmp_path, capsys):
    import tools.lint_program as lint

    main, startup, loss = _mlp_program()
    op = next(o for o in main.global_block.ops if o.type == "relu")
    del op.inputs["X"]  # survives serde (the ctor only checks op types)
    bad = tmp_path / "bad.json"
    bad.write_text(main.to_json())
    good = tmp_path / "good.json"
    good.write_text(startup.to_json())
    assert lint.main([str(good)]) == 0
    assert lint.main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "PT101" in out
