"""Book-style end-to-end gates (VERDICT r2 item 10; reference
python/paddle/fluid/tests/book/): fit_a_line, recognize_digits, word2vec —
each fed through the DataLoader, trained, and (for digits) exported/
reloaded through save_inference_model."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.dataset import imikolov, mnist, uci_housing


def test_fit_a_line():
    """reference book/test_fit_a_line.py: linear regression on uci_housing."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.02).minimize(loss)
    main.random_seed = 1

    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_sample_generator(uci_housing.train(), batch_size=32,
                                drop_last=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(20):
            for batch in loader:
                (lv,) = exe.run(main, feed=batch, fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
    # the book test's bar: average loss below 10.0 on the housing scale
    assert np.mean(losses[-10:]) < 1.0, losses[-10:]


def test_recognize_digits(tmp_path):
    """reference book/test_recognize_digits.py: MNIST MLP + inference
    export round-trip."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[784], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(img, 64, act="relu")
            logits = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(logits, label)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    main.random_seed = 2

    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=4)
    loader.set_sample_generator(mnist.train(), batch_size=64, drop_last=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(3):
            for batch in loader:
                exe.run(main, feed=batch, fetch_list=[loss.name])
        # eval on the test split with the pruned program
        feeder = fluid.DataFeeder(feed_list=[img, label], program=main)
        samples = [(im, np.array([lb])) for im, lb in
                   list(mnist.test()())[:256]]
        (accv,) = exe.run(test_prog, feed=feeder.feed(samples),
                          fetch_list=[acc.name])
        assert float(np.asarray(accv)) > 0.85, float(np.asarray(accv))

        # inference export -> fresh scope -> same predictions
        fluid.io.save_inference_model(str(tmp_path / "digits"), ["img"],
                                      [logits], exe, main_program=main)
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        infer_prog, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path / "digits"), exe2)
        batch_imgs = np.stack([s[0] for s in samples[:32]])
        (out,) = exe2.run(infer_prog, feed={feeds[0]: batch_imgs},
                          fetch_list=fetches)
    with fluid.scope_guard(scope):
        (ref,) = exe.run(test_prog, feed={"img": batch_imgs,
                                          "label": np.zeros((32, 1), np.int64)},
                         fetch_list=[logits.name])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_word2vec():
    """reference book/test_word2vec.py: n-gram next-word model on
    imikolov."""
    N = 3  # 2 context words -> next word
    word_dict = imikolov.build_dict()
    dict_size = len(word_dict)
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w1 = fluid.layers.data("w1", shape=[1], dtype="int64")
            w2 = fluid.layers.data("w2", shape=[1], dtype="int64")
            nxt = fluid.layers.data("next", shape=[1], dtype="int64")
            embs = []
            for w in (w1, w2):
                embs.append(fluid.layers.embedding(
                    w, size=[dict_size, 32],
                    param_attr=fluid.ParamAttr(name="shared_emb")))
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(concat, 64, act="sigmoid")
            logits = fluid.layers.fc(hidden, dict_size)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, nxt))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    main.random_seed = 3

    loader = fluid.DataLoader.from_generator(feed_list=[w1, w2, nxt],
                                             capacity=4)
    loader.set_sample_generator(imikolov.train(word_dict, N), batch_size=128,
                                drop_last=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(10):
            for batch in loader:
                (lv,) = exe.run(main, feed=batch, fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
    # Markov structure: loss must fall clearly below uniform log-vocab
    uniform = np.log(dict_size)
    assert losses[-1] < uniform * 0.75, (losses[-1], uniform)
    assert losses[-1] < losses[0] * 0.7
