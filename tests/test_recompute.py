"""RecomputeOptimizer (gradient checkpointing): numerical equivalence with
the plain path, and a compiled peak-memory reduction proof (reference
optimizer.py:3074 RecomputeOptimizer / backward.py:555)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _build(recompute, width=256, depth=6, ckpt_every=2):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = x
            ckpts = []
            for i in range(depth):
                h = fluid.layers.fc(h, width, act="relu")
                if (i + 1) % ckpt_every == 0:
                    ckpts.append(h)
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.Adam(learning_rate=0.01)
            if recompute:
                opt = fluid.optimizer.RecomputeOptimizer(opt)
                opt._set_checkpoints(ckpts)
            opt.minimize(loss)
    return main, startup, loss


def _train(recompute, steps=6, batch=32, **kw):
    main, startup, loss = _build(recompute, **kw)
    main.random_seed = 7
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xb = rng.randn(batch, kw.get("width", 256)).astype(np.float32)
    yb = rng.randn(batch, 1).astype(np.float32)
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_recompute_matches_plain_training():
    base = _train(False, width=64, depth=4)
    rc = _train(True, width=64, depth=4)
    np.testing.assert_allclose(base, rc, rtol=1e-4, atol=1e-6)
    assert base[-1] < base[0]


def test_recompute_segments_inserted():
    main, _, _ = _build(True, width=32, depth=6, ckpt_every=2)
    types = [op.type for op in main.global_block.ops]
    assert types.count("recompute_segment") >= 2
    assert types.count("recompute_segment_grad") >= 2
    # internals of a segment are demoted out of the global block
    sub = main.blocks[main.global_block.ops[
        types.index("recompute_segment")].attrs["sub_block"]]
    assert sub.ops and sub.vars


def _lowered(recompute, width=256, depth=8, batch=256):
    import jax

    main, startup, loss = _build(recompute, width=width, depth=depth)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((batch, width), np.float32),
            "y": np.zeros((batch, 1), np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = exe._get_compiled(main, feed, [loss.name], scope)
        feed_vals = [jax.ShapeDtypeStruct(feed[n].shape, feed[n].dtype)
                     for n in step.feed_names]
        don = [scope.find_var(n) for n in step.donated_names]
        ro = [scope.find_var(n) for n in step.ro_names]
        key = jax.random.key(0)
        return step.fn.lower(feed_vals, don, ro, key)


def test_recompute_remat_in_lowered_hlo():
    """The lowered program must carry the rematerialisation: recomputed
    segment matmuls (extra dots) behind optimization barriers, so the fwd
    activations inside segments are not operands of backward ops.

    Peak-liveness byte counts are not assertable in this environment: XLA
    CPU's CompiledMemoryStats.temp_size is liveness-blind (identical for
    jax.checkpoint'd and plain jax.grad of a deep MLP), and the axon TPU
    tunnel reports temp_size=0. On real TPU the remat survives to the
    executable (generated_code_size grows by the recompute code); see
    test_tpu_smoke.py for the on-chip check."""
    plain = _lowered(False).as_text()
    rc = _lowered(True).as_text()
    assert rc.count("stablehlo.dot") > plain.count("stablehlo.dot")
    assert "optimization_barrier" in rc
    assert "optimization_barrier" not in plain


def test_recompute_program_serializes_and_runs():
    main, startup, loss = _build(True, width=32, depth=4)
    main.random_seed = 3
    clone = fluid.Program.from_json(main.to_json())
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    feed = {"x": rng.randn(8, 32).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        (a,) = exe.run(main, feed=feed, fetch_list=[loss.name])
    exe2 = fluid.Executor(fluid.CPUPlace())  # fresh step counter: same init
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2.run(startup)
        (b,) = exe2.run(clone, feed=feed, fetch_list=[loss.name])
    np.testing.assert_allclose(a, b, rtol=1e-5)
