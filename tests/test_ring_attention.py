"""Ring attention (sequence/context parallelism): sharded results must
equal dense single-device attention exactly, causal and bidirectional,
including on a combined dp x sp mesh and through jax.grad."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.ring_attention import (attention_reference,
                                                ring_attention)
from paddle_tpu.parallel.sharding import make_mesh

B, T, H, D = 2, 32, 4, 8


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, T, H, D).astype(np.float32) * 0.5
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=causal)
    want = attention_reference(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ring_on_dp_sp_mesh():
    q, k, v = _qkv(1)
    mesh = make_mesh({"dp": 2, "sp": 4})
    got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         mesh, causal=True)
    want = attention_reference(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ring_attention_differentiable():
    q, k, v = _qkv(2)
    mesh = make_mesh({"sp": 4})

    def loss_ring(q_, k_, v_):
        return ring_attention(q_, k_, v_, mesh, causal=True).sum()

    def loss_dense(q_, k_, v_):
        return attention_reference(q_, k_, v_, causal=True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-5, atol=5e-6)


def test_ring_memory_is_local():
    """The point of the ring: no [T, T] global score matrix and no
    all-gathered K/V. Walk the whole jaxpr INCLUDING the shard_map and
    scan sub-jaxprs and assert no intermediate carries a full-T dim in two
    positions (scores) or a gathered [.., T, ..] K/V block."""
    q, k, v = _qkv(3)
    mesh = make_mesh({"sp": 8})
    fn = lambda a, b, c: ring_attention(a, b, c, mesh, causal=False)
    jaxpr = jax.make_jaxpr(fn)(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v))

    seen = []

    def walk(jx, inside_shard_map):
        for eqn in jx.eqns:
            for out in eqn.outvars:
                shape = tuple(getattr(out.aval, "shape", ()))
                seen.append(shape)
                if inside_shard_map:
                    # everything inside the manual region is per-chip: a
                    # full-T array would mean gathered K/V or global scores
                    assert T not in shape, \
                        f"full-T intermediate {shape} in {eqn.primitive}"
                else:
                    assert shape.count(T) < 2, \
                        f"global score matrix {shape} in {eqn.primitive}"
            for val in eqn.params.values():
                # sub-jaxprs appear as raw Jaxpr (has .eqns) or ClosedJaxpr
                inner = val if hasattr(val, "eqns") else \
                    getattr(val, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    walk(inner, inside_shard_map or
                         "shard_map" in str(eqn.primitive))

    walk(jaxpr.jaxpr, False)
    # sanity: the walk actually visited the scan body's score matmuls
    Tl = T // 8
    assert any(s.count(Tl) >= 2 for s in seen), seen[:10]


def test_fluid_api_sequence_parallel_matches_plain():
    """VERDICT r4 item 8: layers.fused_multihead_attention(
    sequence_parallel=True) under a dp x sp mesh must train and match the
    single-device plain path loss-for-loss. The program-builder lives in
    __graft_entry__ (the driver dryrun leg) so the two cannot drift."""
    import sys

    sys.path.insert(0, ".")
    import __graft_entry__ as g

    g._dryrun_ring_attention_fluid_api(8)
