"""Flash-attention Pallas kernel + fused_multihead_attention op.

CPU suite runs the kernel via the pallas interpreter (dropout excluded —
the TPU PRNG has no interpret lowering); the `tpu` marker cases cover the
compiled Mosaic path including in-kernel dropout. Oracle: the primitive
softmax composition (which is also the op's off-TPU lowering), matching
reference semantics of fused attention (operators/fused/ role)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.kernels import flash_attention, flash_attention_with_lse

RNG = np.random.RandomState(3)
HP = jax.lax.Precision.HIGHEST


def _ref(q, k, v, bias=None, causal=False, num_heads=1):
    D = q.shape[-1]
    s = jnp.einsum("bqd,bkd->bqk", q, k, precision=HP) * (D ** -0.5)
    if bias is not None:
        s = s + jnp.repeat(bias, num_heads, axis=0)[:, None, :]
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        m = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(m[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v, precision=HP)


def _qkv(BH=4, S=256, D=64):
    return tuple(jnp.asarray(RNG.randn(BH, S, D).astype(np.float32))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("use_bias", [False, True])
def test_kernel_forward_matches_reference(causal, use_bias):
    q, k, v = _qkv()
    H = 2
    bias = (jnp.asarray(np.where(RNG.rand(2, 256) > 0.25, 0.0,
                                 -10000.0).astype(np.float32))
            if use_bias else None)
    o = flash_attention(q, k, v, bias=bias, causal=causal, num_heads=H,
                        interpret=True)
    o_ref = _ref(q, k, v, bias, causal, num_heads=H)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               atol=2e-5, rtol=1e-4)


def test_kernel_gradients_match_reference():
    q, k, v = _qkv(BH=2, S=128)
    bias = jnp.asarray(np.where(RNG.rand(2, 128) > 0.25, 0.0,
                                -10000.0).astype(np.float32))

    def loss_flash(q, k, v):
        return jnp.sum(jnp.tanh(flash_attention(
            q, k, v, bias=bias, num_heads=1, interpret=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.tanh(_ref(q, k, v, bias, num_heads=1)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3)


def test_lse_combination_differentiates():
    """The ring-attention contract: splitting keys in two kernel calls and
    recombining through lse must equal whole attention — for values AND
    gradients (the kernel honours the lse cotangent)."""
    q, k, v = _qkv(BH=2, S=256)
    k1, k2, v1, v2 = k[:, :128], k[:, 128:], v[:, :128], v[:, 128:]

    def combined(q, k1, k2, v1, v2):
        o1, l1 = flash_attention_with_lse(q, k1, v1, interpret=True)
        o2, l2 = flash_attention_with_lse(q, k2, v2, interpret=True)
        l = jnp.logaddexp(l1, l2)
        o = (o1 * jnp.exp(l1 - l)[..., None]
             + o2 * jnp.exp(l2 - l)[..., None])
        return jnp.sum(jnp.tanh(o))

    def whole(q, k1, k2, v1, v2):
        return jnp.sum(jnp.tanh(_ref(q, jnp.concatenate([k1, k2], 1),
                                     jnp.concatenate([v1, v2], 1))))

    np.testing.assert_allclose(combined(q, k1, k2, v1, v2),
                               whole(q, k1, k2, v1, v2), rtol=1e-5)
    g1 = jax.grad(combined, argnums=(0, 1, 2, 3, 4))(q, k1, k2, v1, v2)
    g2 = jax.grad(whole, argnums=(0, 1, 2, 3, 4))(q, k1, k2, v1, v2)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3)


def test_fully_masked_rows_zero_output_and_grads():
    """A query whose every key is CAUSALLY masked (all keys in the future,
    the ring-attention first-block case): O = 0, grads = 0, no NaNs.

    (An all--10000 additive bias is NOT this case: constant shifts cancel
    in softmax, so such rows attend uniformly — matching the primitive
    path's semantics.)"""
    from paddle_tpu.kernels import flash_attention_with_lse

    q, k, v = _qkv(BH=2, S=128)

    def run(q, k, v):
        # k_offset=128 > every q position -> every key masked for every row
        return flash_attention_with_lse(q, k, v, causal=True,
                                        q_offset=0, k_offset=128,
                                        interpret=True)

    o, lse = run(q, k, v)
    assert bool(jnp.all(o == 0.0))
    assert bool(jnp.all(jnp.isneginf(lse)))
    g = jax.grad(lambda *a: jnp.sum(run(*a)[0]), argnums=(0, 1, 2))(q, k, v)
    for a in g:
        assert bool(jnp.all(jnp.isfinite(a)))
        assert bool(jnp.all(a == 0.0))


def test_op_level_kernel_vs_primitive_path():
    """The registered op under FLAGS_use_flash_attention=always (interpret
    kernel) must match =never (primitive path) through a whole Program."""
    from paddle_tpu import flags

    def run(mode):
        flags.set_flags({"FLAGS_use_flash_attention": mode})
        try:
            with un.guard():
                main, startup = fluid.Program(), fluid.Program()
                with fluid.program_guard(main, startup):
                    q = fluid.layers.data("q", shape=[2, 128, 32],
                                          dtype="float32")
                    k = fluid.layers.data("k", shape=[2, 128, 32],
                                          dtype="float32")
                    v = fluid.layers.data("v", shape=[2, 128, 32],
                                          dtype="float32")
                    m = fluid.layers.data("m", shape=[128], dtype="float32")
                    out = fluid.layers.fused_multihead_attention(
                        q, k, v, bias_qk=m, is_test=True)
                    loss = fluid.layers.mean(out)
                exe = fluid.Executor(fluid.CPUPlace())
                scope = fluid.Scope()
                rng = np.random.RandomState(5)
                feed = {n: rng.randn(3, 2, 128, 32).astype(np.float32)
                        for n in ("q", "k", "v")}
                feed["m"] = np.where(rng.rand(3, 128) > 0.3, 0.0,
                                     -10000.0).astype(np.float32)
                with fluid.scope_guard(scope):
                    exe.run(startup)
                    res = exe.run(main, feed=feed,
                                  fetch_list=[out.name, loss.name])
                return [np.asarray(r) for r in res]
        finally:
            flags.set_flags({"FLAGS_use_flash_attention": "auto"})

    o_kernel, l_kernel = run("always")
    o_prim, l_prim = run("never")
    np.testing.assert_allclose(o_kernel, o_prim, atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(l_kernel, l_prim, rtol=1e-5)


def test_bert_attention_uses_fused_op():
    """models/bert.py emits fused_multihead_attention, not the unfused
    matmul/softmax chain."""
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    with un.guard():
        model = build_bert_pretrain(BertConfig.tiny(), seq_len=128,
                                    build_optimizer=False)
    types = [op.type for op in model["main"].global_block.ops]
    assert types.count("fused_multihead_attention") == 2  # tiny: 2 layers
    assert "softmax" not in types  # attention softmax is inside the op


@pytest.mark.tpu
def test_tpu_compiled_kernel_and_dropout():
    """Compiled Mosaic path on the real chip: numerics + in-kernel PRNG
    dropout determinism (same seed -> same mask in fwd and recompute)."""
    q, k, v = _qkv(BH=2, S=256)
    o = flash_attention(q, k, v, num_heads=1)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=1e-4)
    o1 = flash_attention(q, k, v, dropout_rate=0.5, seed=7, num_heads=1)
    o2 = flash_attention(q, k, v, dropout_rate=0.5, seed=7, num_heads=1)
    o3 = flash_attention(q, k, v, dropout_rate=0.5, seed=8, num_heads=1)
    assert bool(jnp.all(o1 == o2))
    assert not bool(jnp.all(o1 == o3))
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, dropout_rate=0.1, seed=3, num_heads=1)))(q)
    assert bool(jnp.all(jnp.isfinite(g)))
