"""Test env: force the JAX CPU backend with 8 virtual devices so multi-chip
sharding paths compile and run without TPU hardware (SURVEY.md §4: the
fake-device story the reference lacks).

NOTE: this environment's sitecustomize (axon TPU tunnel) imports jax at
interpreter startup, so setting env vars here is too late — use jax.config
updates instead, which work as long as no backend is initialized yet."""
import os

os.environ.setdefault("JAX_ENABLE_X64", "0")
# persistent XLA compile cache: op-test programs compile once ever
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# static program verification before every executor run (the analysis
# subsystem's opt-in hook, on by default for the suite; docs/ANALYSIS.md)
os.environ.setdefault("FLAGS_check_program", "1")

import jax  # noqa: E402

if os.environ.get("PADDLE_TPU_TESTS") != "1":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax (<0.5) spells it via XLA_FLAGS; the env var is read at
        # backend init, which hasn't happened yet even though sitecustomize
        # imported jax at interpreter startup
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: needs a real accelerator; run with PADDLE_TPU_TESTS=1 "
        "pytest -m tpu (skipped on the CPU suite)")
    config.addinivalue_line(
        "markers",
        "known_flaky(reason): order/state-dependent pre-existing flake "
        "documented in KNOWN_FAILURES.md — the reason cross-references "
        "the triage entry. NOT skipped and NOT retried (the tests still "
        "run and usually pass); the marker makes tier-1 triage "
        "mechanical: `pytest -m known_flaky --collect-only -q` lists "
        "exactly the tests allowed to account for a ±1 swing in the "
        "pass count")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _no_global_clip_leak():
    """set_gradient_clip is process-global (reference keeps it per-program);
    a test that sets it and fails before resetting would silently reshape
    every later test's training. Clear it after each test."""
    yield
    from paddle_tpu import clip

    clip._clip_attr["__global__"] = None


@pytest.fixture(autouse=True)
def _pass_registry_isolation():
    """The analysis PassRegistry is process-global (like the flags and the
    clip attr above): a test registering a custom pass, or overriding a
    built-in, must not leak it into the rest of the suite. Snapshot the
    registration table before each test, restore it after, and drop any
    shared PassContext analysis caches."""
    from paddle_tpu.analysis import pass_manager as pm

    reg = pm.get_pass_registry()
    snap = reg.snapshot()
    yield
    reg.restore(snap)
    pm.clear_analysis_caches()


def pytest_collection_modifyitems(config, items):
    import pytest

    on_accel = any(d.platform != "cpu" for d in jax.devices())
    skip = pytest.mark.skip(reason="no accelerator (set PADDLE_TPU_TESTS=1 "
                                   "outside the forced-CPU suite)")
    for item in items:
        if "tpu" in item.keywords and not on_accel:
            item.add_marker(skip)
