"""Test env: force the JAX CPU backend with 8 virtual devices so multi-chip
sharding paths compile and run without TPU hardware (SURVEY.md §4: the
fake-device story the reference lacks). MUST run before jax initialises."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# fp64 off (TPU-like); tests use fp32 tolerances
os.environ.setdefault("JAX_ENABLE_X64", "0")
