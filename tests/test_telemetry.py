"""Fleet telemetry plane (paddle_tpu.serving.fleet.telemetry +
monitor exemplars/merge + serving SLO/tenant hooks).

Covers the ISSUE-18 contract in-process: the two ``/metrics`` forms and
their frozen JSON schema, Prometheus exposition-format conformance
round-tripped through the scrape-side parser (hostile label fuzz), the
EXACT histogram merge property, the SLO burn-rate tracker's multi-window
state machine, per-tenant accounting exactness, trace exemplars (and
their disabled-path non-allocation), and the FleetAggregator's typed
scrape-failure degradation. The multi-process leg is
``tools/load_check.py --fleet`` (``leg_fleet_telemetry``)."""
import http.client
import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.monitor.registry import MetricsRegistry
from paddle_tpu.serving.fleet import (AggregatorConfig, FleetAggregator,
                                      ServingFrontend, WireError, telemetry,
                                      wire)
from paddle_tpu.serving.slo import SloBurnTracker


@pytest.fixture(autouse=True)
def _flags_reset():
    from paddle_tpu import flags as flags_mod

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    flags_mod._set_epoch += 1


def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(**cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = serving.ServingConfig(max_batch=cfg_kw.pop("max_batch", 4),
                                **cfg_kw)
    return serving.ServingEngine(infer, feed_names=["x"],
                                 fetch_list=[pred], scope=scope,
                                 executor=exe, config=cfg)


def _feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


@pytest.fixture()
def frontend():
    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    fe = ServingFrontend(eng, replica_id="t0")
    fe.start()
    yield fe
    fe.stop(wait_inflight_s=2.0)
    eng.stop(drain=False)


def _get_raw(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.getheader("Content-Type"), resp.read()
    finally:
        conn.close()


def _post_submit(port, body, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/submit", body=wire.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, wire.loads(resp.read())
    finally:
        conn.close()


def _stub_server(holder):
    """An HTTP stub answering every GET with ``holder['body']`` /
    ``holder['status']`` — the aggregator's hostile-peer stand-in."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = holder["body"]
            self.send_response(holder.get("status", 200))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


# ---------------------------------------------------------------------------
# /metrics routes + frozen JSON schema
# ---------------------------------------------------------------------------

def test_metrics_route_serves_prometheus_text(frontend):
    frontend.engine.submit(_feed()).result(timeout=60)
    status, ctype, raw = _get_raw(frontend.port, "/metrics")
    assert status == 200
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    parsed = monitor.parse_prometheus_text(raw)
    assert "serving_requests_total" in parsed
    assert parsed["serving_requests_total"].kind == "counter"
    assert "serving_request_latency_seconds" in parsed


def test_metrics_json_route_schema_frozen(frontend):
    frontend.engine.submit(_feed()).result(timeout=60)
    for path in ("/metrics.json", "/metrics?format=json"):
        status, ctype, raw = _get_raw(frontend.port, path)
        assert status == 200
        assert ctype == "application/json"
        doc = json.loads(raw.decode("utf-8"))
        # the key set is FROZEN exactly like the health payload: any
        # drift is a schema-version bump, not a silent addition
        assert set(doc) == set(telemetry.METRICS_SCHEMA_KEYS)
        assert doc["schema_version"] == telemetry.METRICS_SCHEMA_VERSION
        assert doc["replica_id"] == "t0"
        assert "serving_requests_total" in doc["families"]
        assert doc["slo"]["state"] in ("ok", "warning", "burning")
        assert isinstance(doc["tenants"], dict)


def test_metrics_probe_route_immune_to_wire_faults(frontend):
    """/metrics (like /healthz) is a probe route: response fault plans
    must not touch it — telemetry stays observable under chaos."""
    from paddle_tpu.resilience import fault_plan_guard

    with fault_plan_guard("wire_response:99:RuntimeError"):
        status, _, raw = _get_raw(frontend.port, "/metrics")
        assert status == 200 and raw


# ---------------------------------------------------------------------------
# fleet_request_seconds route label (satellite regression)
# ---------------------------------------------------------------------------

def test_fleet_request_seconds_labeled_by_route(frontend):
    status, _ = _post_submit(frontend.port,
                             {"feed": wire.encode_feed(_feed())})
    assert status == 200
    fam = monitor.get_registry().get("fleet_request_seconds")
    assert fam is not None and fam.kind == "histogram"
    label_sets = [labels for labels, _ in fam.children()]
    assert {"route": "submit"} in label_sets
    # every child carries the route label — no unlabeled series left
    # (submit vs generate vs future routes stay distinguishable)
    assert all("route" in labels for labels in label_sets)
    snap = monitor.metric_value("fleet_request_seconds", route="submit")
    assert snap["count"] >= 1


# ---------------------------------------------------------------------------
# Prometheus exposition conformance: escape + round-trip fuzz
# ---------------------------------------------------------------------------

HOSTILE_LABELS = ["plain", "back\\slash", "new\nline", 'quo"te',
                  "both\\\"\n", "trailing\\", "uni·codé",
                  "le=\"0.5\"} fake 1"]


def test_prom_text_roundtrips_hostile_label_values():
    reg = MetricsRegistry()
    c = reg.counter("fuzz_total", 'help with \\, a\nnewline and "quotes"')
    for i, v in enumerate(HOSTILE_LABELS):
        c.labels(tenant=v).inc(i + 1)
    parsed = monitor.parse_prometheus_text(reg.to_prometheus())
    fam = parsed["fuzz_total"]
    assert fam.help == 'help with \\, a\nnewline and "quotes"'
    for i, v in enumerate(HOSTILE_LABELS):
        assert fam.value(tenant=v) == i + 1


def test_prom_text_type_lines_for_labeled_gauges():
    reg = MetricsRegistry()
    reg.gauge("g_labeled", "labeled gauge").labels(replica="r0").set(2.0)
    text = reg.to_prometheus()
    assert "# TYPE g_labeled gauge" in text
    parsed = monitor.parse_prometheus_text(text)
    assert parsed["g_labeled"].kind == "gauge"
    assert parsed["g_labeled"].value(replica="r0") == 2.0


def test_prom_histogram_roundtrips_through_scrape_parser():
    reg = MetricsRegistry()
    h = reg.histogram("rt_seconds", "round trip", buckets=(0.5, 1.0, 2.0))
    for v in (0.25, 0.75, 1.5, 9.0):
        h.observe(v)
    parsed = monitor.parse_prometheus_text(reg.to_prometheus())
    snap = monitor.histogram_snapshot_from_samples(parsed["rt_seconds"])
    direct = reg.get("rt_seconds")._children[()].snapshot()
    assert snap["count"] == direct["count"] == 4
    assert snap["sum"] == pytest.approx(direct["sum"])
    assert snap["buckets"] == direct["buckets"]


def test_prom_parser_refuses_garbage():
    with pytest.raises(monitor.PromParseError):
        monitor.parse_prometheus_text(b"\x00\xffdefinitely{not metrics")


# ---------------------------------------------------------------------------
# exact histogram merge (satellite property test)
# ---------------------------------------------------------------------------

def test_histogram_merge_equals_union_stream():
    """merge(a, b) must equal ONE histogram that observed the union
    stream: count, sum, every cumulative bucket, p50/p99. Values are
    binary-exact (multiples of 1/64) so float summation order cannot
    blur the equality."""
    buckets = (0.25, 0.5, 1.0, 2.0)
    rng = np.random.RandomState(7)
    stream_a = [int(x) / 64.0 for x in rng.randint(1, 160, size=57)]
    stream_b = [int(x) / 64.0 for x in rng.randint(1, 160, size=43)]

    reg = MetricsRegistry()
    ha = reg.histogram("ha", "", buckets=buckets)
    hb = reg.histogram("hb", "", buckets=buckets)
    hu = reg.histogram("hu", "", buckets=buckets)
    for v in stream_a:
        ha.observe(v)
    for v in stream_b:
        hb.observe(v)
    for v in stream_a + stream_b:
        hu.observe(v)
    snap_a = reg.get("ha")._children[()].snapshot()
    snap_b = reg.get("hb")._children[()].snapshot()
    union = reg.get("hu")._children[()].snapshot()

    merged = monitor.merge_histogram_snapshots([snap_a, snap_b])
    assert merged["count"] == union["count"] == 100
    assert merged["sum"] == pytest.approx(union["sum"])
    assert merged["buckets"] == union["buckets"]
    assert merged["min"] == union["min"]
    assert merged["max"] == union["max"]
    assert merged["p50"] == pytest.approx(union["p50"])
    assert merged["p99"] == pytest.approx(union["p99"])


def test_histogram_merge_refuses_mismatched_buckets():
    reg = MetricsRegistry()
    h1 = reg.histogram("m1", "", buckets=(0.5, 1.0))
    h2 = reg.histogram("m2", "", buckets=(0.25, 1.0))
    h1.observe(0.3)
    h2.observe(0.3)
    s1 = reg.get("m1")._children[()].snapshot()
    s2 = reg.get("m2")._children[()].snapshot()
    with pytest.raises(ValueError):
        monitor.merge_histogram_snapshots([s1, s2])


# ---------------------------------------------------------------------------
# SLO burn-rate tracker
# ---------------------------------------------------------------------------

def test_slo_tracker_multiwindow_state_machine():
    t = [1000.0]
    tr = SloBurnTracker({"standard": 0.5}, error_budget=0.1,
                        fast_window_s=10.0, slow_window_s=60.0,
                        _now=lambda: t[0])
    for _ in range(20):
        tr.observe(1, 0.1, error=False)
    s = tr.state()
    assert s["state"] == "ok"
    assert s["classes"]["standard"]["fast_burn"] == 0.0
    # bads: errors AND too-slow completions both consume budget
    for _ in range(3):
        tr.observe(1, None, error=True)
    tr.observe(1, 0.9, error=False)   # completed, but slower than target
    s = tr.state()
    assert s["state"] == "burning"    # both windows hot
    assert s["classes"]["standard"]["bad"] == 4
    t[0] += 15.0                      # bads leave the FAST window only
    s = tr.state()
    assert s["state"] == "warning"
    t[0] += 60.0                      # ...and then the slow window
    s = tr.state()
    assert s["state"] == "ok"


def test_slo_state_rides_health_payload():
    eng = _engine()
    try:
        assert "slo" in serving.HEALTH_SCHEMA_KEYS
        h = eng.health()
        assert h["slo"]["state"] == "ok"
        assert set(h["slo"]["classes"]) == {"batch", "standard",
                                            "interactive"}
    finally:
        eng.stop(drain=False)


# ---------------------------------------------------------------------------
# per-tenant accounting
# ---------------------------------------------------------------------------

def test_wire_tenant_field_validation():
    assert wire.resolve_tenant({}) is None
    assert wire.resolve_tenant({"tenant": "  "}) is None
    assert wire.resolve_tenant({"tenant": "a-b_c.d:e@f"}) == "a-b_c.d:e@f"
    with pytest.raises(WireError):
        wire.resolve_tenant({"tenant": 7})
    with pytest.raises(WireError):
        wire.resolve_tenant({"tenant": "x" * 65})
    with pytest.raises(WireError):
        wire.resolve_tenant({"tenant": "sp ace"})
    with pytest.raises(WireError):
        wire.resolve_tenant({"tenant": 'quo"te{}'})


def test_tenant_ledger_sums_exactly_to_accounting():
    from paddle_tpu.serving.engine import DEFAULT_TENANT

    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    try:
        futs = [eng.submit(_feed(seed=i), tenant="acme") for i in range(3)]
        futs += [eng.submit(_feed(seed=9), tenant="globex")]
        futs += [eng.submit(_feed(seed=10))]          # default tenant
        for f in futs:
            f.result(timeout=60)
        ledger = eng.tenant_accounting()
        assert ledger["acme"]["outcomes"]["completed"] == 3
        assert ledger["globex"]["outcomes"]["completed"] == 1
        assert ledger[DEFAULT_TENANT]["outcomes"]["completed"] >= 1
        assert all(t["occupancy_s"] > 0 for t in ledger.values())
        # the reconciliation invariant: tenant outcome sums == the
        # engine's own terminal ledger, outcome by outcome
        sums = {}
        for t in ledger.values():
            for o, n in t["outcomes"].items():
                sums[o] = sums.get(o, 0) + n
        assert sums == {"completed": eng.accounting()["completed"]}
    finally:
        eng.stop(drain=False)


# ---------------------------------------------------------------------------
# trace exemplars
# ---------------------------------------------------------------------------

def test_exemplars_recorded_when_plane_enabled():
    fluid.set_flags({"FLAGS_fleet_telemetry": 1, "FLAGS_trace": 1})
    monitor.reset()
    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    try:
        fut = eng.submit(_feed())
        fut.result(timeout=60)
        fam = monitor.get_registry().get("serving_request_latency_seconds")
        (_, child), = fam.children()
        ex = child.exemplars()
        assert ex, "enabled plane must record exemplars"
        rings = [e for ring in ex.values() for e in ring]
        assert any(e["trace_id"] == fut.trace_id for e in rings)
        # and they ride the JSON form only
        doc = telemetry.metrics_json(replica_id="x")
        assert "serving_request_latency_seconds" in doc["exemplars"]
        assert "exemplar" not in monitor.get_registry().to_prometheus()
    finally:
        eng.stop(drain=False)


def test_exemplars_disabled_path_never_allocates():
    monitor.reset()
    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    try:
        eng.submit(_feed()).result(timeout=60)
        fam = monitor.get_registry().get("serving_request_latency_seconds")
        (_, child), = fam.children()
        # not "empty exemplars" — NO ring storage at all (the observe
        # path passed exemplar=None, the true-no-op contract)
        assert child._exemplars is None
        assert child.exemplars() == {}
        doc = telemetry.metrics_json(replica_id="x")
        assert doc["exemplars"] == {}
    finally:
        eng.stop(drain=False)


# ---------------------------------------------------------------------------
# FleetAggregator
# ---------------------------------------------------------------------------

def test_aggregator_disabled_start_is_noop():
    agg = FleetAggregator([("r0", "127.0.0.1:1")])
    assert agg.start() is agg
    assert agg._thread is None          # no scrape thread while off
    agg.stop()


def test_aggregator_scrapes_live_frontend_both_modes(frontend):
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    for _ in range(3):
        frontend.engine.submit(_feed()).result(timeout=60)
    for mode in ("json", "prom"):
        agg = FleetAggregator(
            [("t0", f"127.0.0.1:{frontend.port}")],
            AggregatorConfig(scrape_interval_s=60.0, scrape_timeout_s=10.0,
                             mode=mode))
        agg.poll_now()
        snap = agg.snapshot()
        rec = snap["replicas"]["t0"]
        assert rec["up"] and not rec["stale"]
        assert rec["scrape_age_s"] < 60.0
        assert rec["outcomes"]["completed"] >= 3
        assert snap["fleet"]["p50"] is not None
        assert snap["fleet"]["latency"]["count"] >= 3
        assert monitor.metric_value("fleet_agg_up", replica="t0") == 1.0
    # the JSON mode additionally carries SLO + tenants over the wire
    agg = FleetAggregator([("t0", f"127.0.0.1:{frontend.port}")],
                          AggregatorConfig(scrape_interval_s=60.0,
                                           scrape_timeout_s=10.0))
    agg.poll_now()
    rec = agg.snapshot()["replicas"]["t0"]
    assert rec["slo"]["state"] in ("ok", "warning", "burning")
    assert rec["tenants"] is not None


def test_aggregator_typed_connect_failure_degrades_stale():
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()                           # nobody listens here anymore
    agg = FleetAggregator([("gone", f"127.0.0.1:{dead_port}")],
                          AggregatorConfig(scrape_interval_s=60.0,
                                           scrape_timeout_s=2.0))
    agg.poll_now()
    agg.poll_now()
    rec = agg.snapshot()["replicas"]["gone"]
    assert rec["up"] is False and rec["stale"] is True
    assert rec["error"] == "connect"
    assert rec["consecutive_failures"] == 2
    assert monitor.metric_value("fleet_scrape_failures_total", default=0,
                                replica="gone", kind="connect") >= 2


def test_aggregator_corrupt_body_keeps_last_good_snapshot():
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    reg = MetricsRegistry()
    h = reg.histogram(telemetry.REQUEST_LATENCY_METRIC, "lat")
    h.observe(0.2)
    holder = {"body": json.dumps(
        telemetry.metrics_json(registry=reg, replica_id="s0")
    ).encode("utf-8")}
    srv, port = _stub_server(holder)
    try:
        agg = FleetAggregator([("s0", f"127.0.0.1:{port}")],
                              AggregatorConfig(scrape_interval_s=60.0,
                                               scrape_timeout_s=10.0))
        agg.poll_now()
        rec = agg.snapshot()["replicas"]["s0"]
        assert rec["up"] and rec["latency"]["count"] == 1

        holder["body"] = b"\x00\xffnot a metrics body"
        agg.poll_now()
        rec = agg.snapshot()["replicas"]["s0"]
        # degraded, typed — but the LAST GOOD latency data survives,
        # marked stale with a growing age
        assert rec["up"] is False and rec["stale"] is True
        assert rec["error"] == "corrupt"
        assert rec["consecutive_failures"] == 1
        assert rec["latency"]["count"] == 1
        assert agg.snapshot()["fleet"]["p50"] is not None
        assert monitor.metric_value(
            "fleet_scrape_failures_total", default=0,
            replica="s0", kind="corrupt") >= 1
    finally:
        srv.shutdown()


def test_aggregator_refuses_newer_schema_as_corrupt():
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    holder = {"body": json.dumps(
        {"schema_version": telemetry.METRICS_SCHEMA_VERSION + 1,
         "families": {}}).encode("utf-8")}
    srv, port = _stub_server(holder)
    try:
        agg = FleetAggregator([("vnew", f"127.0.0.1:{port}")],
                              AggregatorConfig(scrape_interval_s=60.0,
                                               scrape_timeout_s=10.0))
        agg.poll_now()
        rec = agg.snapshot()["replicas"]["vnew"]
        assert rec["error"] == "corrupt" and rec["stale"] is True
    finally:
        srv.shutdown()


def test_aggregator_counter_reset_clamps_rate():
    """A restarted replica's counters drop to zero: the windowed delta
    must clamp to the new absolute value, never go negative."""
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})

    def doc(completed):
        return json.dumps({
            "schema_version": 1, "replica_id": "s1", "exemplars": {},
            "slo": None, "tenants": None,
            "families": {"serving_requests_total": {
                "kind": "counter", "help": "",
                "values": [{"labels": {"outcome": "completed"},
                            "value": completed}]}}}).encode("utf-8")

    holder = {"body": doc(50)}
    srv, port = _stub_server(holder)
    try:
        agg = FleetAggregator([("s1", f"127.0.0.1:{port}")],
                              AggregatorConfig(scrape_interval_s=60.0,
                                               scrape_timeout_s=10.0))
        agg.poll_now()
        holder["body"] = doc(2)          # restart: 50 -> 2
        agg.poll_now()
        rec = agg.snapshot()["replicas"]["s1"]
        rate = rec["rates"]["serving_requests_total"]["outcome=completed"]
        assert rate > 0                  # clamped to the new absolute
        assert rec["counters"]["serving_requests_total"][
            "outcome=completed"] == 2
    finally:
        srv.shutdown()


def test_aggregator_fleet_rollup_sums_and_worst_slo(frontend):
    fluid.set_flags({"FLAGS_fleet_telemetry": 1})
    frontend.engine.submit(_feed()).result(timeout=60)
    # second "replica": a stub replaying a burning registry
    reg = MetricsRegistry()
    reg.histogram(telemetry.REQUEST_LATENCY_METRIC, "lat").observe(0.1)
    reg.counter(telemetry.OUTCOME_COUNTER, "").labels(
        outcome="completed").inc(7)
    holder = {"body": json.dumps(telemetry.metrics_json(
        registry=reg, replica_id="s2",
        slo={"state": "burning", "classes": {}})).encode("utf-8")}
    srv, port = _stub_server(holder)
    try:
        agg = FleetAggregator(
            [("t0", f"127.0.0.1:{frontend.port}"),
             ("s2", f"127.0.0.1:{port}")],
            AggregatorConfig(scrape_interval_s=60.0, scrape_timeout_s=10.0))
        agg.poll_now()
        snap = agg.snapshot()
        fleet = snap["fleet"]
        n_t0 = snap["replicas"]["t0"]["outcomes"]["completed"]
        assert fleet["outcomes"]["completed"] == n_t0 + 7
        assert fleet["latency"]["count"] == \
            snap["replicas"]["t0"]["latency"]["count"] + 1
        assert fleet["slo_state"] == "burning"   # the WORST across replicas
        assert monitor.metric_value(
            "fleet_agg_slo_state",
            replica=telemetry.FLEET_LABEL) == 2.0
    finally:
        srv.shutdown()
