"""Executor.run_chained — K scanned steps must equal K separate run() calls.

This is the compiled-train-loop role (reference trainer.cc RunFromDataset
runs the loop outside Python) and the measurement substrate for bench.py:
iterations inside one dispatch are serialized by while-loop semantics, so
timing it measures compute, not dispatch rate.
"""
import numpy as np

import paddle_tpu as fluid


def _build(with_bn=False):
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=8, act="relu")
    if with_bn:
        h = fluid.layers.batch_norm(input=h)
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _feed():
    rng = np.random.RandomState(3)
    return {"x": rng.rand(8, 4).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}


def test_chained_matches_sequential_runs():
    for with_bn in (False, True):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            loss = _build(with_bn)
            main, startup = (fluid.default_main_program(),
                             fluid.default_startup_program())
            feed = _feed()
            exe = fluid.Executor(fluid.CPUPlace())

            s1 = fluid.Scope()
            with fluid.scope_guard(s1):
                exe.run(startup)
                seq = [float(np.asarray(exe.run(main, feed=feed,
                                                fetch_list=[loss])[0]))
                       for _ in range(4)]
            exe2 = fluid.Executor(fluid.CPUPlace())
            s2 = fluid.Scope()
            with fluid.scope_guard(s2):
                exe2.run(startup)
                chained = exe2.run_chained(main, feed=feed,
                                           fetch_list=[loss], steps=4)
            got = np.asarray(chained[0]).reshape(-1)
            assert got.shape == (4,)
            # same math modulo per-step dropout keys (none here) — the loss
            # trajectory must match the sequential path step for step
            np.testing.assert_allclose(got, seq, rtol=2e-5, atol=1e-6)
            # final state matches too (params after 4 updates)
            params = [v.name for v in main.global_block.vars.values()
                      if type(v).__name__ == "Parameter"]
            assert params
            for n in params:
                np.testing.assert_allclose(s1.numpy(n), s2.numpy(n),
                                           rtol=2e-5, atol=1e-6)


def test_chained_inference_no_state():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=x, size=2, act="softmax")
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": np.random.RandomState(0).rand(4, 4).astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            one = exe.run(infer, feed=feed, fetch_list=[pred])[0]
            stacked = exe.run_chained(infer, feed=feed, fetch_list=[pred],
                                      steps=3)[0]
        assert np.asarray(stacked).shape == (3,) + np.asarray(one).shape
        for i in range(3):
            np.testing.assert_allclose(np.asarray(stacked)[i],
                                       np.asarray(one), rtol=1e-6)


def test_chained_fetched_param_threads_without_donation():
    """A fetched parameter is donation-unsafe (PT500): run_chained must keep
    it OUT of the donated jit args but still thread it through the scan
    carry — reading it as a loop-invariant would hand every iteration the
    stale pre-run value."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        param = next(v.name for v in main.global_block.vars.values()
                     if type(v).__name__ == "Parameter"
                     and v.name.endswith(".w_0"))
        feed = _feed()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            stacked = exe.run_chained(main, feed=feed,
                                      fetch_list=[loss, param], steps=3)
        step = next(s for k, s in exe._cache.items() if k[0] == "chained")
        assert param not in step.donated_names  # liveness refused donation
        assert param in step.kept_names and param in step.carried_names
        ws = np.asarray(stacked[1])
        assert ws.shape[0] == 3
        # the param moves every step (carried, not loop-invariant), and the
        # scope ends at the last fetched value
        assert not np.array_equal(ws[0], ws[1])
        assert not np.array_equal(ws[1], ws[2])
        np.testing.assert_allclose(scope.numpy(param), ws[2], rtol=1e-6)


def test_scope_serial_keys_cache_not_id():
    """r5 advisor finding: the compile cache keyed on id(scope), which can
    alias after GC hands a dead scope's address to a fresh Scope. Scopes now
    carry a monotonic serial used in every executor cache key."""
    a, b = fluid.Scope(), fluid.Scope()
    assert a._serial != b._serial
    seen = {a._serial, b._serial}
    del a, b
    import gc

    gc.collect()
    c = fluid.Scope()
    assert c._serial not in seen  # serials never recycle, unlike id()


def test_chained_serializes_inference_with_identity_carry():
    """The r03->r05 ResNet-50 infer bench discontinuity (ISSUE 13
    satellite): a for_test clone's only carried state is identity-written
    batch_norm statistics (use_global_stats writes MeanOut = Mean), so the
    old `not carried` trigger skipped the anti-hoisting chain, XLA's
    while-loop simplifier saw the fixed-point carry, hoisted the body, and
    the chained per-step time differenced to ~zero. Non-training programs
    must now ALWAYS engage the chain — and stay numerically identical to
    single runs (the perturbation is runtime-zero)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build(with_bn=True)
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        infer = main.clone(for_test=True)
        feed = _feed()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            one = exe.run(infer, feed=feed, fetch_list=[loss.name])[0]
            stacked = exe.run_chained(infer, feed=feed,
                                      fetch_list=[loss.name], steps=3)[0]
            # training program for contrast: carried params chain it
            exe.run_chained(main, feed=feed, fetch_list=[loss.name],
                            steps=2, scope=scope)
    steps = {}
    for key, step in exe._cache.items():
        if key[0] == "chained":
            steps[key[1][0]] = step
    infer_step = steps[infer._serial]
    train_step = steps[main._serial]
    # the infer program carries BN stats (identity) yet must chain; the
    # training program chains through its genuinely-updated params
    assert infer_step.carried_names, "bn stats should be carried state"
    assert infer_step.needs_chain is True
    assert train_step.needs_chain is False
    for i in range(3):
        np.testing.assert_allclose(np.asarray(stacked)[i],
                                   np.asarray(one), rtol=1e-6)


def test_chained_feedless_state_program_no_hoist_warning():
    """A feed-less program whose per-step variation lives in persistable
    carried state (the GPT decode shape: KV caches / token carry) must
    not warn about hoisting — the body reads the carry it rewrites, so
    XLA cannot hoist it, and the warning would fire on every serving
    decode dispatch."""
    import warnings
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        v = fluid.layers.create_global_var(shape=[1], value=0.0,
                                           dtype="float32",
                                           persistable=True)
        fluid.layers.increment(v, value=1.0, in_place=True)
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            with warnings.catch_warnings():
                warnings.simplefilter("error", RuntimeWarning)
                stacked = exe.run_chained(main, fetch_list=[v.name],
                                          steps=3)[0]
    # genuinely serialized: each step sees the previous step's counter
    np.testing.assert_allclose(np.asarray(stacked).reshape(-1),
                               [1.0, 2.0, 3.0])
