"""Executor.run_chained — K scanned steps must equal K separate run() calls.

This is the compiled-train-loop role (reference trainer.cc RunFromDataset
runs the loop outside Python) and the measurement substrate for bench.py:
iterations inside one dispatch are serialized by while-loop semantics, so
timing it measures compute, not dispatch rate.
"""
import numpy as np

import paddle_tpu as fluid


def _build(with_bn=False):
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h = fluid.layers.fc(input=x, size=8, act="relu")
    if with_bn:
        h = fluid.layers.batch_norm(input=h)
    pred = fluid.layers.fc(input=h, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _feed():
    rng = np.random.RandomState(3)
    return {"x": rng.rand(8, 4).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}


def test_chained_matches_sequential_runs():
    for with_bn in (False, True):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            loss = _build(with_bn)
            main, startup = (fluid.default_main_program(),
                             fluid.default_startup_program())
            feed = _feed()
            exe = fluid.Executor(fluid.CPUPlace())

            s1 = fluid.Scope()
            with fluid.scope_guard(s1):
                exe.run(startup)
                seq = [float(np.asarray(exe.run(main, feed=feed,
                                                fetch_list=[loss])[0]))
                       for _ in range(4)]
            exe2 = fluid.Executor(fluid.CPUPlace())
            s2 = fluid.Scope()
            with fluid.scope_guard(s2):
                exe2.run(startup)
                chained = exe2.run_chained(main, feed=feed,
                                           fetch_list=[loss], steps=4)
            got = np.asarray(chained[0]).reshape(-1)
            assert got.shape == (4,)
            # same math modulo per-step dropout keys (none here) — the loss
            # trajectory must match the sequential path step for step
            np.testing.assert_allclose(got, seq, rtol=2e-5, atol=1e-6)
            # final state matches too (params after 4 updates)
            params = [v.name for v in main.global_block.vars.values()
                      if type(v).__name__ == "Parameter"]
            assert params
            for n in params:
                np.testing.assert_allclose(s1.numpy(n), s2.numpy(n),
                                           rtol=2e-5, atol=1e-6)


def test_chained_inference_no_state():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=x, size=2, act="softmax")
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        infer = main.clone(for_test=True)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": np.random.RandomState(0).rand(4, 4).astype(np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            one = exe.run(infer, feed=feed, fetch_list=[pred])[0]
            stacked = exe.run_chained(infer, feed=feed, fetch_list=[pred],
                                      steps=3)[0]
        assert np.asarray(stacked).shape == (3,) + np.asarray(one).shape
        for i in range(3):
            np.testing.assert_allclose(np.asarray(stacked)[i],
                                       np.asarray(one), rtol=1e-6)


def test_chained_fetched_param_threads_without_donation():
    """A fetched parameter is donation-unsafe (PT500): run_chained must keep
    it OUT of the donated jit args but still thread it through the scan
    carry — reading it as a loop-invariant would hand every iteration the
    stale pre-run value."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        param = next(v.name for v in main.global_block.vars.values()
                     if type(v).__name__ == "Parameter"
                     and v.name.endswith(".w_0"))
        feed = _feed()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            stacked = exe.run_chained(main, feed=feed,
                                      fetch_list=[loss, param], steps=3)
        step = next(s for k, s in exe._cache.items() if k[0] == "chained")
        assert param not in step.donated_names  # liveness refused donation
        assert param in step.kept_names and param in step.carried_names
        ws = np.asarray(stacked[1])
        assert ws.shape[0] == 3
        # the param moves every step (carried, not loop-invariant), and the
        # scope ends at the last fetched value
        assert not np.array_equal(ws[0], ws[1])
        assert not np.array_equal(ws[1], ws[2])
        np.testing.assert_allclose(scope.numpy(param), ws[2], rtol=1e-6)


def test_scope_serial_keys_cache_not_id():
    """r5 advisor finding: the compile cache keyed on id(scope), which can
    alias after GC hands a dead scope's address to a fresh Scope. Scopes now
    carry a monotonic serial used in every executor cache key."""
    a, b = fluid.Scope(), fluid.Scope()
    assert a._serial != b._serial
    seen = {a._serial, b._serial}
    del a, b
    import gc

    gc.collect()
    c = fluid.Scope()
    assert c._serial not in seen  # serials never recycle, unlike id()
