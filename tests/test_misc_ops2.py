"""Round-4 misc op batch vs numpy oracles (reference kernels cited in
paddle_tpu/ops/misc.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from op_test import OpTest
from paddle_tpu.core.registry import get_op_def
from paddle_tpu.lowering import LowerCtx

RNG = np.random.RandomState(9)


def run_op(op_type, ins, attrs=None):
    jins = {k: [None if v is None else jnp.asarray(v) for v in vs]
            for k, vs in ins.items()}
    return get_op_def(op_type).lower(LowerCtx(), jins, attrs or {})


class TestModifiedHuberLoss(OpTest):
    def setup(self):
        x = RNG.randn(8, 1).astype(np.float32)
        y = RNG.randint(0, 2, (8, 1)).astype(np.float32)
        inter = x * (2 * y - 1)
        loss = np.where(inter < -1, -4 * inter,
                        np.where(inter < 1, (1 - inter) ** 2, 0.0))
        self.op_type = "modified_huber_loss"
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"IntermediateVal": inter,
                        "Out": loss.astype(np.float32)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)


class TestBilinearTensorProduct(OpTest):
    def setup(self):
        x = RNG.randn(3, 4).astype(np.float32)
        y = RNG.randn(3, 5).astype(np.float32)
        w = RNG.randn(6, 4, 5).astype(np.float32)
        b = RNG.randn(6).astype(np.float32)
        want = np.einsum("bi,kij,bj->bk", x, w, y) + b
        self.op_type = "bilinear_tensor_product"
        self.inputs = {"X": x, "Y": y, "Weight": w, "Bias": b}
        self.outputs = {"Out": want.astype(np.float32)}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["X", "Y", "Weight"], "Out",
                        max_relative_error=2e-2)


class TestNorm(OpTest):
    def setup(self):
        x = RNG.randn(3, 5, 2).astype(np.float32)
        nrm = np.sqrt((x * x).sum(1, keepdims=True) + 1e-10)
        self.op_type = "norm"
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "epsilon": 1e-10}
        self.outputs = {"Out": x / nrm, "Norm": nrm}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)
        self.check_grad(["X"], "Out", max_relative_error=1e-2)


class TestRowConv(OpTest):
    def setup(self):
        x = RNG.randn(2, 6, 3).astype(np.float32)
        w = RNG.randn(3, 3).astype(np.float32)  # k=3 lookahead
        want = np.zeros_like(x)
        for t in range(6):
            for j in range(3):
                if t + j < 6:
                    want[:, t] += x[:, t + j] * w[j]
        self.op_type = "row_conv"
        self.inputs = {"X": x, "Filter": w}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)
        self.check_grad(["X", "Filter"], "Out", max_relative_error=2e-2)


def test_unique_and_counts():
    x = np.array([2, 3, 3, 1, 5, 2, 2], np.int64)
    res = run_op("unique_with_counts", {"X": [x]})
    uniq = np.asarray(res["Out"][0])
    idx = np.asarray(res["Index"][0])
    cnt = np.asarray(res["Count"][0])
    # first-occurrence order: 2, 3, 1, 5
    np.testing.assert_array_equal(uniq[:4], [2, 3, 1, 5])
    np.testing.assert_array_equal(uniq[idx], x)
    np.testing.assert_array_equal(cnt[:4], [3, 2, 1, 1])


def test_multiplex_strided_slice_linspace_fill():
    xs = [RNG.randn(4, 3).astype(np.float32) for _ in range(3)]
    ids = np.array([[2], [0], [1], [2]], np.int64)
    res = run_op("multiplex", {"Ids": [ids], "X": xs})["Out"][0]
    want = np.stack([xs[2][0], xs[0][1], xs[1][2], xs[2][3]])
    np.testing.assert_allclose(np.asarray(res), want)

    x = RNG.randn(4, 8).astype(np.float32)
    res = run_op("strided_slice", {"Input": [x]},
                 {"axes": [1], "starts": [1], "ends": [7], "strides": [2],
                  "infer_flags": [], "decrease_axis": []})["Out"][0]
    np.testing.assert_allclose(np.asarray(res), x[:, 1:7:2])

    res = run_op("linspace", {"Start": [np.float32(0)],
                              "Stop": [np.float32(1)],
                              "Num": [np.int32(5)]},
                 {"dtype": "float32"})["Out"][0]
    np.testing.assert_allclose(np.asarray(res), np.linspace(0, 1, 5))

    res = run_op("fill", {}, {"value": [1.0, 2.0, 3.0, 4.0],
                              "shape": [2, 2], "dtype": "float32"})["Out"][0]
    np.testing.assert_allclose(np.asarray(res), [[1, 2], [3, 4]])


def test_teacher_student_and_cvm_and_center_loss():
    x = RNG.randn(6).astype(np.float32)
    lbl = np.array([-2.0, -1.0, 0.3, 1.7, -2.0, 0.9], np.float32)
    res = np.asarray(run_op(
        "teacher_student_sigmoid_loss",
        {"X": [x.reshape(-1, 1)], "Label": [lbl.reshape(-1, 1)]},
        {})["Y"][0]).reshape(-1)
    base = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
    want = np.where(lbl < -1, base,
                    np.where(lbl < 0, base - x,
                             np.where(lbl < 1, 2 * base - x * lbl,
                                      2 * base - x - x * (lbl - 1))))
    np.testing.assert_allclose(res, want, rtol=1e-5)

    xc = np.abs(RNG.randn(3, 6)).astype(np.float32)
    y = np.asarray(run_op("cvm", {"X": [xc], "CVM": [np.ones((3, 2),
                                                             np.float32)]},
                          {"use_cvm": True})["Y"][0])
    np.testing.assert_allclose(y[:, 0], np.log(xc[:, 0] + 1), rtol=1e-5)
    np.testing.assert_allclose(y[:, 1],
                               np.log(xc[:, 1] + 1) - np.log(xc[:, 0] + 1),
                               rtol=1e-4, atol=1e-6)
    y2 = np.asarray(run_op("cvm", {"X": [xc], "CVM": [np.ones((3, 2),
                                                              np.float32)]},
                           {"use_cvm": False})["Y"][0])
    np.testing.assert_allclose(y2, xc[:, 2:])

    feat = RNG.randn(5, 4).astype(np.float32)
    labels = np.array([0, 1, 0, 2, 1], np.int64)
    centers = RNG.randn(3, 4).astype(np.float32)
    res = run_op("center_loss",
                 {"X": [feat], "Label": [labels], "Centers": [centers],
                  "CenterUpdateRate": [np.float32([0.5])]},
                 {"cluster_num": 3, "need_update": True})
    diff = feat - centers[labels]
    np.testing.assert_allclose(np.asarray(res["SampleCenterDiff"][0]), diff,
                               rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(res["Loss"][0]).reshape(-1),
        0.5 * (diff ** 2).sum(1), rtol=1e-5)


def test_add_position_encoding_and_conv_shift():
    x = RNG.randn(2, 4, 6).astype(np.float32)
    res = np.asarray(run_op("add_position_encoding", {"X": [x]},
                            {"alpha": 1.0, "beta": 1.0})["Out"][0])
    half = 3
    pos = np.arange(4)[:, None]
    div = 10000.0 ** (np.arange(half) / half)
    pe = np.concatenate([np.sin(pos / div), np.cos(pos / div)], 1)
    np.testing.assert_allclose(res, x + pe[None], rtol=1e-4, atol=1e-5)

    xm = RNG.randn(2, 5).astype(np.float32)
    ym = RNG.randn(2, 3).astype(np.float32)
    res = np.asarray(run_op("conv_shift", {"X": [xm], "Y": [ym]})["Out"][0])
    want = np.zeros_like(xm)
    for b in range(2):
        for i in range(5):
            for j in range(3):
                want[b, i] += xm[b, (i + j - 1) % 5] * ym[b, j]
    np.testing.assert_allclose(res, want, rtol=1e-5)


def test_label_smooth_one_hot_v2_cross_entropy2():
    x = np.eye(4, dtype=np.float32)[None].repeat(2, 0).reshape(8, 4)
    res = np.asarray(run_op("label_smooth", {"X": [x]},
                            {"epsilon": 0.1})["Out"][0])
    np.testing.assert_allclose(res, 0.9 * x + 0.1 / 4, rtol=1e-6)

    ids = np.array([[0, 2], [3, 1]], np.int64)
    res = np.asarray(run_op("one_hot_v2", {"X": [ids]},
                            {"depth": 4, "dtype": "float32"})["Out"][0])
    assert res.shape == (2, 2, 4)
    assert res[0, 1, 2] == 1.0 and res[1, 0, 3] == 1.0

    probs = np.abs(RNG.rand(5, 4)).astype(np.float32)
    probs /= probs.sum(1, keepdims=True)
    lbl = RNG.randint(0, 4, (5, 1)).astype(np.int64)
    res = run_op("cross_entropy2", {"X": [probs], "Label": [lbl]},
                 {"ignore_index": -100})
    want = -np.log(np.take_along_axis(probs, lbl, 1))
    np.testing.assert_allclose(np.asarray(res["Y"][0]), want, rtol=1e-5)


def test_fsp_and_squared_l2_distance_and_minus():
    x = RNG.randn(2, 3, 4, 4).astype(np.float32)
    y = RNG.randn(2, 5, 4, 4).astype(np.float32)
    res = np.asarray(run_op("fsp", {"X": [x], "Y": [y]})["Out"][0])
    want = np.einsum("bch,bdh->bcd", x.reshape(2, 3, 16),
                     y.reshape(2, 5, 16)) / 16
    np.testing.assert_allclose(res, want, rtol=1e-4, atol=1e-5)

    a = RNG.randn(4, 3).astype(np.float32)
    b = RNG.randn(4, 3).astype(np.float32)
    res = run_op("squared_l2_distance", {"X": [a], "Y": [b]})
    np.testing.assert_allclose(np.asarray(res["Out"][0]).reshape(-1),
                               ((a - b) ** 2).sum(1), rtol=1e-5)
    res = np.asarray(run_op("minus", {"X": [a], "Y": [b]})["Out"][0])
    np.testing.assert_allclose(res, a - b)
