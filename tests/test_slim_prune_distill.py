"""slim beyond QAT (VERDICT r5 missing #5): pruning (mask + shape-shrink),
distillation (merged teacher program + L2/FSP/soft-label losses), and the
SA search controller — reference contrib/slim/{prune,distillation,
searcher}."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.contrib.slim.distillation import (FSPDistiller, L2Distiller,
                                                  SoftLabelDistiller,
                                                  merge_teacher_program)
from paddle_tpu.contrib.slim.prune import (StructurePruner, prune_parameters,
                                           shrink_model)
from paddle_tpu.contrib.slim.searcher import SAController


def test_structure_pruner_idx_and_tensor():
    p = StructurePruner({"*": 0}, {"*": "l1_norm"})
    w = np.array([[1, 1], [5, 5], [0.1, 0.1], [3, 3]], np.float32)
    idx = p.cal_pruned_idx("w", w, 0.5)
    assert sorted(idx) == [0, 2]  # two smallest l1 rows
    masked = p.prune_tensor(w, idx, 0, lazy=True)
    assert masked.shape == w.shape and (masked[[0, 2]] == 0).all()
    shrunk = p.prune_tensor(w, idx, 0, lazy=False)
    assert shrunk.shape == (2, 2)
    np.testing.assert_allclose(shrunk, w[[1, 3]])


def _small_convnet():
    img = fluid.layers.data("img", shape=[3, 8, 8], dtype="float32")
    c1 = fluid.layers.conv2d(img, 8, 3, padding=1, act="relu",
                             param_attr=fluid.ParamAttr(name="c1w"),
                             bias_attr=fluid.ParamAttr(name="c1b"))
    c2 = fluid.layers.conv2d(c1, 4, 3, padding=1,
                             param_attr=fluid.ParamAttr(name="c2w"))
    pooled = fluid.layers.pool2d(c2, 8, "avg", 8)
    logits = fluid.layers.fc(fluid.layers.flatten(pooled), 5)
    return logits


def test_mask_prune_zeroes_channels_and_still_runs():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        with un.guard():
            logits = _small_convnet()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        xb = np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            pruned = prune_parameters(scope, {"c1w": 0.5})
            assert len(pruned["c1w"]) == 4
            w = scope.numpy("c1w")
            assert (w[pruned["c1w"]] == 0).all()
            out = exe.run(fluid.default_main_program(), feed={"img": xb},
                          fetch_list=[logits])
            assert np.isfinite(np.asarray(out[0])).all()


def test_shrink_model_removes_channels_end_to_end():
    """Shape-shrink: c1's out-channels 8 -> 4; c1 bias and c2's in-channels
    follow; the shrunk program runs and matches the masked program's
    output (removing zero channels is exact for conv->conv chains)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        with un.guard():
            logits = _small_convnet()
        main = fluid.default_main_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        xb = np.random.RandomState(1).rand(2, 3, 8, 8).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            # masked baseline output
            prune_parameters(scope, {"c1w": 0.5})
            # zero the pruned channels' biases too: a masked channel with a
            # live bias still fires through relu, which shrink removes
            idx = prune_parameters(scope, {"c1w": 0.5})["c1w"]
            b = scope.numpy("c1b").copy()
            b[idx] = 0
            scope.set_var("c1b", b)
            masked_out = np.asarray(exe.run(main, feed={"img": xb},
                                            fetch_list=[logits])[0])
            shrink_model(main, fluid.default_startup_program(), scope,
                         {"c1w": 0.5})
            assert scope.numpy("c1w").shape == (4, 3, 3, 3)
            assert scope.numpy("c1b").shape == (4,)
            assert scope.numpy("c2w").shape == (4, 4, 3, 3)
            shrunk_out = np.asarray(exe.run(main, feed={"img": xb},
                                            fetch_list=[logits])[0])
    np.testing.assert_allclose(shrunk_out, masked_out, rtol=1e-5, atol=1e-6)


def _student_teacher():
    img = fluid.layers.data("img", shape=[4], dtype="float32")
    s_hid = fluid.layers.fc(img, 6, act="relu",
                            param_attr=fluid.ParamAttr(name="s_w"))
    s_logits = fluid.layers.fc(s_hid, 3,
                               param_attr=fluid.ParamAttr(name="s_head"))
    teacher = fluid.Program()
    t_startup = fluid.Program()
    with fluid.program_guard(teacher, t_startup):
        t_img = fluid.layers.data("img", shape=[4], dtype="float32")
        t_hid = fluid.layers.fc(t_img, 6, act="relu",
                                param_attr=fluid.ParamAttr(name="t_w"))
        t_logits = fluid.layers.fc(t_hid, 3,
                                   param_attr=fluid.ParamAttr(name="t_head"))
    return s_logits, teacher, t_startup, t_logits


def test_distillation_student_learns_teacher():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        with un.guard():
            s_logits, teacher, t_startup, t_logits = _student_teacher()
        main = fluid.default_main_program()
        renames = merge_teacher_program(
            main, teacher, feed_map={"img": "img"},
            teacher_startup=t_startup,
            student_startup=fluid.default_startup_program())
        soft = SoftLabelDistiller(s_logits.name, renames[t_logits.name],
                                  student_temperature=1.0,
                                  teacher_temperature=1.0)
        l2 = L2Distiller(s_logits.name, renames[t_logits.name],
                         distillation_loss_weight=0.5)
        loss = fluid.layers.elementwise_add(soft.distiller_loss(main),
                                            l2.distiller_loss(main))
        # teacher params are frozen: only student params may receive grads
        opt = fluid.optimizer.Adam(learning_rate=0.05)
        opt.minimize(loss)
        grads = [op for op in main.global_block.ops
                 if op.type.endswith("_grad")]
        assert grads
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            # make the teacher non-trivial
            scope.set_var("teacher_t_w",
                          rng.randn(4, 6).astype(np.float32))
            scope.set_var("teacher_t_head",
                          rng.randn(6, 3).astype(np.float32))
            t_before = scope.numpy("teacher_t_w").copy()
            vals = []
            for _ in range(60):
                xb = rng.rand(32, 4).astype(np.float32)
                out = exe.run(main, feed={"img": xb}, fetch_list=[loss])
                vals.append(float(np.asarray(out[0]).reshape(-1)[0]))
            # student converges toward the teacher...
            assert vals[-1] < 0.5 * vals[0], (vals[0], vals[-1])
            # ...and the teacher never moved
            np.testing.assert_array_equal(scope.numpy("teacher_t_w"),
                                          t_before)


def test_fsp_distiller_builds_and_decreases():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        with un.guard():
            img = fluid.layers.data("img", shape=[2, 6, 6],
                                    dtype="float32")
            s1 = fluid.layers.conv2d(img, 4, 3, padding=1, act="relu")
            s2 = fluid.layers.conv2d(s1, 4, 3, padding=1)
            t1 = fluid.layers.conv2d(img, 4, 3, padding=1, act="relu")
            t2 = fluid.layers.conv2d(t1, 4, 3, padding=1)
        main = fluid.default_main_program()
        fsp = FSPDistiller([(s1.name, s2.name)], [(t1.name, t2.name)])
        loss = fsp.distiller_loss(main)
        fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(2)
        xb = rng.rand(4, 2, 6, 6).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            vals = [float(np.asarray(exe.run(main, feed={"img": xb},
                                             fetch_list=[loss])[0])
                          .reshape(-1)[0]) for _ in range(40)]
        assert vals[-1] < 0.5 * vals[0], (vals[0], vals[-1])


def test_sa_controller_finds_good_tokens():
    ctrl = SAController(reduce_rate=0.9, init_temperature=1.0, seed=0)
    target = [3, 1, 4, 1, 5]
    rng_table = [8] * 5
    ctrl.reset(rng_table, [0] * 5)
    tokens = [0] * 5

    def reward_of(t):
        return -float(sum((a - b) ** 2 for a, b in zip(t, target)))

    for _ in range(300):
        tokens = ctrl.next_tokens()
        ctrl.update(tokens, reward_of(tokens))
    assert ctrl.max_reward > -6, (ctrl.max_reward, ctrl.best_tokens)
    # constraint path: even tokens only
    ctrl2 = SAController(seed=1)
    ctrl2.reset([6] * 3, [0, 0, 0],
                constrain_func=lambda t: all(x % 2 == 0 for x in t))
    for _ in range(20):
        t = ctrl2.next_tokens()
        assert all(x % 2 == 0 for x in t), t
        ctrl2.update(t, 0.0)
