"""paddle_tpu.resilience.distributed: sharded elastic checkpoints
(manifest format_version 2, PT605-PT609), cross-replica divergence
detection, and the step watchdog — all on the 8-virtual-device CPU mesh
the suite's conftest configures. The real-kill / real-hang end-to-end
lives in ``tools/chaos_check.py --multichip`` (CI); these tests cover the
same machinery in-process."""
import json
import os
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, resilience
from paddle_tpu.resilience import (CheckpointCorruptError,
                                   ReplicaDivergenceError, WatchdogTimeout,
                                   fault_plan_guard)
from paddle_tpu.resilience import distributed as rdist

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@pytest.fixture
def flags_guard():
    """Snapshot/restore set_flags overrides AND the divergence-recovery
    registration so a failing test can't leak distributed-resilience
    state into the rest of the suite."""
    from paddle_tpu import flags as F

    saved = dict(F._overrides)
    yield fluid.set_flags
    F._overrides.clear()
    F._overrides.update(saved)
    rdist.set_divergence_recovery(None)


def _dp_mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


class _Session:
    """A small MLP whose param/moment dims divide 8, so dp-sharding the
    state produces real per-shard slices."""

    def __init__(self, optimizer="adam"):
        self.guard = un.guard()
        self.guard.__enter__()
        self.main, self.startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(self.main, self.startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = fluid.layers.fc(x, 16)
            pred = fluid.layers.fc(h, 1)
            self.loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            opt = (fluid.optimizer.Adam(learning_rate=0.01)
                   if optimizer == "adam"
                   else fluid.optimizer.SGD(learning_rate=0.1))
            opt.minimize(self.loss)
        self.exe = fluid.Executor(fluid.CPUPlace())
        self.scope = fluid.Scope()
        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)
        self.guard.__exit__(None, None, None)

    def feed(self, batch=8, seed=0):
        rng = np.random.RandomState(seed)
        x = rng.rand(batch, 16).astype(np.float32)
        return {"x": x, "y": rng.rand(batch, 1).astype(np.float32)}

    def run(self, prog=None, **kw):
        with fluid.scope_guard(self.scope):
            return self.exe.run(prog or self.main, feed=self.feed(),
                                fetch_list=[self.loss], **kw)

    def shard_state(self, mesh):
        """Place every dim0-divisible state var dp-sharded (the live-
        sharding source save_sharded_vars inspects), the rest replicated."""
        n = mesh.shape["dp"]
        with fluid.scope_guard(self.scope):
            for name in list(self.scope.vars):
                v = np.asarray(self.scope.find_var(name))
                spec = P("dp") if (v.ndim >= 1 and v.shape[0] % n == 0) \
                    else P()
                self.scope.set_var(name, jax.device_put(
                    jnp.asarray(v), NamedSharding(mesh, spec)))

    def save(self, dirname, meta=None, mesh=None):
        with fluid.scope_guard(self.scope):
            fluid.io.save_checkpoint(self.exe, dirname, self.main,
                                     scope=self.scope, meta=meta or {},
                                     mesh=mesh)

    def image(self):
        return {n: np.asarray(self.scope.find_var(n)).copy()
                for n in self.scope.vars}


# ---------------------------------------------------------------------------
# pillar 1: sharded elastic checkpoints
# ---------------------------------------------------------------------------

def test_sharded_save_restore_roundtrip(tmp_path):
    s = _Session()
    mesh = _dp_mesh()
    s.run()
    s.shard_state(mesh)
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, meta={"step": 3}, mesh=mesh)
    manifest = resilience.verify_checkpoint(ck)
    assert manifest["format_version"] == 2
    sh = manifest["sharding"]
    assert sh["num_shards"] == 8 and len(sh["shard_files"]) == 8
    # Adam moments + weights with dim0 % 8 == 0 really did split
    assert any(k.startswith("moment") for k in sh["specs"])
    # every shard file is integrity-hashed
    assert all(f in manifest["files"] for f in sh["shard_files"])
    before = s.image()
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        meta = fluid.io.load_checkpoint(s.exe, ck, s.main, scope=scope2)
    assert meta["step"] == 3
    for n, v in before.items():
        got = scope2.find_var(n)
        if got is not None:
            np.testing.assert_array_equal(np.asarray(got), v)


def test_elastic_restore_8_4_1_matches_full_gather(tmp_path):
    """A checkpoint saved on dp=8 must restore byte-equal on a dp=4
    submesh and on one device, and match the full-gather (v1) restore of
    the same state exactly."""
    s = _Session()
    mesh8 = _dp_mesh(8)
    s.run()
    s.shard_state(mesh8)
    ck_sharded = str(tmp_path / "checkpoint_0")
    ck_full = str(tmp_path / "full" / "checkpoint_0")
    s.save(ck_sharded, meta={"step": 1}, mesh=mesh8)
    s.save(ck_full, meta={"step": 1})          # the full-gather baseline

    def load_bytes(ck, place_mesh=None, device=None):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            fluid.io.load_checkpoint(s.exe, ck, s.main, scope=scope)
            if place_mesh is not None:   # resume on a SMALLER mesh
                n = place_mesh.shape["dp"]
                for name in list(scope.vars):
                    v = np.asarray(scope.find_var(name))
                    spec = P("dp") if (v.ndim >= 1 and v.shape[0] % n
                                       == 0) else P()
                    scope.set_var(name, jax.device_put(
                        jnp.asarray(v), NamedSharding(place_mesh, spec)))
            if device is not None:       # resume on ONE host device
                for name in list(scope.vars):
                    scope.set_var(name, jax.device_put(
                        scope.find_var(name), device))
            return {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.vars}

    gather = load_bytes(ck_full)
    elastic4 = load_bytes(ck_sharded, place_mesh=_dp_mesh(4))
    elastic1 = load_bytes(ck_sharded, device=jax.devices()[0])
    assert set(gather) == set(elastic4) == set(elastic1)
    for n in gather:
        np.testing.assert_array_equal(gather[n], elastic4[n], err_msg=n)
        np.testing.assert_array_equal(gather[n], elastic1[n], err_msg=n)


def test_shard_write_fault_leaves_no_published_checkpoint(tmp_path):
    """An injected failure inside one shard's write (the exception flavour
    of the chaos multichip kill) must leave the serial unpublished and the
    previous checkpoint intact."""
    s = _Session()
    mesh = _dp_mesh()
    s.run()
    s.shard_state(mesh)
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, meta={"step": 1}, mesh=mesh)
    with fault_plan_guard("shard_write:@4:RuntimeError"):
        with pytest.raises(RuntimeError):
            s.save(str(tmp_path / "checkpoint_1"), meta={"step": 2},
                   mesh=mesh)
    assert [sn for sn, _ in resilience.iter_serials(str(tmp_path))] == [0]
    assert resilience.verify_checkpoint(ck)["format_version"] == 2
    assert [p for p in os.listdir(str(tmp_path)) if ".tmp." in p] == []


def _strip_shard(ck, idx=3, drop_hash=True):
    mpath = os.path.join(ck, "manifest.json")
    with open(mpath) as f:
        man = json.load(f)
    sf = man["sharding"]["shard_files"][idx]
    os.remove(os.path.join(ck, sf))
    if drop_hash:
        del man["files"][sf]
    with open(mpath, "w") as f:
        json.dump(man, f)
    return man


def test_sharded_corruption_codes(tmp_path):
    s = _Session()
    mesh = _dp_mesh()
    s.run()
    s.shard_state(mesh)
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, mesh=mesh)
    mpath = os.path.join(ck, "manifest.json")

    # PT607: shard declared but absent (torn distributed write, variant A:
    # the file was hashed but the writer's data never landed)
    man = _strip_shard(ck, drop_hash=False)
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT607"

    # PT607 variant B: shard present but never integrity-hashed (a writer
    # died between naming its shard and finalize hashing it)
    s.save(ck, mesh=mesh)
    with open(mpath) as f:
        man = json.load(f)
    del man["files"][man["sharding"]["shard_files"][2]]
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT607"

    # PT605: shard-count mismatch
    s.save(ck, mesh=mesh)
    with open(mpath) as f:
        man = json.load(f)
    man["sharding"]["num_shards"] = 4
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT605"

    # PT609: malformed sharding section
    s.save(ck, mesh=mesh)
    with open(mpath) as f:
        man = json.load(f)
    del man["sharding"]["shard_files"]
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT609"

    # PT606/PT608 are load-time: lie about a spec so reassembly breaks
    s.save(ck, mesh=mesh)
    with open(mpath) as f:
        man = json.load(f)
    name = sorted(man["sharding"]["specs"])[0]
    man["vars"][name]["shape"] = [3, 3, 3]
    with open(mpath, "w") as f:
        json.dump(man, f)
    scope2 = fluid.Scope()
    with pytest.raises(CheckpointCorruptError) as ei:
        with fluid.scope_guard(scope2):
            fluid.io.load_checkpoint(s.exe, ck, s.main, scope=scope2,
                                     verify=False)
    assert ei.value.code in ("PT606", "PT608")
    assert not scope2.vars, "failed sharded load must not touch the scope"


def test_recovery_walk_skips_torn_sharded_serial(tmp_path):
    """Satellite: a serial whose manifest declares more shard files than
    are present must be SKIPPED by the recovery walk (counted on
    trainer_ckpt_fallback_total with its PT6xx code), falling back to the
    previous verified serial — never a raw KeyError."""
    s = _Session()
    mesh = _dp_mesh()
    s.run()
    s.shard_state(mesh)
    s.save(str(tmp_path / "checkpoint_0"), meta={"step": 5}, mesh=mesh)
    s.run()
    s.shard_state(mesh)
    s.save(str(tmp_path / "checkpoint_1"), meta={"step": 9}, mesh=mesh)
    _strip_shard(str(tmp_path / "checkpoint_1"))   # torn distributed write
    before = monitor.metric_value("trainer_ckpt_fallback_total",
                                  default=0.0, code="PT607")
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        meta, serial, skipped = resilience.load_latest_checkpoint(
            s.exe, str(tmp_path), main_program=s.main, scope=scope2)
    assert meta is not None and meta["step"] == 5 and serial == 0
    assert [k["code"] for k in skipped] == ["PT607"]
    after = monitor.metric_value("trainer_ckpt_fallback_total",
                                 default=0.0, code="PT607")
    assert after == before + 1


def test_trainer_sharded_checkpoint_resume(tmp_path):
    """CheckpointConfig(sharded=True) writes format_version-2 serials the
    normal Trainer resume walk restores from."""
    def train_func():
        x = fluid.layers.data("x", shape=[16], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1, name="fit")
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    cfg = fluid.contrib.CheckpointConfig(str(tmp_path), step_interval=2,
                                         sharded=True)
    with un.guard():
        t = fluid.contrib.Trainer(train_func,
                                  lambda: fluid.optimizer.SGD(0.05),
                                  checkpoint_config=cfg)
    rng = np.random.RandomState(0)
    batch = [(rng.rand(16).astype(np.float32),
              rng.rand(1).astype(np.float32)) for _ in range(4)]
    t.train(1, lambda ev: None, lambda: iter([batch, batch]), ["x", "y"])
    serials = t._serials()
    assert serials, "sharded trainer checkpoints were not written"
    man = resilience.verify_checkpoint(t._ckpt_path(serials[-1]))
    assert man["format_version"] == 2 and "sharding" in man
    with un.guard():
        t2 = fluid.contrib.Trainer(train_func,
                                   lambda: fluid.optimizer.SGD(0.05),
                                   checkpoint_config=cfg)
    assert t2._step == t._step
    for n, v in t.scope.vars.items():
        got = t2.scope.find_var(n)
        if got is not None:
            np.testing.assert_array_equal(np.asarray(got), np.asarray(v))


# ---------------------------------------------------------------------------
# pillar 2: cross-replica divergence detection
# ---------------------------------------------------------------------------

def _divergent_replicated(mesh, shape=(4, 8), bad_device=3, eps=1.0):
    """A 'replicated' global array whose physical copy differs on ONE
    device — exactly what silent replica divergence looks like."""
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        a = np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
        if i == bad_device:
            a = a.copy()
            a.flat[0] += eps
        bufs.append(jax.device_put(a, d))
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, P()), bufs)


def test_divergence_detector_negative():
    mesh = _dp_mesh()
    w = jax.device_put(np.ones((4, 8), np.float32),
                       NamedSharding(mesh, P()))
    m = jax.device_put(np.arange(16, dtype=np.float32),
                       NamedSharding(mesh, P("dp")))
    assert rdist.replica_divergence_check(mesh, {"w": w, "m": m}) == []


def test_divergence_detector_positive_names_the_param():
    mesh = _dp_mesh()
    good = jax.device_put(np.ones((4, 8), np.float32),
                          NamedSharding(mesh, P()))
    bad = _divergent_replicated(mesh)
    got = rdist.replica_divergence_check(mesh, {"w_ok": good,
                                                "w_bad": bad})
    assert got == ["w_bad"]
    # a single-ULP flip on one replica is still caught (bit checksums,
    # not tolerance comparison)
    tiny = _divergent_replicated(mesh, eps=np.float32(1e-6))
    assert rdist.replica_divergence_check(mesh, {"t": tiny}) == ["t"]


def test_divergence_policy_raise_and_restore(flags_guard, tmp_path):
    flags_guard({"FLAGS_replica_divergence_policy": "raise"})
    with pytest.raises(ReplicaDivergenceError) as ei:
        rdist.handle_divergence(["fc_0.w_0", "moment1"], path="parallel")
    assert ei.value.param == "fc_0.w_0"
    # restore: a registered recovery walk resolves it
    calls = []
    rdist.set_divergence_recovery(lambda: calls.append(1) or True)
    flags_guard({"FLAGS_replica_divergence_policy": "restore"})
    rdist.handle_divergence(["fc_0.w_0"], path="parallel")
    assert calls == [1]
    # restore with nothing restorable escalates to raise
    rdist.set_divergence_recovery(lambda: False)
    with pytest.raises(ReplicaDivergenceError):
        rdist.handle_divergence(["fc_0.w_0"], path="parallel")


def test_divergence_never_retried():
    assert not resilience.is_transient(ReplicaDivergenceError(["w"]))
    assert not resilience.is_transient(WatchdogTimeout("step", 1.0))


def test_parallel_step_divergence_check_integration(flags_guard):
    """End to end through CompiledProgram: a clean run under
    FLAGS_replica_check_interval=1 never trips; planting a divergent
    replica into the scope trips the NEXT step's check and names it."""
    s = _Session(optimizer="sgd")
    prog = fluid.CompiledProgram(s.main).with_data_parallel(
        loss_name=s.loss.name)
    flags_guard({"FLAGS_replica_check_interval": 1})
    s.run(prog)
    s.run(prog)          # clean steps: the sweep runs and stays silent
    assert monitor.metric_value("resilience_divergence_checks_total",
                                default=0.0) >= 2
    mesh = prog._mesh
    # corrupt ONE replica of a replicated param; the executor reads its
    # physical copies, so the post-step state stays diverged and the
    # in-step check must catch it
    name = next(n for n in s.scope.vars
                if np.asarray(s.scope.find_var(n)).shape == (16, 1))
    v = np.asarray(s.scope.find_var(name))
    bufs = []
    for i, d in enumerate(mesh.devices.flat):
        a = v.copy()
        if i == 2:
            a.flat[0] += 1.0
        bufs.append(jax.device_put(jnp.asarray(a), d))
    with fluid.scope_guard(s.scope):
        s.scope.set_var(name, jax.make_array_from_single_device_arrays(
            v.shape, NamedSharding(mesh, P()), bufs))
    with pytest.raises(ReplicaDivergenceError):
        s.run(prog)


# ---------------------------------------------------------------------------
# pillar 3: step watchdog
# ---------------------------------------------------------------------------

def test_watchdog_silent_on_normal_run(flags_guard):
    s = _Session(optimizer="sgd")
    before = monitor.metric_value("watchdog_timeouts_total", default=0.0,
                                  section="step")
    flags_guard({"FLAGS_step_timeout_s": 60.0})
    s.run()
    s.run()
    assert monitor.metric_value("watchdog_timeouts_total", default=0.0,
                                section="step") == before
    armed = monitor.metric_value("watchdog_sections_armed_total",
                                 default=0.0, section="step")
    assert armed >= 2, "watchdog must actually arm around the step"


def test_watchdog_converts_injected_hang(flags_guard):
    s = _Session(optimizer="sgd")
    s.run()              # compile once so the hang hits a cached step
    flags_guard({"FLAGS_step_timeout_s": 1.0,
                 "FLAGS_watchdog_hard_exit": 0})
    before = monitor.metric_value("watchdog_timeouts_total", default=0.0,
                                  section="step")
    t0 = time.monotonic()
    with fault_plan_guard("hang:@1:hang"):
        with pytest.raises(WatchdogTimeout) as ei:
            s.run()
    elapsed = time.monotonic() - t0
    assert elapsed < 30, f"watchdog took {elapsed:.1f}s to break the hang"
    assert ei.value.section == "step"
    assert monitor.metric_value("watchdog_timeouts_total", default=0.0,
                                section="step") == before + 1
    # the session survives: the scope was never donated into the hung step
    flags_guard({"FLAGS_step_timeout_s": 0.0})
    s.run()


def test_watchdog_direct_section(flags_guard):
    """watchdog_section is usable standalone (the collective wrappers in
    parallel/pipeline and parallel/ring_attention arm it the same way)."""
    flags_guard({"FLAGS_watchdog_hard_exit": 0})
    with pytest.raises(WatchdogTimeout) as ei:
        with resilience.watchdog_section("collective", detail="unit",
                                         timeout=0.5):
            while True:
                time.sleep(0.02)
    assert ei.value.section == "collective" and "unit" in ei.value.detail
    # disabled timeout is a no-op passthrough
    with resilience.watchdog_section("collective", timeout=0):
        pass


# ---------------------------------------------------------------------------
# satellite: the multichip dryrun entry points stay warning-clean
# ---------------------------------------------------------------------------

def test_multichip_paths_no_dtype_truncation_warnings():
    """The int64 UserWarning the MULTICHIP tail showed came from
    ops/tensor.py's jnp.full boundary when jnp_dtype's hand-rolled x64
    probe failed open on newer jax. jnp_dtype now asks
    jax.dtypes.canonicalize_dtype; this runs an int64-heavy program
    through the CompiledProgram mesh path (the dryrun's route) with
    warnings-as-errors."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[1], dtype="int64")
            y = fluid.layers.data("y", shape=[4], dtype="float32")
            fc64 = fluid.layers.fill_constant([4], "int64", 3)
            oh = fluid.layers.one_hot(ids, depth=4)
            pred = fluid.layers.fc(oh, 4)
            s = (pred + fluid.layers.cast(fc64, "float32")
                 + fluid.layers.cast(fluid.layers.cast(y, "int64"),
                                     "float32"))
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(s, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    prog = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    feed = {"ids": np.zeros((8, 1), np.int64),
            "y": np.zeros((8, 4), np.float32)}
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with fluid.scope_guard(scope):
            exe.run(startup)
            (out,) = exe.run(prog, feed=feed, fetch_list=[loss.name])
    assert np.isfinite(np.asarray(out)).all()


def test_jnp_dtype_canonicalizes_64bit():
    from paddle_tpu.core.types import jnp_dtype, np_dtype

    assert np_dtype("int64") == np.dtype("int64")
    if not jax.config.jax_enable_x64:
        assert jnp_dtype("int64") == np.dtype("int32")
        assert jnp_dtype("float64") == np.dtype("float32")
        assert jnp_dtype("uint64") == np.dtype("uint32")
    assert jnp_dtype("bfloat16").name == "bfloat16"
