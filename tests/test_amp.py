"""AMP / bf16 mixed precision (VERDICT r2 item #1).

Reference: contrib/mixed_precision/decorator.py:27 decorate,
fp16_lists.py white/black lists, update_loss_scaling state machine.
"""
import re

import jax
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.contrib import mixed_precision as mp
from paddle_tpu.executor import analyze_block_io, make_step_fn


def _mlp_program(batch=32, use_amp=True, **amp_kw):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[batch, 64], append_batch_size=False)
        label = layers.data("label", shape=[batch, 1], dtype="int64",
                            append_batch_size=False)
        h = layers.fc(img, 64, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        opt = optimizer.Adam(1e-2)
        if use_amp:
            opt = mp.decorate(opt, **amp_kw)
        opt.minimize(loss)
    return main, startup, loss, opt


def _batch(rng, batch=32):
    x = rng.rand(batch, 64).astype(np.float32)
    y = ((x.sum(1) > 32).astype(np.int64) % 10).reshape(batch, 1)
    return x, y


def test_bf16_policy_casts_matmuls_keeps_master_weights_fp32():
    main, startup, loss, _ = _mlp_program()
    io = analyze_block_io(main.global_block, {"img", "label"}, [loss.name])
    fn = make_step_fn(main.global_block, io, [loss.name])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        feed_vals = [np.zeros((32, 64), np.float32) if n == "img"
                     else np.zeros((32, 1), np.int64)
                     for n in io["feed_order"]]
        jaxpr = str(jax.make_jaxpr(fn)(
            feed_vals, [scope.find_var(n) for n in io["donated"]],
            [scope.find_var(n) for n in io["ro"]], jax.random.key(0)))
        # every dot_general (fwd + grads) computes in bf16
        dot_lines = [ln for ln in jaxpr.splitlines() if "dot_general" in ln]
        assert dot_lines, "no matmuls traced"
        assert all("bf16" in ln for ln in dot_lines), dot_lines
        # master weights stay fp32 in the scope
        for n in io["donated"]:
            assert np.asarray(scope.find_var(n)).dtype == np.float32, n


def test_amp_trains_to_fp32_quality():
    rng = np.random.RandomState(0)
    batches = [_batch(rng) for _ in range(60)]

    def run(use_amp):
        main, startup, loss, _ = _mlp_program(use_amp=use_amp)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for x, y in batches:
                l = exe.run(main, feed={"img": x, "label": y},
                            fetch_list=[loss])[0]
        return float(l)

    l_fp32, l_bf16 = run(False), run(True)
    assert l_bf16 < 0.9, f"bf16 failed to train: {l_bf16}"  # from ~2.08
    assert abs(l_bf16 - l_fp32) < 0.1, (l_fp32, l_bf16)


def test_dynamic_loss_scaling_grows_and_shrinks():
    main, startup, loss, opt = _mlp_program(
        use_amp=True, use_dynamic_loss_scaling=True,
        init_loss_scaling=1024.0, incr_every_n_steps=2,
        decr_every_n_nan_or_inf=1, incr_ratio=2.0, decr_ratio=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    scale_var = opt.get_loss_scaling()
    with fluid.scope_guard(scope):
        exe.run(startup)
        x, y = _batch(rng)
        for _ in range(2):
            sc = exe.run(main, feed={"img": x, "label": y},
                         fetch_list=[scale_var])[0]
        assert float(sc) == 2048.0, sc  # grew after 2 finite steps
        # poison the batch: inf activations -> non-finite grads -> shrink,
        # and the whole update must be SKIPPED (params + momentum/adam state
        # untouched — reference skip-update semantics, not just zeroed grads)
        param_names = [n for n in scope.vars
                       if n.startswith(("fc_", "moment", "beta"))]
        before = {n: np.asarray(scope.find_var(n)).copy()
                  for n in list(scope.vars)}
        bad = np.full((32, 64), np.float32(3e38))
        sc = exe.run(main, feed={"img": bad, "label": y},
                     fetch_list=[scale_var])[0]
        assert float(sc) == 1024.0, sc
        for n, v in before.items():
            if "loss_scaling" in n or "bad_steps" in n or "good_steps" in n \
                    or "learning_rate" in n:
                continue
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(n)), v,
                err_msg=f"{n} changed on an overflow step")
        l = exe.run(main, feed={"img": x, "label": y}, fetch_list=[loss])[0]
        assert np.isfinite(l)


def test_eval_clone_keeps_amp_policy():
    main, startup, loss, _ = _mlp_program()
    test_prog = main.clone(for_test=True)
    assert getattr(test_prog, "_amp_policy", None) is not None


def test_custom_lists():
    lists = mp.AutoMixedPrecisionLists(custom_white_list={"softmax"},
                                       custom_black_list={"mul"})
    assert "softmax" in lists.white_list
    assert "softmax" not in lists.black_list
    assert "mul" in lists.black_list
