"""OpTest harness: numpy-oracle correctness + numeric-gradient checks.

Port of the reference's keystone test base class
(python/paddle/fluid/tests/unittests/op_test.py:135): a subclass declares
``op_type``, ``inputs``, ``attrs``, ``outputs`` (numpy reference);
``check_output`` builds a one-op program and compares against the numpy
oracle; ``check_grad`` compares the registered grad lowering against central
finite differences (reference get_numeric_gradient, op_test.py:46).
An op is "done" when its OpTest passes on the XLA backend.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.types import canonical_dtype


class OpTest:
    op_type: str = None
    inputs: dict = {}
    outputs: dict = {}
    attrs: dict = {}

    def setup(self):
        """Subclasses populate op_type/inputs/attrs/outputs here."""
        raise NotImplementedError

    # -- helpers ---------------------------------------------------------
    def _as_items(self, spec):
        """{'X': arr} or {'X': [('x0', arr), ...]} -> [(slot, var, arr)]."""
        items = []
        for slot, v in spec.items():
            if isinstance(v, list) and v and isinstance(v[0], tuple):
                for name, arr in v:
                    items.append((slot, name, np.asarray(arr)))
            else:
                items.append((slot, slot.lower() + "_var", np.asarray(v)))
        return items

    def _build(self, extra_fetch_grads=()):
        self.setup()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block
            in_map, feeds = {}, {}
            for slot, name, arr in self._as_items(self.inputs):
                v = block.create_var(name=name, shape=arr.shape,
                                     dtype=canonical_dtype(arr.dtype),
                                     is_data=True,
                                     stop_gradient=False)
                in_map.setdefault(slot, []).append(v)
                feeds[name] = arr
            out_map, out_names = {}, {}
            for slot, name, arr in self._as_items(self.outputs):
                v = block.create_var(name=name, shape=arr.shape,
                                     dtype=canonical_dtype(arr.dtype))
                out_map.setdefault(slot, []).append(v)
                out_names.setdefault(slot, []).append(name)
            block.append_op(self.op_type, inputs=in_map, outputs=out_map,
                            attrs=dict(self.attrs))
        return main, startup, feeds, out_names

    # -- checks ----------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5, no_check=(), place=None):
        main, startup, feeds, out_names = self._build()
        exe = fluid.Executor(place or fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            fetch = [n for slot, names in out_names.items()
                     for n in names if slot not in no_check]
            got = exe.run(main, feed=feeds, fetch_list=fetch)
        expect_items = [(slot, name, arr)
                        for slot, name, arr in self._as_items(self.outputs)
                        if slot not in no_check]
        for (slot, name, want), have in zip(expect_items, got):
            np.testing.assert_allclose(
                have, want, atol=atol, rtol=rtol,
                err_msg=f"op {self.op_type} output {slot}/{name} mismatch")

    def check_grad(self, inputs_to_check, output_name, delta=0.005,
                   max_relative_error=0.005, place=None):
        """Analytic grads (registry lowering under vjp) vs central finite
        differences of loss = mean(output)."""
        self.setup()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            block = main.global_block
            in_map, feeds, name_of = {}, {}, {}
            for slot, name, arr in self._as_items(self.inputs):
                arr = np.asarray(arr)
                v = block.create_var(name=name, shape=arr.shape,
                                     dtype=canonical_dtype(arr.dtype),
                                     is_data=True, stop_gradient=False)
                in_map.setdefault(slot, []).append(v)
                feeds[name] = arr
                name_of[slot] = name
            out_map = {}
            out_var = None
            for slot, name, arr in self._as_items(self.outputs):
                v = block.create_var(name=name, shape=np.asarray(arr).shape,
                                     dtype=canonical_dtype(
                                         np.asarray(arr).dtype))
                out_map.setdefault(slot, []).append(v)
                if slot == output_name or name == output_name:
                    out_var = v
            block.append_op(self.op_type, inputs=in_map, outputs=out_map,
                            attrs=dict(self.attrs))
            assert out_var is not None, f"output {output_name} not found"
            loss = fluid.layers.mean(out_var)
            grads = fluid.gradients(
                [loss], [block.var(name_of[s]) if s in name_of else
                         block.var(s) for s in inputs_to_check])

        exe = fluid.Executor(place or fluid.CPUPlace())
        scope = fluid.Scope()
        sample_rng = np.random.RandomState(1234)
        max_samples = 24  # sampled finite differences keep runtime bounded
        with fluid.scope_guard(scope):
            exe.run(startup)
            fetch = [loss.name] + [g.name for g in grads]
            vals = exe.run(main, feed=feeds, fetch_list=fetch)
            analytic = dict(zip(inputs_to_check, vals[1:]))

            def run_loss():
                return float(exe.run(main, feed=feeds,
                                     fetch_list=fetch)[0])

            for slot in inputs_to_check:
                fname = name_of.get(slot, slot)
                base = feeds[fname].astype(np.float64)
                flat = base.reshape(-1)
                n = flat.size
                idxs = (np.arange(n) if n <= max_samples else
                        sample_rng.choice(n, max_samples, replace=False))
                a = np.asarray(analytic[slot], np.float64).reshape(-1)
                for i in idxs:
                    orig = flat[i]
                    flat[i] = orig + delta
                    feeds[fname] = base.astype(np.float32)
                    lp = run_loss()
                    flat[i] = orig - delta
                    feeds[fname] = base.astype(np.float32)
                    lm = run_loss()
                    flat[i] = orig
                    feeds[fname] = base.astype(np.float32)
                    num = (lp - lm) / (2 * delta)
                    scale = max(abs(a[i]), abs(num), 1e-3)
                    rel = abs(a[i] - num) / scale
                    assert rel <= max_relative_error, (
                        f"op {self.op_type} grad wrt {slot}[{i}]: rel err "
                        f"{rel:.5f} (analytic {a[i]:.6f} vs numeric {num:.6f})")
