"""Book-style end-to-end gates, round 5 additions (reference
python/paddle/fluid/tests/book/): image_classification,
recommender_system, label_semantic_roles (CRF), rnn_encoder_decoder.
Each is the reference model's shape scaled to CPU-test size, fed through
the DataFeeder/DataLoader, and judged on learning (loss drop / accuracy),
mirroring the reference tests' convergence gates."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.dataset import cifar, conll05, movielens, wmt16


def test_image_classification():
    """reference book/test_image_classification.py: conv net on cifar10."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data("img", shape=[3072], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            img_nchw = fluid.layers.reshape(img, [-1, 3, 32, 32])
            c1 = fluid.layers.conv2d(img_nchw, 16, 3, padding=1, act="relu")
            p1 = fluid.layers.pool2d(c1, 2, "max", 2)
            b1 = fluid.layers.batch_norm(p1)
            c2 = fluid.layers.conv2d(b1, 32, 3, padding=1, act="relu")
            p2 = fluid.layers.pool2d(c2, 2, "max", 2)
            flat = fluid.layers.flatten(p2)
            h = fluid.layers.fc(flat, 64, act="relu")
            logits = fluid.layers.fc(h, 10)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            acc = fluid.layers.accuracy(logits, label)
            test_prog = main.clone(for_test=True)
            fluid.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    main.random_seed = 5

    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=4)
    loader.set_sample_generator(cifar.train10(), batch_size=64,
                                drop_last=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(2):
            for batch in loader:
                (lv,) = exe.run(main, feed=batch, fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        feeder = fluid.DataFeeder(feed_list=[img, label], program=main)
        samples = [(im, np.array([lb])) for im, lb in
                   list(cifar.test10()())[:256]]
        (accv,) = exe.run(test_prog, feed=feeder.feed(samples),
                          fetch_list=[acc.name])
    assert losses[-1] < 0.7 * losses[0], (losses[0], losses[-1])
    assert float(np.asarray(accv)) > 0.3, float(np.asarray(accv))


def test_recommender_system():
    """reference book/test_recommender_system.py: dual-tower user/movie
    embeddings -> cos_sim -> scaled rating regression on movielens."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            uid = fluid.layers.data("user_id", shape=[1], dtype="int64")
            gender = fluid.layers.data("gender_id", shape=[1], dtype="int64")
            age = fluid.layers.data("age_id", shape=[1], dtype="int64")
            job = fluid.layers.data("job_id", shape=[1], dtype="int64")
            mid = fluid.layers.data("movie_id", shape=[1], dtype="int64")
            cat = fluid.layers.data("category_id", shape=[2], dtype="int64")
            score = fluid.layers.data("score", shape=[1], dtype="float32")

            def tower(feats, sizes, dim=16):
                parts = []
                for f, n in zip(feats, sizes):
                    e = fluid.layers.embedding(f, size=[n + 1, dim])
                    parts.append(fluid.layers.reshape(e, [-1, dim]))
                return fluid.layers.fc(fluid.layers.concat(parts, axis=1),
                                       32, act="tanh")

            usr = tower([uid, gender, age, job],
                        [movielens.max_user_id(), 2,
                         len(movielens.age_table),
                         movielens.max_job_id()])
            cat_emb = fluid.layers.embedding(
                cat, size=[movielens.categories_dict_size() + 1, 16])
            cat_vec = fluid.layers.reduce_mean(cat_emb, dim=1)
            mov_id_emb = fluid.layers.embedding(
                mid, size=[movielens.max_movie_id() + 1, 16])
            mov = fluid.layers.fc(
                fluid.layers.concat(
                    [fluid.layers.reshape(mov_id_emb, [-1, 16]), cat_vec],
                    axis=1), 32, act="tanh")
            sim = fluid.layers.cos_sim(usr, mov)
            pred = fluid.layers.scale(sim, scale=5.0)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, score))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    main.random_seed = 6

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        batch, feed = 128, {}
        gen = movielens.train()()
        for step in range(60):
            rows = [next(gen) for _ in range(batch)]
            feed = {
                "user_id": np.array([[r[0]] for r in rows], np.int64),
                "gender_id": np.array([[r[1]] for r in rows], np.int64),
                "age_id": np.array([[r[2]] for r in rows], np.int64),
                "job_id": np.array([[r[3]] for r in rows], np.int64),
                "movie_id": np.array([[r[4]] for r in rows], np.int64),
                "category_id": np.stack([r[5] for r in rows]),
                "score": np.array([[r[7]] for r in rows], np.float32),
            }
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < 0.6 * losses[0], (losses[0], losses[-1])


def test_label_semantic_roles():
    """reference book/test_label_semantic_roles.py: the CRF gate — word +
    mark embeddings -> bi-LSTM -> linear_chain_crf; decode with
    crf_decoding, score with chunk_eval."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            word = fluid.layers.data("word", shape=[1], dtype="int64",
                                     lod_level=1)
            mark = fluid.layers.data("mark", shape=[1], dtype="int64",
                                     lod_level=1)
            target = fluid.layers.data("target", shape=[1], dtype="int64",
                                       lod_level=1)
            w_emb = fluid.layers.embedding(
                word, size=[conll05.word_dict_len(), 32])
            m_emb = fluid.layers.embedding(mark, size=[2, 8])
            feat = fluid.layers.concat([w_emb, m_emb], axis=2)
            gates = fluid.layers.fc(feat, 4 * 32, num_flatten_dims=2)
            fwd, _ = fluid.layers.dynamic_lstm(gates, size=4 * 32)
            rev_gates = fluid.layers.fc(feat, 4 * 32, num_flatten_dims=2)
            rev, _ = fluid.layers.dynamic_lstm(rev_gates, size=4 * 32,
                                               is_reverse=True)
            both = fluid.layers.concat([fwd, rev], axis=2)
            emission = fluid.layers.fc(
                both, conll05.label_dict_len(), num_flatten_dims=2)
            crf_cost = fluid.layers.linear_chain_crf(
                input=emission, label=target,
                param_attr=fluid.ParamAttr(name="crfw"),
                length=fluid.layers.sequence.seq_len_var(word))
            loss = fluid.layers.mean(crf_cost)
            fluid.optimizer.Adam(learning_rate=0.02).minimize(loss)
            decode = fluid.layers.crf_decoding(
                input=emission, param_attr=fluid.ParamAttr(name="crfw"),
                length=fluid.layers.sequence.seq_len_var(word))
            (prec, rec, f1, _, _, _) = fluid.layers.chunk_eval(
                decode, target, chunk_scheme="IOB",
                num_chunk_types=conll05.num_chunk_types(),
                seq_length=fluid.layers.sequence.seq_len_var(word))
    main.random_seed = 7

    feeder = fluid.DataFeeder(feed_list=[word, mark, target], program=main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses, f1s = [], []
    with fluid.scope_guard(scope):
        exe.run(startup)
        gen = conll05.train()()
        for step in range(120):
            rows = [next(gen) for _ in range(32)]
            samples = [(w[:, None], m[:, None], t[:, None])
                       for (w, p, m, t) in rows]
            feed = feeder.feed(samples)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        f1v = exe.run(main, feed=feed, fetch_list=[f1.name])[0]
        f1s.append(float(np.asarray(f1v).reshape(-1)[0]))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert f1s[-1] > 0.9, f1s


def test_rnn_encoder_decoder():
    """reference book/test_rnn_encoder_decoder.py: GRU encoder, GRU
    decoder conditioned on the encoder's final state, teacher-forced
    cross-entropy on the synthetic wmt16 word-mapping task."""
    vocab, emb_dim, hid = 130, 32, 64
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src = fluid.layers.data("src", shape=[1], dtype="int64",
                                    lod_level=1)
            trg = fluid.layers.data("trg", shape=[1], dtype="int64",
                                    lod_level=1)
            trg_next = fluid.layers.data("trg_next", shape=[1],
                                         dtype="int64", lod_level=1)
            s_emb = fluid.layers.embedding(src, size=[vocab, emb_dim])
            s_gates = fluid.layers.fc(s_emb, 3 * hid, num_flatten_dims=2)
            enc = fluid.layers.dynamic_gru(s_gates, size=hid)
            enc_last = fluid.layers.sequence_last_step(enc)

            t_emb = fluid.layers.embedding(trg, size=[vocab, emb_dim])
            t_gates = fluid.layers.fc(t_emb, 3 * hid, num_flatten_dims=2)
            dec = fluid.layers.dynamic_gru(t_gates, size=hid,
                                           h_0=enc_last)
            logits = fluid.layers.fc(dec, vocab, num_flatten_dims=2)
            ce = fluid.layers.softmax_with_cross_entropy(logits, trg_next)
            from paddle_tpu.layers.sequence import seq_len_var

            t_max = 9  # wmt16 synthetic: src <= 8, trg = src + BOS
            mask = fluid.layers.cast(
                fluid.layers.sequence_mask(seq_len_var(trg), maxlen=t_max),
                "float32")
            loss = fluid.layers.reduce_sum(
                fluid.layers.squeeze(ce, axes=[2]) * mask) / (
                fluid.layers.reduce_sum(mask) + 1e-6)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    main.random_seed = 8

    def pad_to(a, n):
        return np.pad(a, (0, n - len(a)))

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        gen = wmt16.train()()
        for step in range(100):
            rows = [next(gen) for _ in range(64)]
            feed = {
                "src": np.stack([pad_to(s, 8) for s, t, n in rows])[..., None],
                "src@LOD": np.array([len(s) for s, t, n in rows], np.int32),
                "trg": np.stack([pad_to(t, 9) for s, t, n in rows])[..., None],
                "trg@LOD": np.array([len(t) for s, t, n in rows], np.int32),
                "trg_next": np.stack(
                    [pad_to(n, 9) for s, t, n in rows])[..., None],
                "trg_next@LOD": np.array([len(n) for s, t, n in rows],
                                         np.int32),
            }
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    # the mapping is deterministic: teacher-forced CE must fall well below
    # uniform log(vocab) ~ 4.87
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert losses[-1] < 2.5, losses[-1]
