"""Batch coverage: new activations, tensor utilities, losses, metrics ops,
and the distributions module."""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest

RNG = np.random.RandomState(5)


class _Unary(OpTest):
    op = None
    fn = None
    attrs_ = {}

    def setup(self):
        xv = RNG.randn(3, 7).astype(np.float32) * 0.8
        self.op_type = self.op
        self.inputs = {"X": xv}
        self.attrs = dict(self.attrs_)
        self.outputs = {"Out": self.fn(xv)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)


class TestTan(_Unary):
    op, fn = "tan", staticmethod(np.tan)


class TestMish(_Unary):
    op = "mish"
    fn = staticmethod(lambda v: v * np.tanh(np.log1p(np.exp(v))))


class TestStanh(_Unary):
    op = "stanh"
    fn = staticmethod(lambda v: 1.7159 * np.tanh(0.67 * v))


class TestSoftshrink(_Unary):
    op = "softshrink"
    attrs_ = {"lambda": 0.5}
    fn = staticmethod(lambda v: np.where(v > 0.5, v - 0.5,
                                         np.where(v < -0.5, v + 0.5, 0)))


class TestMaxout(OpTest):
    def setup(self):
        xv = RNG.randn(2, 6, 4).astype(np.float32)
        self.op_type = "maxout"
        self.inputs = {"X": xv}
        self.attrs = {"groups": 3, "axis": 1}
        self.outputs = {"Out": xv.reshape(2, 2, 3, 4).max(2)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestGatherNd(OpTest):
    def setup(self):
        xv = RNG.randn(4, 5, 6).astype(np.float32)
        idx = np.array([[0, 1], [3, 4]], np.int64)
        self.op_type = "gather_nd"
        self.inputs = {"X": xv, "Index": idx}
        self.outputs = {"Out": np.stack([xv[0, 1], xv[3, 4]])}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestPad2dReflect(OpTest):
    def setup(self):
        xv = RNG.randn(1, 2, 4, 4).astype(np.float32)
        self.op_type = "pad2d"
        self.inputs = {"X": xv}
        self.attrs = {"paddings": [1, 1, 2, 0], "mode": "reflect"}
        self.outputs = {"Out": np.pad(
            xv, [(0, 0), (0, 0), (1, 1), (2, 0)], mode="reflect")}

    def test(self):
        self.check_output()


class TestKLDiv(OpTest):
    def setup(self):
        p = np.abs(RNG.rand(4, 6).astype(np.float32)) + 0.1
        p = p / p.sum(-1, keepdims=True)
        logq = np.log(np.abs(RNG.rand(4, 6).astype(np.float32)) + 0.1)
        want = (p * (np.log(p) - logq)).mean()
        self.op_type = "kldiv_loss"
        self.inputs = {"X": logq, "Target": p}
        self.attrs = {"reduction": "mean"}
        self.outputs = {"Loss": np.float32(want)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)
        self.check_grad(["X"], "Loss")


class TestCosSim(OpTest):
    def setup(self):
        a = RNG.randn(5, 8).astype(np.float32)
        b = RNG.randn(5, 8).astype(np.float32)
        want = (a * b).sum(-1, keepdims=True) / (
            np.linalg.norm(a, axis=-1, keepdims=True) *
            np.linalg.norm(b, axis=-1, keepdims=True))
        self.op_type = "cos_sim"
        self.inputs = {"X": a, "Y": b}
        self.outputs = {"Out": want.astype(np.float32)}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5,
                          no_check=("XNorm", "YNorm"))


def test_precision_recall_binary():
    idx = np.array([[1], [0], [1], [1]], np.int64)
    lbl = np.array([[1], [0], [0], [1]], np.int64)
    probs = np.ones((4, 1), np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.data("i", shape=[1], dtype="int64")
        l = fluid.layers.data("l", shape=[1], dtype="int64")
        p = fluid.layers.data("p", shape=[1], dtype="float32")
        blk = main.global_block
        bm = blk.create_var(name="bm", dtype="float32")
        am = blk.create_var(name="am", dtype="float32")
        st = blk.create_var(name="st", dtype="float32")
        blk.append_op("precision_recall",
                      inputs={"MaxProbs": "p", "Indices": "i", "Labels": "l"},
                      outputs={"BatchMetrics": "bm", "AccumMetrics": "am",
                               "AccumStatesInfo": "st"},
                      attrs={"class_number": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (m,) = exe.run(main, feed={"i": idx, "l": lbl, "p": probs},
                       fetch_list=["bm"])
    m = np.asarray(m)
    # micro: TP=3 (c1:2, c0:1), FP=1, FN=1 -> P=R=0.75
    np.testing.assert_allclose(m[3], 0.75, rtol=1e-5)
    np.testing.assert_allclose(m[4], 0.75, rtol=1e-5)


def test_mean_iou():
    pred = np.array([0, 0, 1, 1], np.int64)
    lbl = np.array([0, 1, 1, 1], np.int64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = fluid.layers.data("p", shape=[-1], dtype="int64",
                              append_batch_size=False)
        l = fluid.layers.data("l", shape=[-1], dtype="int64",
                              append_batch_size=False)
        blk = main.global_block
        for n in ("miou", "wrong", "correct"):
            blk.create_var(name=n, dtype="float32")
        blk.append_op("mean_iou", inputs={"Predictions": "p", "Labels": "l"},
                      outputs={"OutMeanIou": "miou", "OutWrong": "wrong",
                               "OutCorrect": "correct"},
                      attrs={"num_classes": 2})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (miou,) = exe.run(main, feed={"p": pred, "l": lbl},
                          fetch_list=["miou"])
    # class0: i=1 u=2 -> 0.5 ; class1: i=2 u=3 -> 2/3 ; mean = 7/12
    np.testing.assert_allclose(float(np.asarray(miou)), 7 / 12, rtol=1e-5)


def test_distributions():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu.layers.distributions import Categorical, Normal, Uniform

        n1 = Normal(0.0, 1.0)
        n2 = Normal(1.0, 2.0)
        val = fluid.layers.data("v", shape=[1], dtype="float32")
        lp = n1.log_prob(val)
        ent = n2.entropy()
        kl = n1.kl_divergence(n2)
        u = Uniform(0.0, 2.0)
        ue = u.entropy()
        logits = fluid.layers.data("lg", shape=[3], dtype="float32")
        ids = fluid.layers.data("ids", shape=[1], dtype="int64")
        c = Categorical(logits)
        ce = c.entropy()
        clp = c.log_prob(ids)
        sample = n1.sample([4, 2], seed=7)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"v": np.array([[0.5]], np.float32),
            "lg": np.array([[1.0, 2.0, 0.0]], np.float32),
            "ids": np.array([[1]], np.int64)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        vals = exe.run(main, feed=feed,
                       fetch_list=[lp.name, ent.name, kl.name, ue.name,
                                   ce.name, clp.name, sample.name])
    lp_, ent_, kl_, ue_, ce_, clp_, s_ = [np.asarray(v) for v in vals]
    np.testing.assert_allclose(
        lp_.reshape(-1)[0], -0.5 * 0.25 - 0.5 * np.log(2 * np.pi), rtol=1e-5)
    np.testing.assert_allclose(
        ent_.reshape(-1)[0], np.log(2.0) + 0.5 + 0.5 * np.log(2 * np.pi),
        rtol=1e-5)
    # KL(N(0,1) || N(1,2)) = log(2) + (1 + 1)/8 - 0.5
    np.testing.assert_allclose(kl_.reshape(-1)[0],
                               np.log(2.0) + 2 / 8 - 0.5, rtol=1e-5)
    np.testing.assert_allclose(ue_.reshape(-1)[0], np.log(2.0), rtol=1e-6)
    z = np.array([1.0, 2.0, 0.0])
    p = np.exp(z - z.max()); p /= p.sum()
    np.testing.assert_allclose(ce_.reshape(-1)[0], -(p * np.log(p)).sum(),
                               rtol=1e-5)
    np.testing.assert_allclose(clp_.reshape(-1)[0], np.log(p[1]), rtol=1e-5)
    assert s_.shape == (4, 2) and np.isfinite(s_).all()


def test_detection_map_metric():
    from paddle_tpu.metrics import DetectionMAP

    m = DetectionMAP(ap_version="11point")
    # img0: one GT of class 0, detected perfectly + one FP
    m.update([[0, 0.9, 0, 0, 10, 10], [0, 0.3, 50, 50, 60, 60]],
             [[0, 0, 0, 10, 10]])
    # img1: one GT of class 0, missed entirely
    m.update([[-1, -1, -1, -1, -1, -1]], [[0, 20, 20, 30, 30]])
    v = m.eval()
    # recall caps at 0.5 -> 11-point AP = 6/11 * precision(1.0)
    np.testing.assert_allclose(v, 6 / 11, rtol=1e-6)
    m2 = DetectionMAP(ap_version="integral")
    m2.update([[0, 0.9, 0, 0, 10, 10]], [[0, 0, 0, 10, 10]])
    np.testing.assert_allclose(m2.eval(), 1.0, rtol=1e-6)
