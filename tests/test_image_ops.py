"""Image-op family vs numpy oracles (reference operators/{grid_sampler,
pixel_shuffle,affine_grid,...}_op.h kernels re-derived in numpy)."""
import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(5)


class TestGridSampler(OpTest):
    def setup(self):
        x = RNG.randn(2, 3, 5, 6).astype(np.float32)
        # sample mid-cell pixel coords (fractional part in [.25, .75]) so
        # the finite-difference probe never crosses a floor() boundary of
        # the piecewise-linear interpolant
        H, W = 5, 6
        fx = RNG.randint(0, W - 1, (2, 4, 4)) + RNG.uniform(.25, .75, (2, 4, 4))
        fy = RNG.randint(0, H - 1, (2, 4, 4)) + RNG.uniform(.25, .75, (2, 4, 4))
        gx = fx * 2 / (W - 1) - 1
        gy = fy * 2 / (H - 1) - 1
        grid = np.stack([gx, gy], axis=-1).astype(np.float32)
        N, C, H, W = x.shape
        out = np.zeros((2, 3, 4, 4), np.float32)
        for n in range(2):
            for hg in range(4):
                for wg in range(4):
                    gx, gy = grid[n, hg, wg]
                    fx = (gx + 1) * (W - 1) / 2
                    fy = (gy + 1) * (H - 1) / 2
                    x0, y0 = int(np.floor(fx)), int(np.floor(fy))
                    wx, wy = fx - x0, fy - y0
                    for dy, dx, w in ((0, 0, (1-wx)*(1-wy)),
                                      (0, 1, wx*(1-wy)),
                                      (1, 0, (1-wx)*wy), (1, 1, wx*wy)):
                        yy, xx = y0 + dy, x0 + dx
                        if 0 <= yy < H and 0 <= xx < W:
                            out[n, :, hg, wg] += w * x[n, :, yy, xx]
        self.op_type = "grid_sampler"
        self.inputs = {"X": x, "Grid": grid}
        self.outputs = {"Output": out}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["X", "Grid"], "Output", max_relative_error=3e-2)


class TestAffineGrid(OpTest):
    def setup(self):
        theta = RNG.randn(2, 2, 3).astype(np.float32)
        H, W = 3, 4
        xs = np.linspace(-1, 1, W)
        ys = np.linspace(-1, 1, H)
        out = np.zeros((2, H, W, 2), np.float32)
        for n in range(2):
            for i in range(H):
                for j in range(W):
                    base = np.array([xs[j], ys[i], 1.0])
                    out[n, i, j] = theta[n] @ base
        self.op_type = "affine_grid"
        self.inputs = {"Theta": theta}
        self.attrs = {"output_shape": [2, 3, H, W]}
        self.outputs = {"Output": out}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-5)
        self.check_grad(["Theta"], "Output", max_relative_error=1e-2)


class TestPixelShuffle(OpTest):
    def setup(self):
        x = RNG.randn(2, 8, 3, 3).astype(np.float32)
        r = 2
        N, C, H, W = x.shape
        c = C // (r * r)
        want = x.reshape(N, c, r, r, H, W).transpose(0, 1, 4, 2, 5, 3) \
                .reshape(N, c, H * r, W * r)
        self.op_type = "pixel_shuffle"
        self.inputs = {"X": x}
        self.attrs = {"upscale_factor": r}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestShuffleChannel(OpTest):
    def setup(self):
        x = RNG.randn(2, 6, 2, 2).astype(np.float32)
        g = 3
        want = x.reshape(2, g, 2, 2, 2).swapaxes(1, 2).reshape(2, 6, 2, 2)
        self.op_type = "shuffle_channel"
        self.inputs = {"X": x}
        self.attrs = {"group": g}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestSpaceToDepth(OpTest):
    def setup(self):
        x = RNG.randn(2, 3, 4, 4).astype(np.float32)
        b = 2
        want = x.reshape(2, 3, 2, b, 2, b).transpose(0, 3, 5, 1, 2, 4) \
                .reshape(2, 12, 2, 2)
        self.op_type = "space_to_depth"
        self.inputs = {"X": x}
        self.attrs = {"blocksize": b}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestTemporalShift(OpTest):
    def setup(self):
        x = RNG.randn(4, 4, 2, 2).astype(np.float32)  # N=2, T=2
        T, ratio = 2, 0.25
        v = x.reshape(2, T, 4, 2, 2)
        want = np.zeros_like(v)
        c1, c2 = 1, 2
        want[:, :-1, :c1] = v[:, 1:, :c1]
        want[:, 1:, c1:c2] = v[:, :-1, c1:c2]
        want[:, :, c2:] = v[:, :, c2:]
        self.op_type = "temporal_shift"
        self.inputs = {"X": x}
        self.attrs = {"seg_num": T, "shift_ratio": ratio}
        self.outputs = {"Out": want.reshape(4, 4, 2, 2)}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestUnfold(OpTest):
    def setup(self):
        x = RNG.randn(2, 3, 5, 5).astype(np.float32)
        kh = kw = 2
        oh = ow = 4
        want = np.zeros((2, 3 * kh * kw, oh * ow), np.float32)
        for n in range(2):
            col = 0
            for i in range(oh):
                for j in range(ow):
                    want[n, :, col] = x[n, :, i:i+kh, j:j+kw].reshape(-1)
                    col += 1
        self.op_type = "unfold"
        self.inputs = {"X": x}
        self.attrs = {"kernel_sizes": [kh, kw], "strides": [1, 1],
                      "paddings": [0, 0, 0, 0], "dilations": [1, 1]}
        self.outputs = {"Y": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Y", max_relative_error=1e-2)


class TestLRN(OpTest):
    def setup(self):
        x = RNG.randn(2, 6, 3, 3).astype(np.float32)
        n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        sq = x * x
        half = n // 2
        pad = np.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
        acc = sum(pad[:, i:i + 6] for i in range(n))
        mid = k + alpha * acc
        self.op_type = "lrn"
        self.inputs = {"X": x}
        self.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
        self.outputs = {"Out": x / mid ** beta, "MidOut": mid}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)


class TestCropPad(OpTest):
    def setup(self):
        x = RNG.randn(2, 5, 5).astype(np.float32)
        self.op_type = "crop"
        self.inputs = {"X": x}
        self.attrs = {"offsets": [0, 1, 2], "shape": [2, 3, 3]}
        self.outputs = {"Out": x[:, 1:4, 2:5]}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestPadConstantLike(OpTest):
    def setup(self):
        x = np.zeros((3, 5), np.float32)
        y = RNG.randn(2, 3).astype(np.float32)
        want = np.zeros((3, 5), np.float32)
        want[:2, :3] = y
        self.op_type = "pad_constant_like"
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()
        self.check_grad(["Y"], "Out")


class TestMaxPoolWithIndexUnpool(OpTest):
    def setup(self):
        x = RNG.randn(1, 2, 4, 4).astype(np.float32)
        out = np.zeros((1, 2, 2, 2), np.float32)
        mask = np.zeros((1, 2, 2, 2), np.int32)
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    blk = x[0, c, 2*i:2*i+2, 2*j:2*j+2]
                    out[0, c, i, j] = blk.max()
                    a = int(np.argmax(blk))
                    mask[0, c, i, j] = (2*i + a // 2) * 4 + (2*j + a % 2)
        self.op_type = "max_pool2d_with_index"
        self.inputs = {"X": x}
        self.attrs = {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]}
        self.outputs = {"Out": out, "Mask": mask}
        self._unpool_args = (out, mask, x.shape)

    def test(self):
        self.check_output()
        # unpool round-trips the pooled values to their argmax positions
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core.registry import get_op_def
        from paddle_tpu.lowering import LowerCtx

        out, mask, xshape = self._unpool_args
        res = get_op_def("unpool").lower(
            LowerCtx(), {"X": [jnp.asarray(out)],
                         "Indices": [jnp.asarray(mask)]},
            {"unpooling_type": "max", "ksize": [2, 2], "strides": [2, 2],
             "paddings": [0, 0]})["Out"][0]
        assert res.shape == xshape
        want = np.zeros(xshape, np.float32)
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    f = mask[0, c, i, j]
                    want[0, c, f // 4, f % 4] = out[0, c, i, j]
        np.testing.assert_allclose(np.asarray(res), want)


class TestConv3d(OpTest):
    def setup(self):
        x = RNG.randn(1, 2, 4, 4, 4).astype(np.float32)
        w = RNG.randn(3, 2, 2, 2, 2).astype(np.float32)
        out = np.zeros((1, 3, 3, 3, 3), np.float32)
        for o in range(3):
            for d in range(3):
                for i in range(3):
                    for j in range(3):
                        out[0, o, d, i, j] = np.sum(
                            x[0, :, d:d+2, i:i+2, j:j+2] * w[o])
        self.op_type = "conv3d"
        self.inputs = {"Input": x, "Filter": w}
        self.outputs = {"Output": out}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=2e-2)


class TestPool3d(OpTest):
    def setup(self):
        x = RNG.randn(1, 2, 4, 4, 4).astype(np.float32)
        want = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
        self.op_type = "pool3d"
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2, 2],
                      "strides": [2, 2, 2], "paddings": [0, 0, 0]}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


def test_affine_channel_and_spp():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.registry import get_op_def
    from paddle_tpu.lowering import LowerCtx

    x = RNG.randn(2, 3, 4, 4).astype(np.float32)
    s = RNG.randn(3).astype(np.float32)
    b = RNG.randn(3).astype(np.float32)
    res = get_op_def("affine_channel").lower(
        LowerCtx(), {"X": [jnp.asarray(x)], "Scale": [jnp.asarray(s)],
                     "Bias": [jnp.asarray(b)]}, {"data_layout": "NCHW"})
    np.testing.assert_allclose(
        np.asarray(res["Out"][0]),
        x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1), rtol=1e-6)

    res = get_op_def("spp").lower(
        LowerCtx(), {"X": [jnp.asarray(x)]},
        {"pyramid_height": 2, "pooling_type": "max"})["Out"][0]
    assert res.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(np.asarray(res)[:, :3],
                               x.max(axis=(2, 3)), rtol=1e-6)
