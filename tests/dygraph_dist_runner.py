"""Eager (dygraph) DataParallel runner for the launcher test (reference
TestParallelDyGraphRunnerBase, test_dist_base.py:333): each rank trains on
its slice of the SAME global batch; grads are averaged collectively, so
losses... params must match the single-process full-batch run."""
import json
import os
import sys

import numpy as np

GLOBAL_BATCH, STEPS, DIM = 8, 6, 12


def main():
    nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    if nranks > 1:
        from paddle_tpu import distributed as dist

        dist.init_parallel_env()
    else:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid
    from paddle_tpu import dygraph

    rng = np.random.RandomState(42)
    w_true = np.linspace(-1, 1, DIM).astype(np.float32).reshape(DIM, 1)
    xb = rng.rand(GLOBAL_BATCH, DIM).astype(np.float32)
    yb = (xb @ w_true).astype(np.float32)
    sl = slice(rank * (GLOBAL_BATCH // nranks),
               (rank + 1) * (GLOBAL_BATCH // nranks)) if nranks > 1 \
        else slice(None)

    with dygraph.guard():
        dygraph.seed_parameters(7)
        model = dygraph.DataParallel(dygraph.Linear(DIM, 1))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        losses = []
        x = dygraph.to_variable(xb[sl])
        y = dygraph.to_variable(yb[sl])
        for _ in range(STEPS):
            pred = model(x)
            loss = dygraph.ops.mean(dygraph.ops.square(pred - y))
            loss.backward()
            model.apply_collective_grads()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        w_final = model.state_dict()["weight"].ravel().tolist()
    if rank == 0:
        print("WFINAL " + json.dumps(w_final), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
