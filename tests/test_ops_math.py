"""OpTest coverage: activations, elementwise, reductions, softmax, scale.
(reference analogues: test_activation_op.py, test_elementwise_*_op.py,
test_reduce_op.py, test_softmax_op.py)"""
import numpy as np
import pytest

from op_test import OpTest

def _x(shape, lo=-1.0, hi=1.0, seed=42):
    """Deterministic per-call data: a fresh RandomState each time so test
    outcomes don't depend on pytest execution order."""
    rng = np.random.RandomState(seed + int(np.prod(shape)) % 1000)
    return rng.uniform(lo, hi, shape).astype(np.float32)


class _UnaryOp(OpTest):
    shape = (4, 17)
    lo, hi = -1.0, 1.0

    def setup(self):
        x = _x(self.shape, self.lo, self.hi)
        self.inputs = {"X": x}
        self.outputs = {"Out": self.ref(x.astype(np.float64)).astype(np.float32)}


UNARY_CASES = [
    ("relu", lambda x: np.maximum(x, 0), (-1, 1)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-3, 3)),
    ("tanh", np.tanh, (-3, 3)),
    ("exp", np.exp, (-2, 2)),
    ("log", np.log, (0.1, 3)),
    ("sqrt", np.sqrt, (0.1, 4)),
    ("square", np.square, (-2, 2)),
    ("abs", np.abs, (-2, 2)),
    ("softplus", lambda x: np.log1p(np.exp(x)), (-3, 3)),
    ("reciprocal", lambda x: 1 / x, (0.5, 3)),
    ("sin", np.sin, (-3, 3)),
    ("cos", np.cos, (-3, 3)),
]


@pytest.mark.parametrize("op,ref,rng", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_output_and_grad(op, ref, rng):
    class T(_UnaryOp):
        op_type = op
        lo, hi = rng
        shape = (3, 9)

        def ref(self, x):
            return ref(x)

    t = T()
    t.check_output(atol=1e-5, rtol=1e-4)
    t.check_grad(["X"], "Out", max_relative_error=5e-3)


ELEMENTWISE_CASES = [
    ("elementwise_add", np.add),
    ("elementwise_sub", np.subtract),
    ("elementwise_mul", np.multiply),
    ("elementwise_div", np.divide),
    ("elementwise_max", np.maximum),
    ("elementwise_min", np.minimum),
]


@pytest.mark.parametrize("op,ref", ELEMENTWISE_CASES,
                         ids=[c[0] for c in ELEMENTWISE_CASES])
def test_elementwise_same_shape(op, ref):
    class T(OpTest):
        op_type = op

        def setup(self):
            x = _x((3, 7), 0.5, 2.0, seed=1)
            y = _x((3, 7), 0.5, 2.0, seed=2)
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": ref(x, y)}

    t = T()
    t.check_output()
    t.check_grad(["X", "Y"], "Out", max_relative_error=6e-3)


def test_elementwise_add_axis_broadcast():
    """Paddle broadcast rule: Y [7] spans X [3,7,2] dims starting at axis 1."""
    class T(OpTest):
        op_type = "elementwise_add"

        def setup(self):
            x = _x((3, 7, 2), seed=1)
            y = _x((7,), seed=2)
            self.inputs = {"X": x, "Y": y}
            self.attrs = {"axis": 1}
            self.outputs = {"Out": x + y.reshape(1, 7, 1)}

    T().check_output()
    T().check_grad(["X", "Y"], "Out")


def test_scale():
    class T(OpTest):
        op_type = "scale"

        def setup(self):
            x = _x((4, 5))
            self.inputs = {"X": x}
            self.attrs = {"scale": 2.5, "bias": 0.7}
            self.outputs = {"Out": x * 2.5 + 0.7}

    T().check_output()
    T().check_grad(["X"], "Out")


def test_sum_op_multi_input():
    class T(OpTest):
        op_type = "sum"

        def setup(self):
            xs = [("a", _x((3, 4), seed=1)), ("b", _x((3, 4), seed=2)), ("c", _x((3, 4), seed=3))]
            self.inputs = {"X": xs}
            self.outputs = {"Out": xs[0][1] + xs[1][1] + xs[2][1]}

    T().check_output()


@pytest.mark.parametrize("op,ref", [
    ("reduce_sum", np.sum), ("reduce_mean", np.mean),
    ("reduce_max", np.max), ("reduce_min", np.min),
])
def test_reduce(op, ref):
    class T(OpTest):
        op_type = op

        def setup(self):
            x = _x((3, 5, 4))
            self.inputs = {"X": x}
            self.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
            self.outputs = {"Out": ref(x, axis=1)}

    T().check_output(atol=1e-5, rtol=1e-4)


def test_reduce_all_flag():
    class T(OpTest):
        op_type = "reduce_sum"

        def setup(self):
            x = _x((3, 5))
            self.inputs = {"X": x}
            self.attrs = {"dim": [0], "keep_dim": False, "reduce_all": True}
            self.outputs = {"Out": np.sum(x)}

    T().check_output(atol=1e-5, rtol=1e-4)
    T().check_grad(["X"], "Out")


def test_softmax():
    class T(OpTest):
        op_type = "softmax"

        def setup(self):
            x = _x((5, 11))
            e = np.exp(x - x.max(-1, keepdims=True))
            self.inputs = {"X": x}
            self.attrs = {"axis": -1}
            self.outputs = {"Out": e / e.sum(-1, keepdims=True)}

    T().check_output()
    T().check_grad(["X"], "Out")


def test_cast():
    class T(OpTest):
        op_type = "cast"

        def setup(self):
            x = _x((4, 4))
            self.inputs = {"X": x}
            self.attrs = {"in_dtype": "float32", "out_dtype": "float64"}
            self.outputs = {"Out": x.astype(np.float64)}

    T().check_output()


def test_clip():
    class T(OpTest):
        op_type = "clip"

        def setup(self):
            x = _x((4, 6), -2, 2)
            # keep away from the kink for the numeric grad
            x[np.abs(np.abs(x) - 1.0) < 0.05] = 0.0
            self.inputs = {"X": x}
            self.attrs = {"min": -1.0, "max": 1.0}
            self.outputs = {"Out": np.clip(x, -1, 1)}

    T().check_output()
    T().check_grad(["X"], "Out")


def test_matmul_transpose():
    class T(OpTest):
        op_type = "matmul"

        def setup(self):
            x = _x((4, 6))
            y = _x((5, 6))
            self.inputs = {"X": x, "Y": y}
            self.attrs = {"transpose_X": False, "transpose_Y": True,
                          "alpha": 1.0}
            self.outputs = {"Out": x @ y.T}

    T().check_output(atol=1e-5, rtol=1e-4)
    T().check_grad(["X", "Y"], "Out")


def test_matmul_batched():
    class T(OpTest):
        op_type = "matmul"

        def setup(self):
            x = _x((2, 3, 4, 6), seed=1)
            y = _x((2, 3, 6, 5), seed=2)
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": x @ y}

    T().check_output(atol=1e-5, rtol=1e-4)


def test_mul_flatten():
    class T(OpTest):
        op_type = "mul"

        def setup(self):
            x = _x((2, 3, 4))
            y = _x((12, 5))
            self.inputs = {"X": x, "Y": y}
            self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
            self.outputs = {"Out": (x.reshape(2, 12) @ y).reshape(2, 5)}

    T().check_output(atol=1e-5, rtol=1e-4)
    T().check_grad(["X", "Y"], "Out")
