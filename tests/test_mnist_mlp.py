"""End-to-end slice: MNIST-style MLP trains and the loss drops
(BASELINE config #1; reference analogue: tests/book/test_recognize_digits.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _build_mlp():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[784], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 64, act="relu")
        h = fluid.layers.fc(h, 32, act="relu")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.softmax_with_cross_entropy(logits, label)
        avg_loss = fluid.layers.mean(loss)
        acc = fluid.layers.accuracy(logits, label)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(avg_loss)
    return main, startup, avg_loss, acc


def test_mnist_mlp_loss_decreases():
    main, startup, avg_loss, acc = _build_mlp()
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)

    with fluid.scope_guard(scope):
        exe.run(startup)
        # synthetic separable task: class = argmax of 10 fixed projections
        proj = rng.randn(784, 10).astype(np.float32)
        losses = []
        for step in range(80):
            xb = rng.randn(64, 784).astype(np.float32)
            yb = np.argmax(xb @ proj, axis=1).astype(np.int64)[:, None]
            loss_v, acc_v = exe.run(main, feed={"img": xb, "label": yb},
                                    fetch_list=[avg_loss, acc])
            losses.append(float(loss_v))
    assert losses[0] > losses[-1], f"loss did not decrease: {losses}"
    assert losses[-1] < losses[0] * 0.8


def test_program_serialization_roundtrip():
    main, startup, avg_loss, acc = _build_mlp()
    js = main.to_json()
    main2 = fluid.Program.from_json(js)
    assert len(main2.global_block.ops) == len(main.global_block.ops)
    assert sorted(main2.global_block.vars) == sorted(main.global_block.vars)


def test_adam_trains():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    w_true = rng.randn(8, 1).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        first = last = None
        for i in range(60):
            xb = rng.randn(32, 8).astype(np.float32)
            yb = xb @ w_true
            (lv,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
            if first is None:
                first = float(lv)
            last = float(lv)
    assert last < first * 0.2, (first, last)
