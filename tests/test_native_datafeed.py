"""Native C++ data-feed engine (reference framework/data_feed.cc
MultiSlotDataFeed + blocking queue): compile, parse the MultiSlot text
protocol on worker threads, drain batches, agree with the Python fallback,
and feed a real training loop."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dataset_feed import DatasetFactory


def _write_files(tmp_path, n_files=3, rows_per_file=40, seed=0):
    """MultiSlot protocol: '<num> <v...>' per slot; slots: feat f32[4],
    label i64[1]."""
    rng = np.random.RandomState(seed)
    paths, all_rows = [], []
    for fi in range(n_files):
        p = tmp_path / f"part-{fi}.txt"
        with open(p, "w") as f:
            for _ in range(rows_per_file):
                feat = rng.randn(4).astype(np.float32)
                lbl = int(rng.randint(0, 2))
                f.write("4 " + " ".join(f"{v:.6f}" for v in feat) +
                        f" 1 {lbl}\n")
                all_rows.append((feat, lbl))
        paths.append(str(p))
    return paths, all_rows


def _make(paths, batch=16, threads=2):
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_use_var([("feat", "float32", 4), ("label", "int64", 1)])
    ds.set_filelist(paths)
    ds.set_thread(threads)
    ds.set_batch_size(batch)
    return ds


def test_native_engine_builds_and_parses(tmp_path):
    paths, rows = _write_files(tmp_path)
    ds = _make(paths)
    assert ds.using_native, "g++ toolchain expected in this environment"
    seen = 0
    labels = []
    for batch in ds.iter_batches():
        assert batch["feat"].shape[1:] == (4,)
        assert batch["feat"].dtype == np.float32
        assert batch["label"].dtype == np.int64
        assert len(batch["feat"]) == len(batch["label"])
        seen += len(batch["feat"])
        labels.extend(batch["label"].ravel().tolist())
    assert seen == len(rows)
    # multiset equality (threads interleave order)
    want = sorted(l for _, l in rows)
    assert sorted(labels) == want


def test_native_matches_python_fallback(tmp_path):
    paths, _ = _write_files(tmp_path, n_files=1, rows_per_file=10)
    ds = _make(paths, batch=4, threads=1)
    native_batches = list(ds.iter_batches())
    py_batches = list(_make(paths, batch=4)._iter_python())
    assert len(native_batches) == len(py_batches)
    for a, b in zip(native_batches, py_batches):
        np.testing.assert_allclose(a["feat"], b["feat"], rtol=1e-6)
        np.testing.assert_array_equal(a["label"], b["label"])


def test_malformed_rows_are_skipped(tmp_path):
    p = tmp_path / "bad.txt"
    with open(p, "w") as f:
        f.write("4 1 2 3 4 1 0\n")          # good
        f.write("3 1 2 3 1 0\n")            # wrong slot len -> skip
        f.write("4 1 2 oops 4 1 0\n")       # non-numeric -> skip
        f.write("4 9 9 9 9 1 1\n")          # good
    ds = _make([str(p)], batch=8, threads=1)
    rows = sum(len(b["label"]) for b in ds.iter_batches())
    assert rows == 2


def test_train_from_native_dataset(tmp_path):
    """End-to-end: the C++ feed drives a train loop (the reference
    exe.train_from_dataset shape)."""
    rng = np.random.RandomState(3)
    w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    p = tmp_path / "train.txt"
    with open(p, "w") as f:
        for _ in range(512):
            feat = rng.randn(4).astype(np.float32)
            y = float(feat @ w_true)
            f.write("4 " + " ".join(f"{v:.6f}" for v in feat) +
                    f" 1 {y:.6f}\n")
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_use_var([("feat", "float32", 4), ("y", "float32", 1)])
    ds.set_filelist([str(p)])
    ds.set_thread(2)
    ds.set_batch_size(64)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("feat", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for epoch in range(4):
            for batch in ds.iter_batches():
                if len(batch["feat"]) < 64:
                    continue  # fixed-shape tail drop
                (lv,) = exe.run(main, feed=batch, fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_early_abandon_does_not_deadlock(tmp_path):
    """Review regression: breaking out of iter_batches with a full queue
    must not hang df_destroy's thread join."""
    paths, _ = _write_files(tmp_path, n_files=1, rows_per_file=500)
    ds = _make(paths, batch=8, threads=2)
    ds.set_queue_capacity(16)  # force producers to park on a full queue
    it = ds.iter_batches()
    next(it)
    it.close()  # generator finally -> df_destroy; must return promptly


def test_parse_errors_counted(tmp_path):
    p = tmp_path / "bad.txt"
    with open(p, "w") as f:
        f.write("4 1 2 3 4 1 0\n")
        f.write("4 x y z w 1 0\n")
        f.write("2 1 2 1 0\n")
    ds = _make([str(p)], batch=8, threads=1)
    rows = sum(len(b["label"]) for b in ds.iter_batches())
    assert rows == 1
    assert ds.parse_errors() == 2
    # python fallback: identical skip/count semantics
    ds2 = _make([str(p)], batch=8, threads=1)
    rows2 = sum(len(b["label"]) for b in ds2._iter_python())
    assert rows2 == 1 and ds2.parse_errors() == 2


def test_slot_name_validation():
    ds = DatasetFactory().create_dataset()
    import pytest as _pt
    with _pt.raises(ValueError, match="may not contain"):
        ds.set_use_var([("a:b", "float32", 1)])
