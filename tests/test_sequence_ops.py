"""Sequence-op correctness on the padded+lengths LoD encoding (reference
operators/sequence_ops/ tests built on OpTest; oracles computed per-sequence
on the PACKED representation, so these double as padded-vs-packed
equivalence checks)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest

RNG = np.random.RandomState(7)
LENS = np.array([5, 1, 8, 3], np.int32)
MAXLEN = 8


def _padded(feat=(4,), lens=LENS, maxlen=MAXLEN, rng=RNG):
    x = np.zeros((len(lens), maxlen) + feat, np.float32)
    packed = []
    for i, L in enumerate(lens):
        s = rng.randn(L, *feat).astype(np.float32)
        x[i, :L] = s
        packed.append(s)
    return x, packed


class TestSequencePoolSum(OpTest):
    pooltype = "SUM"

    def _oracle(self, packed):
        return np.stack([{
            "SUM": s.sum(0),
            "AVERAGE": s.mean(0),
            "SQRT": s.sum(0) / np.sqrt(s.shape[0]),
            "MAX": s.max(0),
            "LAST": s[-1],
            "FIRST": s[0],
        }[self.pooltype] for s in packed])

    def setup(self):
        x, packed = _padded()
        self.op_type = "sequence_pool"
        self.inputs = {"X": x, "SeqLen": LENS}
        self.attrs = {"pooltype": self.pooltype}
        self.outputs = {"Out": self._oracle(packed)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-5)
        self.check_grad(["X"], "Out")


class TestSequencePoolAverage(TestSequencePoolSum):
    pooltype = "AVERAGE"


class TestSequencePoolSqrt(TestSequencePoolSum):
    pooltype = "SQRT"


class TestSequencePoolMax(TestSequencePoolSum):
    pooltype = "MAX"


class TestSequencePoolLast(TestSequencePoolSum):
    pooltype = "LAST"


class TestSequencePoolFirst(TestSequencePoolSum):
    pooltype = "FIRST"


class TestSequenceSoftmax(OpTest):
    def setup(self):
        x, packed = _padded(feat=())
        want = np.zeros_like(x)
        for i, s in enumerate(packed):
            e = np.exp(s - s.max())
            want[i, :len(s)] = e / e.sum()
        self.op_type = "sequence_softmax"
        self.inputs = {"X": x, "SeqLen": LENS}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestSequenceReverse(OpTest):
    def setup(self):
        x, packed = _padded(feat=(3,))
        want = x.copy()
        for i, s in enumerate(packed):
            want[i, :len(s)] = s[::-1]
        self.op_type = "sequence_reverse"
        self.inputs = {"X": x, "SeqLen": LENS}
        self.outputs = {"Y": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Y")


class TestSequenceExpand(OpTest):
    def setup(self):
        xrow = RNG.randn(4, 4).astype(np.float32)
        y, _ = _padded(feat=(2,))
        want = np.zeros((4, MAXLEN, 4), np.float32)
        for i, L in enumerate(LENS):
            want[i, :L] = xrow[i]
        self.op_type = "sequence_expand"
        self.inputs = {"X": xrow, "Y": y, "SeqLen": LENS}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSequenceConcat(OpTest):
    def setup(self):
        a, pa = _padded(feat=(2,))
        lens_b = np.array([2, 4, 1, 3], np.int32)
        b, pb = _padded(feat=(2,), lens=lens_b, maxlen=4)
        total = MAXLEN + 4
        want = np.zeros((4, total, 2), np.float32)
        out_len = LENS + lens_b
        for i in range(4):
            cat = np.concatenate([pa[i], pb[i]], 0)
            want[i, :len(cat)] = cat
        self.op_type = "sequence_concat"
        self.inputs = {"X": [("xa", a), ("xb", b)],
                       "SeqLen": [("la", LENS), ("lb", lens_b)]}
        self.outputs = {"Out": want, "OutLen": out_len.astype(np.int32)}

    def test(self):
        self.check_output()


class TestSequencePad(OpTest):
    def setup(self):
        x, packed = _padded(feat=(2,))
        pad = np.array(-1.0, np.float32)
        want = np.full((4, MAXLEN, 2), -1.0, np.float32)
        for i, s in enumerate(packed):
            want[i, :len(s)] = s
        self.op_type = "sequence_pad"
        self.inputs = {"X": x, "SeqLen": LENS, "PadValue": pad}
        self.attrs = {"padded_length": -1}
        self.outputs = {"Out": want, "Length": LENS}

    def test(self):
        self.check_output()


class TestSequenceUnpad(OpTest):
    def setup(self):
        x, packed = _padded(feat=(2,))
        x_noisy = x.copy()
        x_noisy[:, :, :] += (np.arange(MAXLEN)[None, :, None] >=
                             LENS[:, None, None]) * 9.0  # garbage in padding
        self.op_type = "sequence_unpad"
        self.inputs = {"X": x_noisy, "Length": LENS}
        self.outputs = {"Out": x, "OutLen": LENS}

    def test(self):
        self.check_output()


class TestSequenceSlice(OpTest):
    def setup(self):
        x, packed = _padded(feat=(2,))
        off = np.array([1, 0, 2, 0], np.int64)
        ln = np.array([3, 1, 4, 2], np.int64)
        want = np.zeros((4, MAXLEN, 2), np.float32)
        for i in range(4):
            want[i, :ln[i]] = x[i, off[i]:off[i] + ln[i]]
        self.op_type = "sequence_slice"
        self.inputs = {"X": x, "SeqLen": LENS, "Offset": off, "Length": ln}
        self.outputs = {"Out": want, "OutLen": ln.astype(np.int32)}

    def test(self):
        self.check_output()


class TestSequenceErase(OpTest):
    def setup(self):
        ids = np.array([[2, 1, 2, 3, 0, 0],
                        [1, 1, 1, 0, 0, 0]], np.int64)
        lens = np.array([4, 3], np.int32)
        want = np.array([[2, 2, 3, 0, 0, 0],
                         [0, 0, 0, 0, 0, 0]], np.int64)
        self.op_type = "sequence_erase"
        self.inputs = {"X": ids, "SeqLen": lens}
        self.attrs = {"tokens": [1]}
        self.outputs = {"Out": want, "OutLen": np.array([3, 0], np.int32)}

    def test(self):
        self.check_output()


class TestSequenceEnumerate(OpTest):
    def setup(self):
        ids = np.array([[1, 2, 3, 4, 0], [5, 6, 0, 0, 0]], np.int64)
        lens = np.array([4, 2], np.int32)
        want = np.array([[[1, 2], [2, 3], [3, 4], [4, 9], [9, 9]],
                         [[5, 6], [6, 9], [9, 9], [9, 9], [9, 9]]], np.int64)
        self.op_type = "sequence_enumerate"
        self.inputs = {"X": ids, "SeqLen": lens}
        self.attrs = {"win_size": 2, "pad_value": 9}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestSequenceConv(OpTest):
    def setup(self):
        x, packed = _padded(feat=(3,))
        w = RNG.randn(9, 5).astype(np.float32) * 0.3
        want = np.zeros((4, MAXLEN, 5), np.float32)
        for i, s in enumerate(packed):
            L = len(s)
            for t in range(L):
                ctx = []
                for k in range(3):
                    j = t - 1 + k
                    ctx.append(s[j] if 0 <= j < L else np.zeros(3, np.float32))
                want[i, t] = np.concatenate(ctx) @ w
        self.op_type = "sequence_conv"
        self.inputs = {"X": x, "Filter": w, "SeqLen": LENS}
        self.attrs = {"contextLength": 3, "contextStart": -1}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["X", "Filter"], "Out", max_relative_error=0.01)


class TestSequenceMask(OpTest):
    def setup(self):
        ln = np.array([3, 0, 5], np.int64)
        want = (np.arange(6)[None, :] < ln[:, None]).astype(np.float32)
        self.op_type = "sequence_mask"
        self.inputs = {"X": ln}
        self.attrs = {"maxlen": 6, "out_dtype": "float32"}
        self.outputs = {"Y": want}

    def test(self):
        self.check_output()


# ---------------------------------------------------------------------------
# layer-level: varlen feed, bucketing, LoD inference through embedding
# ---------------------------------------------------------------------------

def test_varlen_bow_model_trains_with_bucketing():
    """IMDB-style bag-of-words: embedding over varlen ids -> sequence_pool
    -> fc. Lengths are inferred through the embedding op; DataFeeder pads
    to buckets so the executor compiles once per bucket, not per batch."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            words = fluid.layers.data("words", shape=[1], dtype="int64",
                                      lod_level=1)
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            emb = fluid.layers.embedding(words, size=[100, 16])
            pooled = fluid.layers.sequence_pool(emb, "average")
            logits = fluid.layers.fc(pooled, 2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    main.random_seed = 11

    feeder = fluid.DataFeeder(feed_list=[words, label], program=main)
    rng = np.random.RandomState(0)

    def make_batch():
        samples = []
        for _ in range(16):
            y = int(rng.randint(0, 2))
            L = int(rng.randint(3, 12))  # all batches land in bucket 16
            lo, hi = (0, 50) if y else (50, 100)
            samples.append((rng.randint(lo, hi, L), np.array([y])))
        return feeder.feed(samples)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(30):
            (lv,) = exe.run(main, feed=make_batch(), fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5
    # bucketing: every batch padded to 16 -> one compiled train step (the
    # second cache entry is the startup program)
    assert len(exe._cache) == 2, f"expected 2 cached steps, got {len(exe._cache)}"


def test_bucket_length():
    from paddle_tpu.data_feeder import DEFAULT_SEQ_BUCKETS, bucket_length

    assert bucket_length(3, DEFAULT_SEQ_BUCKETS) == 8
    assert bucket_length(8, DEFAULT_SEQ_BUCKETS) == 8
    assert bucket_length(100, DEFAULT_SEQ_BUCKETS) == 128
    assert bucket_length(5000, DEFAULT_SEQ_BUCKETS) == 8192


def test_seq_len_var_error_message():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("plain", shape=[4], dtype="float32")
        with pytest.raises(ValueError, match="lod_level=1"):
            fluid.layers.sequence_pool(x, "sum")
