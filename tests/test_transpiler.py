"""DistributeTranspiler shim — r3/r4 done-criterion test.

A 2019-style parameter-server script (2 pservers x 2 trainers config) runs
through transpile() -> get_trainer_program()/get_pserver_program() ->
Executor.run. On TPU there are no pservers (see transpiler package
docstring): the trainer program IS the original program, pserver programs
are no-ops that return immediately. Reference flow:
python/paddle/fluid/transpiler/distribute_transpiler.py:494 (transpile),
:832 (get_trainer_program), :966 (get_pserver_program).
"""
import numpy as np
import pytest

import paddle_tpu as fluid


PSERVERS = "127.0.0.1:6174,127.0.0.1:6175"
EPS = PSERVERS.split(",")


def _build_net():
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1, act=None)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _run_steps(main, startup, loss, steps=3):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(7)
    feeds = [{"x": rng.rand(8, 4).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)} for _ in range(steps)]
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for f in feeds:
            out = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def test_pserver_sync_script_end_to_end():
    """The full 2019 flow: trainer losses match plain (untranspiled)
    execution exactly, and every pserver program returns immediately."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        loss = _build_net()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        plain = _run_steps(main, startup, loss)

        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, pservers=PSERVERS, trainers=2,
                    sync_mode=True, program=main, startup_program=startup)

        trainer_prog = t.get_trainer_program()
        assert trainer_prog is main  # gradient exchange is GSPMD's job
        transpiled = _run_steps(trainer_prog, startup, loss)
        assert plain == transpiled

        exe = fluid.Executor(fluid.CPUPlace())
        for ep in EPS:
            pserver_main, pserver_startup = t.get_pserver_programs(ep)
            assert exe.run(pserver_startup) == []
            assert exe.run(pserver_main) == []


def test_param_shard_layout_recorded():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        _build_net()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, pservers=PSERVERS, trainers=2)
        mapping = t.param_grad_ep_mapping
        assert set(mapping) == set(EPS)
        placed = [p.name for ep in EPS for p in mapping[ep]["params"]]
        # fc weight + bias, each on exactly one endpoint
        assert len(placed) == len(set(placed)) == 2


def test_pserver_program_unknown_endpoint_rejected():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        _build_net()
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, pservers=PSERVERS, trainers=2)
        with pytest.raises(ValueError):
            t.get_pserver_program("10.0.0.1:9999")


def test_async_mode_raises_with_migration_path():
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        _build_net()
        t = fluid.DistributeTranspiler()
        with pytest.raises(NotImplementedError, match="LocalSGD|local_sgd"):
            t.transpile(trainer_id=0, pservers=PSERVERS, trainers=2,
                        sync_mode=False)


@pytest.mark.parametrize("mode", ["nccl2", "collective"])
def test_collective_modes_record_endpoints(mode):
    """nccl2/collective record the cluster and return the program unchanged;
    sync_mode is ignored (reference returns before the pserver machinery)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        _build_net()
        cfg = fluid.DistributeTranspilerConfig()
        cfg.mode = mode
        t = fluid.DistributeTranspiler(config=cfg)
        eps = "10.0.0.1:6170,10.0.0.2:6170"
        t.transpile(trainer_id=0, trainers=eps, sync_mode=False,
                    current_endpoint="10.0.0.1:6170")
        assert t.trainer_endpoints == eps.split(",")
        assert t.trainer_num == 2
        assert t.get_trainer_program() is fluid.default_main_program()


def test_collective_mode_rejects_int_trainers():
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = "collective"
    t = fluid.DistributeTranspiler(config=cfg)
    with pytest.raises(ValueError, match="endpoint string"):
        t.transpile(trainer_id=0, trainers=2)


def test_top_level_reexports():
    """ADVICE r4: fluid.DistributeTranspiler & co must be reachable the way
    reference fluid/__init__.py:65,74 exposes them."""
    for name in ("DistributeTranspiler", "DistributeTranspilerConfig",
                 "memory_optimize", "release_memory"):
        assert hasattr(fluid, name)
    assert fluid.transpiler.DistributeTranspiler is fluid.DistributeTranspiler
