"""paddle_tpu.serving: continuous batching, admission control, deadlines,
circuit breaker, degradation — plus the executor thread-safety regression
the serving dispatch thread depends on.

Every test drives the PUBLIC surface (submit/result/health/accounting);
the exactly-one-terminal-outcome contract is asserted through
``accounting()['exact']`` wherever chaos is injected."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.resilience import fault_plan_guard
from paddle_tpu.resilience.deadline import Deadline, DeadlineExceeded


@pytest.fixture(autouse=True)
def _flags_and_plan_reset():
    """Serving tests flip watchdog/fault flags; restore the override map
    and drop any installed fault plan so later tests see defaults."""
    from paddle_tpu import flags as flags_mod
    from paddle_tpu.resilience import faults

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    faults.clear_plan()


def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(config=None, **cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = config or serving.ServingConfig(
        max_batch=cfg_kw.pop("max_batch", 4), **cfg_kw)
    eng = serving.ServingEngine(infer, feed_names=["x"], fetch_list=[pred],
                                scope=scope, executor=exe, config=cfg)
    return eng


def _feed(rows=1, seed=None):
    rng = np.random.RandomState(seed if seed is not None else 0)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


def _wait_queue_empty(eng, timeout=10.0):
    """Block until every queued request has been TAKEN by the dispatcher
    (dispatched or in flight — e.g. stalled in a hang), so the next
    submit cannot coalesce into the same batch. Accounting's ``pending``
    deliberately still counts in-flight requests, so poll the queue."""
    until = time.monotonic() + timeout
    while time.monotonic() < until:
        if not eng._queue:
            return
        time.sleep(0.01)
    raise AssertionError("dispatcher never drained the queue")


# ---------------------------------------------------------------------------
# batching into buckets
# ---------------------------------------------------------------------------

def test_requests_coalesce_into_one_padded_bucket():
    """Three 1-row requests inside one batch window dispatch as ONE batch
    padded to the 4-bucket, and each caller gets exactly its own rows."""
    # absolute assertion on the occupancy histogram's max below: clear
    # the process-global registry so another test's engines (any order)
    # cannot leak a 1.0-occupancy observation in
    monitor.reset()
    eng = _engine(max_batch=4, batch_window_s=0.5)
    eng.warm_up()
    before = monitor.metric_value("serving_batches_total", 0.0, result="ok")
    with eng:
        futs = [eng.submit(_feed(seed=i)) for i in range(3)]
        outs = [f.result(timeout=30) for f in futs]
    assert all(o[0].shape == (1, 4) for o in outs)
    got = monitor.metric_value("serving_batches_total", 0.0, result="ok")
    assert got - before == 1, "3 requests inside one window must be 1 batch"
    occ = monitor.metric_value("serving_batch_occupancy")
    assert occ["count"] >= 1 and abs(occ["max"] - 0.75) < 1e-6  # 3 rows / 4


def test_batched_results_match_direct_execution():
    """Padding + slicing must be invisible: a request's rows equal what a
    direct exe.run of just that request returns."""
    eng = _engine(max_batch=8, batch_window_s=0.3)
    with eng:
        feeds = [_feed(rows=r, seed=i) for i, r in enumerate((1, 2, 1))]
        futs = [eng.submit(f) for f in feeds]
        outs = [f.result(timeout=30) for f in futs]
    for f, o in zip(feeds, outs):
        direct = eng._exe.run(eng._program, feed=f,
                              fetch_list=eng._fetch_names, scope=eng._scope)
        np.testing.assert_allclose(o[0], direct[0], rtol=1e-5, atol=1e-6)


def test_distinct_shapes_land_in_distinct_buckets():
    """Different example shapes never share a batch; both succeed."""
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    eng = serving.ServingEngine(infer, feed_names=["x"], fetch_list=[pred],
                                scope=scope, executor=exe,
                                config=serving.ServingConfig(max_batch=4))
    with eng:
        f1 = eng.submit({"x": np.zeros((1, 13), np.float32)})
        f2 = eng.submit({"x": np.zeros((2, 13), np.float32)})
        r1, r2 = f1.result(timeout=30), f2.result(timeout=30)
    assert r1[0].shape == (1, 4) and r2[0].shape == (2, 4)


def test_warm_up_precompiles_every_bucket():
    eng = _engine(max_batch=4)
    misses0 = monitor.metric_value("executor_cache_lookups_total", 0.0,
                                   path="run", result="miss")
    assert eng.warm_up() == 3   # buckets 1, 2, 4
    misses1 = monitor.metric_value("executor_cache_lookups_total", 0.0,
                                   path="run", result="miss")
    assert misses1 - misses0 == 3
    with eng:
        assert eng.submit(_feed()).result(timeout=30)[0].shape == (1, 4)
    misses2 = monitor.metric_value("executor_cache_lookups_total", 0.0,
                                   path="run", result="miss")
    assert misses2 == misses1, "warmed bucket must be a cache hit"


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_expiry_is_typed_and_swept():
    """A queued request whose deadline passes while a hang occupies the
    dispatcher gets DeadlineExceeded, not a stale late response."""
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    eng = _engine(max_batch=4)
    eng.warm_up()
    with eng, fault_plan_guard("hang:@1:hang"):
        f_hang = eng.submit(_feed())
        _wait_queue_empty(eng)    # the hang batch must dispatch alone
        f_dead = eng.submit(_feed(), deadline_s=0.3)
        err_hang = f_hang.exception(timeout=60)
        err_dead = f_dead.exception(timeout=60)
    assert isinstance(err_dead, DeadlineExceeded)
    assert isinstance(err_hang, serving.BatchFailed)
    acct = eng.accounting()
    assert acct["exact"] and acct["deadline_exceeded"] == 1


def test_default_deadline_from_config():
    """submit() without deadline_s inherits the config default: with a
    1 ms default and a hang occupying the dispatcher, at least one
    request must expire typed — proof the default applied at all."""
    eng = _engine(max_batch=4, deadline_s=0.001)
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    eng.warm_up()
    with eng, fault_plan_guard("hang:@1:hang"):
        f1 = eng.submit(_feed())
        _wait_queue_empty(eng)
        f2 = eng.submit(_feed())
        errs = [f1.exception(timeout=60), f2.exception(timeout=60)]
    assert any(isinstance(e, DeadlineExceeded) for e in errs), errs
    acct = eng.accounting()
    assert acct["exact"] and acct["deadline_exceeded"] >= 1


# ---------------------------------------------------------------------------
# admission control / shedding
# ---------------------------------------------------------------------------

def test_full_queue_sheds_typed_overloaded():
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    eng = _engine(max_batch=1, queue_depth=2)
    eng.warm_up()
    with eng, fault_plan_guard("hang:@1:hang"):
        futs = [eng.submit(_feed())]          # dispatched, hangs
        _wait_queue_empty(eng)
        futs += [eng.submit(_feed()), eng.submit(_feed())]  # queue full
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed())
        assert ei.value.reason == "queue_full"
        for f in futs:
            f.exception(timeout=60)            # all settle eventually
    acct = eng.accounting()
    assert acct["exact"] and acct["shed"] == 1
    assert monitor.metric_value("serving_shed_total", 0.0,
                                reason="queue_full") >= 1


def test_injected_overload_site_forces_shed():
    eng = _engine(max_batch=4)
    with eng, fault_plan_guard("overload:1:RuntimeError"):
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed())
        assert ei.value.reason == "injected"
        # next request sails through
        assert eng.submit(_feed()).result(timeout=30)[0].shape == (1, 4)
    assert eng.accounting()["exact"]


def test_enqueue_fault_is_typed_submission_failure():
    from paddle_tpu.resilience.faults import InjectedFault

    eng = _engine(max_batch=4)
    with eng, fault_plan_guard("enqueue:1:RuntimeError"):
        with pytest.raises(InjectedFault):
            eng.submit(_feed())
        assert eng.submit(_feed()).result(timeout=30)[0].shape == (1, 4)
    acct = eng.accounting()
    assert acct["exact"] and acct["rejected_fault"] == 1


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_open_halfopen_close_cycle():
    eng = _engine(max_batch=4, breaker_threshold=2, breaker_cooldown_s=0.2)
    eng.warm_up()
    with eng:
        with fault_plan_guard("batch_dispatch:2:RuntimeError"):
            for _ in range(2):
                err = eng.submit(_feed()).exception(timeout=30)
                assert isinstance(err, serving.BatchFailed)
        # open: immediate rejection, no dispatch
        err = eng.submit(_feed()).exception(timeout=30)
        assert isinstance(err, serving.CircuitOpen)
        assert eng.health()["status"] == "degraded"
        assert [b["state"] for b in eng.health()["open_buckets"]] == ["open"]
        # past cooldown: half-open probe succeeds and closes
        time.sleep(0.6)
        out = eng.submit(_feed()).result(timeout=30)
        assert out[0].shape == (1, 4)
        assert eng.health()["status"] == "ok"
        assert eng.health()["open_buckets"] == []
    acct = eng.accounting()
    assert acct["exact"] and acct["circuit_open"] == 1 \
        and acct["failed"] == 2
    assert monitor.metric_value("serving_breaker_transitions_total", 0.0,
                                to="closed") >= 1


def test_breaker_failed_probe_reopens():
    eng = _engine(max_batch=4, breaker_threshold=1, breaker_cooldown_s=0.1)
    eng.warm_up()
    with eng:
        with fault_plan_guard("batch_dispatch:2:RuntimeError"):
            err = eng.submit(_feed()).exception(timeout=30)   # opens
            assert isinstance(err, serving.BatchFailed)
            time.sleep(0.3)
            err = eng.submit(_feed()).exception(timeout=30)   # probe fails
            assert isinstance(err, serving.BatchFailed)
        assert [b["state"] for b in eng.health()["open_buckets"]] == ["open"]
        # the re-open cooldown backs off (retry schedule): wait longer
        time.sleep(1.0)
        assert eng.submit(_feed()).result(timeout=30)[0].shape == (1, 4)
    assert eng.accounting()["exact"]


def test_breaker_isolation_other_bucket_keeps_serving():
    """A quarantined bucket must not affect a different shape bucket."""
    eng = _engine(max_batch=4, breaker_threshold=1,
                  breaker_cooldown_s=30.0)
    eng.warm_up()
    with eng:
        with fault_plan_guard("batch_dispatch:1:RuntimeError"):
            eng.submit(_feed(rows=1)).exception(timeout=30)
        err = eng.submit(_feed(rows=1)).exception(timeout=30)
        assert isinstance(err, serving.CircuitOpen)
        # 2-row requests land in the b2 bucket: unaffected
        assert eng.submit(_feed(rows=2)).result(timeout=30)[0].shape == (2, 4)
    assert eng.accounting()["exact"]


# ---------------------------------------------------------------------------
# graceful degradation + recovery
# ---------------------------------------------------------------------------

def _degradation_harness(eng):
    """Deterministic degradation driver (deflaked at ISSUE 20, see
    KNOWN_FAILURES.md): the sustain windows read the engine's injectable
    clock (``eng._now``, the autoscaler idiom) advanced by a test-owned
    offset, and the dispatcher is parked by gating ``_take_batch_locked``
    so queued requests create pressure for exactly as long as the test
    wants — no wall-clock sleeps racing the dispatch thread. Returns
    ``(advance, release)``."""
    off = [0.0]
    eng._now = lambda: time.monotonic() + off[0]
    hold = [True]
    orig_take = eng._take_batch_locked
    # the gate yields once the engine stops, so a failing assert can
    # never leave the dispatcher spinning on an undrainable queue
    eng._take_batch_locked = \
        lambda now: [] if hold[0] and eng._running else orig_take(now)

    def advance(seconds):
        off[0] += seconds

    def release():
        hold[0] = False

    return advance, release


def _wait_health(eng, key, want, timeout=10.0):
    until = time.monotonic() + timeout
    while time.monotonic() < until:
        if eng.health()[key] == want:
            return
        time.sleep(0.01)
    raise AssertionError(f"health[{key!r}] never became {want!r}")


def test_degradation_sheds_priority_and_recovers():
    eng = _engine(max_batch=4, queue_depth=3, degrade_after_s=5.0,
                  recover_after_s=5.0, degraded_min_priority=1,
                  queue_age_s=0.0)
    eng.warm_up()
    advance, release = _degradation_harness(eng)
    with eng:
        f1 = eng.submit(_feed(), priority=5)
        f2 = eng.submit(_feed(), priority=5)
        # two parked requests >= 3/4 of queue_depth: pressure holds, but
        # the sustain window has not elapsed on the injected clock
        assert not eng.health()["degraded"]
        advance(6.0)                           # past degrade_after_s
        _wait_health(eng, "degraded", True)
        assert eng.health()["current_max_batch"] == 2
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed(), priority=0)    # below min priority
        assert ei.value.reason == "priority"
        # high-priority traffic still admitted while degraded
        f3 = eng.submit(_feed(), priority=3)
        release()
        for f in (f1, f2, f3):
            assert f.result(timeout=30)[0].shape == (1, 4)
        # queue drained -> calm; advancing past recover_after_s restores
        # the full ceiling at the dispatcher's next idle tick
        advance(6.0)
        _wait_health(eng, "degraded", False)
        assert eng.health()["current_max_batch"] == 4
        assert eng.submit(_feed(), priority=0).result(timeout=30)
    acct = eng.accounting()
    assert acct["exact"] and acct["shed"] == 1
    assert monitor.metric_value("serving_degradations_total", 0.0) >= 1


def test_degraded_mode_still_dispatches_oversized_requests():
    """A request wider than the degraded batch ceiling (but within
    max_batch) must dispatch alone, never strand without an outcome."""
    eng = _engine(max_batch=4, queue_depth=3, degrade_after_s=5.0,
                  recover_after_s=30.0, degraded_min_priority=1,
                  queue_age_s=0.0)
    eng.warm_up()
    advance, release = _degradation_harness(eng)
    with eng:
        f1 = eng.submit(_feed(), priority=5)
        f2 = eng.submit(_feed(), priority=5)
        advance(6.0)                           # sustain -> degraded
        _wait_health(eng, "degraded", True)
        assert eng.health()["current_max_batch"] == 2
        f3 = eng.submit(_feed(rows=3), priority=5)   # 3 > degraded cap 2
        release()
        assert f3.result(timeout=30)[0].shape == (3, 4)
        for f in (f1, f2):
            f.result(timeout=30)
    assert eng.accounting()["exact"]


# ---------------------------------------------------------------------------
# negative control: clean traffic has a clean ledger
# ---------------------------------------------------------------------------

def test_no_faults_zero_sheds_zero_rejections():
    eng = _engine(max_batch=4)
    eng.warm_up()
    with eng:
        futs = [eng.submit(_feed(seed=i)) for i in range(20)]
        outs = [f.result(timeout=60) for f in futs]
    assert len(outs) == 20
    acct = eng.accounting()
    recent = acct.pop("recent_outcomes")
    assert acct == {"submitted": 20, "completed": 20, "failed": 0,
                    "poisoned": 0, "shed": 0, "deadline_exceeded": 0,
                    "circuit_open": 0, "rejected_fault": 0,
                    "rejected_stopped": 0, "pending": 0, "accounted": 20,
                    "exact": True}
    # every terminal outcome is attributable (trace ids are "" with
    # FLAGS_trace off, but the outcome ring is always kept)
    assert len(recent) == 20
    assert all(r["outcome"] == "completed" for r in recent)
    assert eng.health()["open_buckets"] == []
    assert not eng.health()["degraded"]


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def test_stop_without_drain_fails_queued_typed():
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    eng = _engine(max_batch=1)
    eng.warm_up()
    with fault_plan_guard("hang:@1:hang"):
        eng.start()
        f1 = eng.submit(_feed())
        _wait_queue_empty(eng)
        f2 = eng.submit(_feed())
        eng.stop(drain=False, timeout=60)
        assert isinstance(f1.exception(timeout=60), serving.BatchFailed)
        assert isinstance(f2.exception(timeout=60), serving.EngineStopped)
    with pytest.raises(serving.EngineStopped):
        eng.submit(_feed())
    assert eng.accounting()["exact"]
    assert not eng.ready()


def test_submit_before_start_is_typed():
    eng = _engine(max_batch=4)
    with pytest.raises(serving.EngineStopped):
        eng.submit(_feed())
    assert eng.accounting()["exact"]


def test_malformed_feed_never_enters_accounting():
    eng = _engine(max_batch=4)
    with eng:
        with pytest.raises(ValueError):
            eng.submit({})                      # empty
        with pytest.raises(ValueError):
            eng.submit({"wrong": np.zeros((1, 13), np.float32)})
        with pytest.raises(ValueError):
            eng.submit({"x": np.zeros((99, 13), np.float32)})  # > max_batch
    assert eng.accounting()["submitted"] == 0


# ---------------------------------------------------------------------------
# watchdog: slow batch in the (non-main) dispatch thread dies diagnosed
# ---------------------------------------------------------------------------

def test_hang_in_dispatch_thread_dies_under_watchdog():
    from paddle_tpu.resilience.distributed import WatchdogTimeout

    fluid.set_flags({"FLAGS_step_timeout_s": 1.0,
                     "FLAGS_watchdog_hard_exit": 0})
    eng = _engine(max_batch=4, breaker_threshold=10)
    eng.warm_up()
    with eng, fault_plan_guard("hang:@1:hang"):
        t0 = time.monotonic()
        fut = eng.submit(_feed())
        _wait_queue_empty(eng)
        # invariant holds mid-flight too: the hung request is pending
        mid = eng.accounting()
        assert mid["exact"] and mid["pending"] == 1
        err = fut.exception(timeout=60)
        took = time.monotonic() - t0
        assert isinstance(err, serving.BatchFailed)
        assert isinstance(err.__cause__, WatchdogTimeout)
        assert took < 30, "hang must die at the deadline, not ride it out"
        # engine survives and keeps serving
        assert eng.submit(_feed()).result(timeout=30)[0].shape == (1, 4)
    acct = eng.accounting()
    assert acct["exact"] and acct["failed"] == 1 and acct["completed"] == 1
    assert monitor.metric_value("watchdog_timeouts_total", 0.0,
                                section="step") >= 1


def test_watchdog_interrupts_plain_worker_thread():
    """The distributed-layer primitive itself: a section armed in a
    non-main thread is broken with a typed WatchdogTimeout."""
    from paddle_tpu.resilience import distributed as dist

    fluid.set_flags({"FLAGS_watchdog_hard_exit": 0})
    out = {}

    def body():
        try:
            with dist.watchdog_section("step", timeout=0.5):
                while True:
                    time.sleep(0.02)
        except dist.WatchdogTimeout as e:
            out["err"] = e
        except BaseException as e:   # pragma: no cover - diagnosis aid
            out["err"] = e

    t = threading.Thread(target=body)
    t.start()
    t.join(30)
    assert not t.is_alive(), "watchdog failed to break the worker thread"
    assert isinstance(out.get("err"), dist.WatchdogTimeout)


# ---------------------------------------------------------------------------
# executor thread-safety regression (the satellite serving depends on)
# ---------------------------------------------------------------------------

def test_two_threads_distinct_scopes_no_cache_corruption():
    """Two threads hammer ONE executor + ONE program against their own
    scopes: no exceptions, finite results, and exactly one step-cache
    entry per scope serial."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[13], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(7)
    feed = {"x": rng.rand(8, 13).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}
    results, errors = {}, []

    def worker(tid):
        try:
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup, scope=scope)
            vals = []
            for _ in range(12):
                out = exe.run(main, feed=feed, fetch_list=[loss],
                              scope=scope)
                vals.append(float(out[0]))
            results[tid] = vals
        except BaseException as e:
            errors.append((tid, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    assert not errors, f"concurrent executor runs failed: {errors}"
    assert len(results) == 2
    for vals in results.values():
        assert all(np.isfinite(v) for v in vals)
        assert vals[-1] < vals[0], "training must still make progress"
    # one training-step cache entry per scope (startup adds its own pair)
    scope_serials = {k[3] for k in exe._cache if isinstance(k[3], int)}
    assert len(exe._cache) == 4 and len(scope_serials) == 2


def test_scope_concurrent_set_find():
    scope = fluid.Scope()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            scope.set_var(f"v{i % 50}", np.full((4,), i))
            i += 1

    def reader():
        try:
            while not stop.is_set():
                for i in range(50):
                    v = scope.find_var(f"v{i}")
                    if v is not None:
                        np.asarray(v)
        except BaseException as e:
            errors.append(e)

    ts = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in ts:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in ts:
        t.join(10)
    assert not errors
