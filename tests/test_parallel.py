"""Distributed correctness: same model, 1 device vs 8-device mesh — losses
must match (reference test strategy: parallel_executor_test_base.py and
test_dist_base.py:827 check_with_place)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models.mlp import build_mnist_mlp


def _train(compiled: bool, steps=5, batch=64):
    import paddle_tpu.unique_name as un

    with un.guard():
        model = build_mnist_mlp(hidden=(32,), lr=0.5)
    model["main"].random_seed = 17
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(3)
    prog = model["main"]
    if compiled:
        prog = fluid.CompiledProgram(model["main"]).with_data_parallel(
            loss_name=model["loss"].name)
    # fixed batch -> memorizable -> loss must fall; same data both runs
    xb = rng.randn(batch, 784).astype(np.float32)
    yb = rng.randint(0, 10, (batch, 1)).astype(np.int64)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"img": xb, "label": yb},
                            fetch_list=[model["loss"].name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_data_parallel_matches_single_device():
    """Startup inits must be identical across runs (startup program random
    ops use fixed per-op uid keys via program.random_seed path), so the two
    runs see the same params and identical data -> identical losses."""
    single = _train(compiled=False)
    parallel = _train(compiled=True)
    # fp32 reduction-order differences accumulate over steps; the reference
    # dist tests use delta tolerances too (test_dist_base.py check_with_place)
    np.testing.assert_allclose(single, parallel, rtol=5e-3, atol=1e-4)
    assert single[0] > single[-1]


def _train_strategy(reduce_strategy, steps=6, batch=64):
    import paddle_tpu.unique_name as un

    with un.guard():
        model = build_mnist_mlp(hidden=(32,), lr=0.01, optimizer="adam")
    model["main"].random_seed = 17
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    bs = fluid.BuildStrategy()
    bs.reduce_strategy = reduce_strategy
    prog = fluid.CompiledProgram(model["main"]).with_data_parallel(
        loss_name=model["loss"].name, build_strategy=bs)
    rng = np.random.RandomState(3)
    xb = rng.randn(batch, 784).astype(np.float32)
    yb = rng.randint(0, 10, (batch, 1)).astype(np.int64)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(model["startup"])
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"img": xb, "label": yb},
                            fetch_list=[model["loss"].name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses, scope


def test_reduce_strategy_zero1_matches_allreduce():
    """ZeRO-1 (ReduceStrategy.Reduce): Adam moments sharded over dp must
    train identically to the replicated AllReduce path (reference
    multi_devices_graph_pass.h:157 ReduceSSAGraphBuilder semantics)."""
    RS = fluid.BuildStrategy.ReduceStrategy
    base, _ = _train_strategy(RS.AllReduce)
    zero, scope = _train_strategy(RS.Reduce)
    np.testing.assert_allclose(base, zero, rtol=5e-3, atol=1e-4)
    assert base[0] > base[-1]

    # the optimizer state must actually be dp-sharded in the scope
    sharded = [n for n, v in scope.vars.items()
               if "moment" in n and hasattr(v, "sharding")
               and "dp" in str(v.sharding.spec)]
    assert sharded, f"no dp-sharded moments found in {list(scope.vars)}"


@pytest.mark.known_flaky(
    reason="KNOWN_FAILURES.md 'Pre-existing flake': intermittently "
           "misses its rtol=2e-5 pipeline-vs-plain loss comparison in "
           "whole-SUITE runs only (1-ULP CPU-reduction amplification "
           "over 3 SGD steps); passes standalone and with any reduced "
           "selection. Expect ±1 on the tier-1 count")
def test_sharded_bert_tp_dp_one_step():
    """Megatron-style tp x dp sharded BERT train step compiles and runs on
    the 8-device CPU mesh (the dryrun_multichip path, as a regression test)."""
    import sys
    sys.path.insert(0, ".")
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_zero1_hlo_has_sharded_collectives():
    """VERDICT r5 item 9: the only scaling-efficiency evidence this
    environment can produce — compile ReduceStrategy.Reduce on the 8-device
    CPU mesh and assert the optimized HLO moves grads/params with sharded
    collectives (reduce-scatter / all-gather, possibly fused as
    all-reduce + dynamic-slice by the partitioner), the way
    test_pipeline.py asserts collective-permute."""
    import re

    import jax

    def hlo_for(reduce_strategy):
        import paddle_tpu.unique_name as un

        with un.guard():
            model = build_mnist_mlp(hidden=(32,), lr=0.01, optimizer="adam")
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        bs = fluid.BuildStrategy()
        bs.reduce_strategy = reduce_strategy
        cp = fluid.CompiledProgram(model["main"]).with_data_parallel(
            loss_name=model["loss"].name, build_strategy=bs)
        rng = np.random.RandomState(3)
        feed = {"img": rng.randn(64, 784).astype(np.float32),
                "label": rng.randint(0, 10, (64, 1)).astype(np.int64)}
        with fluid.scope_guard(scope):
            exe.run(model["startup"])
            step = cp._get_compiled(exe, model["main"], feed,
                                    [model["loss"].name], scope)
            feed_vals = [np.asarray(feed[n]) for n in step.feed_names]
            donated = [np.asarray(scope.find_var(n))
                       for n in step.donated_names]
            ro = [np.asarray(scope.find_var(n)) for n in step.ro_names]
            return step.fn.lower(feed_vals, donated, ro,
                                 jax.random.key(0)).compile().as_text()

    RS = fluid.BuildStrategy.ReduceStrategy
    zero = hlo_for(RS.Reduce)
    base = hlo_for(RS.AllReduce)

    def counts(t):
        return {p: len(re.findall(p, t))
                for p in ("all-reduce", "reduce-scatter", "all-gather",
                          "dynamic-slice")}

    cz, cb = counts(zero), counts(base)
    # grads must be exchanged in both modes
    assert cb["all-reduce"] > 0, cb
    # ZeRO-1: each dp rank updates only its optimizer-state shard, so the
    # Reduce HLO must slice into shards (reduce-scatter, or the
    # partitioner's all-reduce + dynamic-slice fusion of it) and rebuild
    # full params (all-gather)
    assert cz["reduce-scatter"] + cz["dynamic-slice"] > \
        cb["reduce-scatter"] + cb["dynamic-slice"], (cz, cb)
    assert cz["all-gather"] > cb["all-gather"], (cz, cb)
