"""paddle_tpu.ops.quant_ops — forward parity against the reference
fake_quantize_op.h formulas and the straight-through-estimator backward
through append_backward (ISSUE 17 satellite: the numerics analysis
polices these ops' IR contract, this file proves their arithmetic)."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.backward import append_backward

RNG = np.random.RandomState(11)


def _ref_quant(v, scale, bits=8):
    """ClipAndFakeQuantFunctor: round(clip(v/s, -1, 1) * qmax) / qmax * s."""
    qmax = float(2 ** (bits - 1) - 1)
    s = max(float(scale), 1e-8)
    return (np.round(np.clip(v / s, -1.0, 1.0) * qmax) / qmax * s).astype(
        np.float32)


def _run_op(op_type, inputs, attrs, out_names, input_vars=(),
            extra_vars=()):
    """Append one raw quant op and run it, returning the fetches."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            blk = main.global_block
            feed = {}
            for name, val in input_vars:
                fluid.layers.data(name, shape=list(val.shape[1:]) or [1],
                                  dtype="float32")
                feed[name] = val
            for name, val in extra_vars:
                blk.create_var(name=name, shape=val.shape, dtype="float32",
                               persistable=True)
                feed[name] = val
            for name in out_names:
                blk.create_var(name=name, dtype="float32")
            blk.append_op(op_type, inputs=inputs,
                          outputs=dict(zip(("Out", "OutScale"),
                                           [[n] for n in out_names])),
                          attrs=attrs)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            return exe.run(main, feed=feed, fetch_list=out_names)


# ---------------------------------------------------------------------------
# forward parity vs the reference formulas
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bits", [8, 4])
def test_abs_max_forward_matches_reference(bits):
    v = (RNG.randn(4, 6) * 3).astype(np.float32)
    out, out_scale = _run_op(
        "fake_quantize_dequantize_abs_max",
        inputs={"X": ["x"]}, attrs={"bit_length": bits},
        out_names=["q", "s"], input_vars=[("x", v)])
    scale = np.abs(v).max()
    np.testing.assert_allclose(np.asarray(out_scale).reshape(-1),
                               [scale], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out), _ref_quant(v, scale, bits),
                               rtol=1e-6, atol=1e-7)


def test_abs_max_quantization_error_bounded_by_resolution():
    """|q - v| <= scale / qmax / 2 everywhere inside the clip range —
    the 8-bit resolution guarantee the QAT accuracy argument rests on."""
    v = (RNG.randn(32, 16)).astype(np.float32)
    (out, _s) = _run_op(
        "fake_quantize_dequantize_abs_max",
        inputs={"X": ["x"]}, attrs={"bit_length": 8},
        out_names=["q", "s"], input_vars=[("x", v)])
    scale = np.abs(v).max()
    err = np.abs(np.asarray(out) - v)
    assert err.max() <= scale / 127.0 / 2.0 + 1e-6


def test_moving_average_training_updates_the_scale():
    """Training mode: scale = rate * in_scale + (1 - rate) * batch_absmax,
    and the output quantizes against the UPDATED scale."""
    v = (RNG.randn(5, 7) * 2).astype(np.float32)
    in_scale = np.array([0.5], np.float32)
    rate = 0.9
    out, out_scale = _run_op(
        "fake_quantize_dequantize_moving_average_abs_max",
        inputs={"X": ["x"], "InScale": ["scale_in"]},
        attrs={"bit_length": 8, "moving_rate": rate, "is_test": False},
        out_names=["q", "s"], input_vars=[("x", v)],
        extra_vars=[("scale_in", in_scale)])
    expect_scale = rate * in_scale[0] + (1 - rate) * np.abs(v).max()
    np.testing.assert_allclose(np.asarray(out_scale).reshape(-1),
                               [expect_scale], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out),
                               _ref_quant(v, expect_scale),
                               rtol=1e-5, atol=1e-6)


def test_moving_average_test_mode_freezes_the_scale():
    """is_test: the batch abs-max is ignored — inference quantizes
    against the calibrated scale exactly (values beyond it saturate)."""
    v = (RNG.randn(5, 7) * 4).astype(np.float32)
    in_scale = np.array([1.25], np.float32)
    out, out_scale = _run_op(
        "fake_quantize_dequantize_moving_average_abs_max",
        inputs={"X": ["x"], "InScale": ["scale_in"]},
        attrs={"bit_length": 8, "moving_rate": 0.9, "is_test": True},
        out_names=["q", "s"], input_vars=[("x", v)],
        extra_vars=[("scale_in", in_scale)])
    np.testing.assert_allclose(np.asarray(out_scale).reshape(-1),
                               in_scale, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out),
                               _ref_quant(v, in_scale[0]),
                               rtol=1e-6, atol=1e-7)
    assert np.abs(np.asarray(out)).max() <= in_scale[0] + 1e-6


# ---------------------------------------------------------------------------
# STE backward through append_backward
# ---------------------------------------------------------------------------

def _ste_program(op_type, extra_inputs=None, attrs=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6], dtype="float32",
                              stop_gradient=False)
        blk = main.global_block
        q = blk.create_var(name="q", dtype="float32")
        s = blk.create_var(name="s", dtype="float32")
        inputs = {"X": ["x"]}
        for slot, (name, val) in (extra_inputs or {}).items():
            blk.create_var(name=name, shape=val.shape, dtype="float32",
                           persistable=True)
            inputs[slot] = [name]
        blk.append_op(op_type, inputs=inputs,
                      outputs={"Out": ["q"], "OutScale": ["s"]},
                      attrs=attrs or {})
        loss = fluid.layers.mean(fluid.layers.scale(q, scale=3.0))
        grads = append_backward(loss)
    return main, startup, x, loss, grads


@pytest.mark.parametrize("op_type,extra", [
    ("fake_quantize_dequantize_abs_max", None),
    ("fake_quantize_dequantize_moving_average_abs_max",
     {"InScale": ("scale_in", np.array([1.0], np.float32))}),
])
def test_straight_through_gradient_via_append_backward(op_type, extra):
    """The STE contract: d(loss)/dx passes through the staircase as
    identity — here d(mean(3 q))/dx = 3/n exactly, even though the true
    staircase derivative is 0 almost everywhere."""
    with un.guard():
        main, startup, x, loss, _grads = _ste_program(
            op_type, extra_inputs=extra)
    gname = f"{x.name}@GRAD"
    assert main.global_block.has_var(gname), (
        "append_backward must reach through the fake-quant op back to x")
    v = (RNG.randn(4, 6) * 2).astype(np.float32)
    feed = {"x": v}
    for _slot, (name, val) in (extra or {}).items():
        feed[name] = val
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed=feed, fetch_list=[gname])
    np.testing.assert_allclose(np.asarray(g),
                               np.full_like(v, 3.0 / v.size), rtol=1e-6)


def test_scale_output_carries_no_gradient():
    """OutScale is declared no_grad: the backward must not try to route a
    gradient into the scale computation."""
    with un.guard():
        main, _startup, x, _loss, _grads = _ste_program(
            "fake_quantize_dequantize_abs_max")
    assert not main.global_block.has_var("s@GRAD")
    assert main.global_block.has_var(f"{x.name}@GRAD")
