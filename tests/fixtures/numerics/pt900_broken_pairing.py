"""PT900 positive control: fake-quant output consumed off the GEMM path.

A hand-spliced ``fake_quantize_dequantize_abs_max`` feeds ``relu`` —
a consumer the int8 rewrite cannot reproduce (the dequantized values
would differ from the int8 kernel's). A second fake-quant output is never
consumed at all (dead quantization). Both shapes of broken pairing must
report PT900.
"""
import paddle_tpu as fluid


EXPECTED = "PT900"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        blk = main.global_block
        q = blk.create_var(name="x.quantized", shape=x.shape,
                           dtype="float32")
        s = blk.create_var(name="x.quant_scale", shape=(1,),
                           dtype="float32")
        blk.append_op("fake_quantize_dequantize_abs_max",
                      inputs={"X": [x.name]},
                      outputs={"Out": [q.name], "OutScale": [s.name]},
                      attrs={"bit_length": 8})
        out = fluid.layers.relu(q)          # off-path consumer -> PT900
        # dead fake-quant: output never consumed, never fetched -> PT900
        q2 = blk.create_var(name="x.quantized_dead", shape=x.shape,
                            dtype="float32")
        s2 = blk.create_var(name="x.quant_scale_dead", shape=(1,),
                            dtype="float32")
        blk.append_op("fake_quantize_dequantize_abs_max",
                      inputs={"X": [x.name]},
                      outputs={"Out": [q2.name], "OutScale": [s2.name]},
                      attrs={"bit_length": 8})
    return main, startup, [out.name]
