"""PT901 positive control: non-persistable moving-average scale state.

A properly QAT-rewritten training program (``quant_aware`` before
``minimize``, the documented order) whose moving-average activation
scale vars are then flipped to ``persistable=False`` — the running scale
would reset every step and the calibration never converges. The analysis
must report PT901 for each such scale.
"""
import paddle_tpu as fluid
from paddle_tpu.contrib.slim.quantization import quant_aware


EXPECTED = "PT901"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        p = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(p - y))
        quant_aware(main, startup)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    # break the state contract: moving-average scales must persist
    for v in main.global_block.vars.values():
        if ".quant_scale" in v.name and v.persistable:
            v.persistable = False
    return main, startup, [loss.name]
