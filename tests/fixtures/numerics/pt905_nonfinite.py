"""PT905 positive control: domain hazards on statically-proven intervals.

``log`` of an exactly-negative constant and a division whose denominator
interval provably contains 0 — both produce inf/nan with no guard in
sight. The analysis must report PT905. (The companion negative case — the
same ops behind ``clip``/``abs`` guards — lives in tests/test_numerics.py:
guards narrow the interval and must clear the finding.)
"""
import paddle_tpu as fluid


EXPECTED = "PT905"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant(shape=[4], dtype="float32",
                                       value=-1.0)
        bad_log = fluid.layers.log(c)           # log of [-1, -1] -> PT905
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        den = fluid.layers.tanh(x)              # [-1, 1] contains 0
        q = fluid.layers.elementwise_div(x, den)  # PT905
        out = fluid.layers.mean(q + bad_log)
    return main, startup, [out.name]
