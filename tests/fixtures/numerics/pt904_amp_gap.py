"""PT904 positive control: loss-scale coverage gap.

A training program where ``check_finite_and_unscale`` is spliced over
ONE parameter gradient while the others reach their SGD updates raw —
those updates apply gradients still multiplied by the loss-scale factor.
The analysis must report PT904 for every uncovered grad.
"""
import paddle_tpu as fluid


EXPECTED = "PT904"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        p = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square(p - y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        blk = main.global_block
        grads = sorted(n for n in blk.vars
                       if n.endswith("@GRAD") and ".w_" in n)
        scale = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                           value=128.0)
        found = blk.create_var(name="found_inf", shape=(1,), dtype="bool")
        # unscale covers only the first weight grad; the rest reach the
        # sgd ops raw -> PT904 per uncovered grad
        blk.append_op("check_finite_and_unscale",
                      inputs={"X": [grads[0]], "Scale": [scale.name]},
                      outputs={"Out": [grads[0]],
                               "FoundInfinite": [found.name]})
    return main, startup, [loss.name]
