"""PT902 positive control: cast whose proven interval overflows the
target dtype.

``fill_constant(1e6)`` has the exact interval [1e6, 1e6]; float16's
finite range tops out at 65504, so the cast is a statically-proven
overflow to inf. The analysis must report PT902.
"""
import paddle_tpu as fluid


EXPECTED = "PT902"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        c = fluid.layers.fill_constant(shape=[4], dtype="float32",
                                       value=1.0e6)
        h = fluid.layers.cast(c, "float16")     # 1e6 > 65504 -> PT902
        out = fluid.layers.cast(h, "float32")
    return main, startup, [out.name]
