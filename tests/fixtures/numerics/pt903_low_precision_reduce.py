"""PT903 positive control: reduction accumulated in storage precision.

A float16 tensor reduced by ``reduce_sum`` into a float16 output — every
partial sum rounds to float16 (vs the float32-accumulate idiom). The
analysis must report PT903.
"""
import paddle_tpu as fluid


EXPECTED = "PT903"


def build():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1024], dtype="float32")
        h = fluid.layers.cast(x, "float16")
        s = fluid.layers.reduce_sum(h)          # fp16 -> fp16 accumulate
        out = fluid.layers.cast(s, "float32")
    return main, startup, [out.name]
