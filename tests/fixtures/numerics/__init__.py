"""Broken-program fixtures for the numerics linter's negative control.

One module per PT90x code. Each defines ``EXPECTED`` (the code it must
trip) and ``build()`` returning ``(main_program, startup_program,
fetch_names)``. ``tools/lint_numerics.py --negative-control`` loads every
module here, runs ``analysis.numerics.analyze_numerics`` over the built
program and exits non-zero unless EVERY code fires — a control that
cannot trip a family proves that family's detector is broken, so a
missing code is exit 2, not a pass (same contract as the concurrency
linter's control over tests/fixtures/concurrency/).
"""
FIXTURE_MODULES = (
    "pt900_broken_pairing",
    "pt901_dead_scale",
    "pt902_overflow_cast",
    "pt903_low_precision_reduce",
    "pt904_amp_gap",
    "pt905_nonfinite",
)
