"""PT801 positive control: blocking calls under a held lock.

The exact shape of the PR-13 aot_cache regression: a compile path that
sleeps while holding the cache lock, serializing every other thread
behind a wait that has nothing to do with them. ``get`` blocks
directly; ``warm`` blocks transitively through the ``_backoff`` helper
— the linter must flag both (the transitive case is the one a lexical
grep misses).
"""
import threading
import time


class CompileCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}

    def get(self, key):
        with self._lock:
            if key not in self._cache:
                time.sleep(0.05)
                self._cache[key] = object()
            return self._cache[key]

    def warm(self, keys):
        with self._lock:
            for k in keys:
                if k not in self._cache:
                    self._backoff()
                    self._cache[k] = object()

    def _backoff(self):
        time.sleep(0.01)
