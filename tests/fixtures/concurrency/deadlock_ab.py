"""PT800 positive control: AB/BA lock-order cycle.

``submit`` acquires ``_a`` then ``_b``; ``drain`` acquires ``_b`` then
``_a``. Two threads running one each deadlock; the static lock-order
graph has the cycle a->b->a and the linter must report PT800.
"""
import threading


class Worker:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.jobs = []

    def submit(self, job):
        with self._a:
            with self._b:
                self.jobs.append(job)

    def drain(self):
        with self._b:
            with self._a:
                jobs, self.jobs = self.jobs, []
        return jobs
