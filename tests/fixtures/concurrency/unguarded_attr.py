"""PT802 positive control: cross-thread attribute with unguarded access.

``count`` is written by the worker thread (``_loop``) and read by the
caller side (``snapshot``), neither under ``_lock`` — a data race the
linter must report. ``__init__`` accesses do not count (construction
happens-before ``Thread.start``).
"""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self.count += 1

    def snapshot(self):
        return self.count
