"""Negative control: concurrency idioms the linter must NOT flag.

* consistent lock order (always ``_a`` before ``_b``) — no PT800;
* ``Condition.wait`` under the condition's own lock and ``Event.wait``
  with a timeout — neither is blocking-under-lock (PT801);
* cross-thread state accessed only under the lock, including through a
  ``*_locked`` helper only ever called with the lock held — no PT802.
"""
import threading


class Pipeline:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cond = threading.Condition(self._a)
        self._stop = threading.Event()
        self.pending = []
        self.done = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def submit(self, job):
        with self._a:
            self.pending.append(job)
            self._cond.notify()
            with self._b:     # consistent nesting order: always _a -> _b
                pass

    def _run(self):
        while not self._stop.wait(timeout=0.01):
            with self._a:
                while not self.pending:
                    self._cond.wait(timeout=0.1)
                self._drain_locked()
            with self._b:
                pass

    def _drain_locked(self):
        self.pending.clear()
        self.done += 1

    def stats(self):
        with self._a:
            return self.done
