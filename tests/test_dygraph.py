"""Dygraph (eager) mode — VERDICT r2 item 7 done-criterion: an MNIST MLP
trains eagerly to the same losses as the static-graph path (reference
imperative/tracer.h TraceOp + dygraph/layers.py Layer)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph


class MLP(dygraph.Layer):
    def __init__(self, hidden=32):
        super().__init__("mlp")
        self.fc1 = dygraph.FC(784, hidden, act="relu")
        self.fc2 = dygraph.FC(hidden, 10)

    def forward(self, x):
        return self.fc2(self.fc1(x))


def _static_reference(w1, b1, w2, b2, xb, yb, steps, lr):
    """The same model/updates on the static path, params force-set."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[784], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, w1.shape[1], act="relu", name="s1")
            logits = fluid.layers.fc(h, 10, name="s2")
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        # overwrite the random init with the dygraph model's params
        import jax.numpy as jnp

        for name, arr in [("s1.w_0", w1), ("s1.b_0", b1),
                          ("s2.w_0", w2), ("s2.b_0", b2)]:
            assert scope.find_var(name) is not None, list(scope.vars)
            scope.set_var(name, jnp.asarray(arr))
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_dygraph_mnist_matches_static():
    rng = np.random.RandomState(0)
    xb = rng.randn(32, 784).astype(np.float32)
    yb = rng.randint(0, 10, (32, 1)).astype(np.int64)
    steps, lr = 6, 0.5

    with dygraph.guard():
        dygraph.seed_parameters(7)
        model = MLP()
        w1, b1 = model.fc1.weight.numpy(), model.fc1.bias.numpy()
        w2, b2 = model.fc2.weight.numpy(), model.fc2.bias.numpy()
        opt = fluid.optimizer.SGD(learning_rate=lr)
        dy_losses = []
        x = dygraph.to_variable(xb)
        y = dygraph.to_variable(yb)
        for _ in range(steps):
            logits = model(x)
            _, ce = dygraph.ops.softmax_with_cross_entropy(logits, y)
            loss = dygraph.ops.mean(ce)
            dy_losses.append(float(loss.numpy()))
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()

    st_losses = _static_reference(w1, b1, w2, b2, xb, yb, steps, lr)
    np.testing.assert_allclose(dy_losses, st_losses, rtol=1e-4, atol=1e-6)
    assert dy_losses[-1] < dy_losses[0]


def test_dygraph_adam_trains():
    rng = np.random.RandomState(1)
    xb = rng.randn(16, 8).astype(np.float32)
    w_true = rng.randn(8, 1).astype(np.float32)
    yb = xb @ w_true
    with dygraph.guard():
        fc = dygraph.Linear(8, 1)
        opt = fluid.optimizer.Adam(learning_rate=0.05)
        first = last = None
        for _ in range(40):
            pred = fc(dygraph.to_variable(xb))
            loss = dygraph.ops.mean(
                dygraph.ops.square(pred - dygraph.to_variable(yb)))
            loss.backward()
            opt.minimize(loss, parameter_list=fc.parameters())
            fc.clear_gradients()
            last = float(loss.numpy())
            first = first if first is not None else last
    assert last < first * 0.05


def test_dygraph_layers_forward():
    """Conv2D/BatchNorm/Pool2D/LayerNorm/Embedding/Dropout eager shapes."""
    rng = np.random.RandomState(2)
    with dygraph.guard():
        img = dygraph.to_variable(rng.randn(2, 3, 8, 8).astype(np.float32))
        conv = dygraph.Conv2D(3, 6, 3, padding=1, act="relu")
        bn = dygraph.BatchNorm(6)
        pool = dygraph.Pool2D(2, "max", 2)
        out = pool(bn(conv(img)))
        assert out.shape == (2, 6, 4, 4)

        ln = dygraph.LayerNorm(16)
        z = ln(dygraph.to_variable(rng.randn(4, 16).astype(np.float32)))
        assert z.shape == (4, 16)
        np.testing.assert_allclose(z.numpy().mean(axis=-1), 0, atol=1e-5)

        emb = dygraph.Embedding([50, 12])
        e = emb(dygraph.to_variable(np.array([[1, 2], [3, 4]], np.int64)))
        assert e.shape == (2, 2, 12)

        drop = dygraph.Dropout(0.5)
        drop.eval()
        d = drop(z)
        # reference downgrade_in_infer: inference output is x * (1 - p)
        np.testing.assert_allclose(d.numpy(), z.numpy() * 0.5, rtol=1e-6)

        # BatchNorm running stats moved after a train-mode forward
        assert not np.allclose(bn._mean.numpy(), 0)


def test_dygraph_python_control_flow():
    """The dygraph point: data-dependent Python control flow just works."""
    with dygraph.guard():
        fc = dygraph.Linear(4, 4)
        x = dygraph.to_variable(np.ones((2, 4), np.float32))
        h = x
        steps = 0
        while float(dygraph.ops.mean(h).numpy()) < 5 and steps < 50:
            h = dygraph.ops.relu(fc(h)) + 1.0
            steps += 1
        assert steps > 0
        loss = dygraph.ops.mean(h)
        loss.backward()
        assert fc.weight.gradient() is not None


def test_dygraph_state_dict_roundtrip(tmp_path):
    with dygraph.guard():
        model = MLP(hidden=16)
        ref = model.fc1.weight.numpy().copy()
        dygraph.save_dygraph(model.state_dict(), str(tmp_path / "mlp"))

        model2 = MLP(hidden=16)
        assert not np.allclose(model2.fc1.weight.numpy(), ref)
        state, _ = dygraph.load_dygraph(str(tmp_path / "mlp"))
        model2.set_dict(state)
        np.testing.assert_array_equal(model2.fc1.weight.numpy(), ref)

        with pytest.raises(ValueError, match="shape"):
            bad = dict(state)
            bad["fc1.weight"] = np.zeros((2, 2), np.float32)
            model2.set_dict(bad)


def test_dygraph_grad_accumulation_and_clear():
    with dygraph.guard():
        fc = dygraph.Linear(3, 1)
        x = dygraph.to_variable(np.ones((2, 3), np.float32))
        loss = dygraph.ops.mean(fc(x))
        loss.backward()
        g1 = fc.weight.gradient().copy()
        loss2 = dygraph.ops.mean(fc(x))
        loss2.backward()
        np.testing.assert_allclose(fc.weight.gradient(), 2 * g1, rtol=1e-6)
        fc.clear_gradients()
        assert fc.weight.gradient() is None


def test_dygraph_lamb_is_real_lamb():
    """Regression (advisor r3): LambOptimizer's eager path must apply the
    trust-ratio-scaled lamb rule (via the 'lamb' registry lowering), not a
    plain Adam update inherited from AdamOptimizer."""
    from paddle_tpu.optimizer import LambOptimizer

    with dygraph.guard():
        fc = dygraph.nn.FC(4, 4)
        x = dygraph.to_variable(np.ones((2, 4), np.float32))
        opt = LambOptimizer(learning_rate=0.1, lamb_weight_decay=0.01)
        loss = dygraph.ops.reduce_mean(fc(x))
        loss.backward()
        params = list(fc.parameters())
        before = {p.name: np.array(p.value) for p in params}
        grads = {p.name: (np.array(p._grad) if p._grad is not None else None)
                 for p in params}
        opt.minimize(loss, parameter_list=params)
        for p in params:
            g, w = grads[p.name], before[p.name]
            if g is None:
                continue
            b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
            m1h = ((1 - b1) * g) / (1 - b1)
            m2h = ((1 - b2) * g * g) / (1 - b2)
            r = m1h / (np.sqrt(m2h) + eps) + wd * w
            wn = np.sqrt((w ** 2).sum())
            rn = np.sqrt((r ** 2).sum())
            ratio = wn / rn if (wn > 0 and rn > 0) else 1.0
            np.testing.assert_allclose(np.array(p.value),
                                       w - 0.1 * ratio * r, atol=1e-5)
