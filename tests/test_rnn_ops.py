"""RNN ops (lstm/gru/gru_unit/cudnn_lstm) + warpctc against numpy oracles
implementing the reference kernels' math (lstm_kernel.h / gru_kernel.h /
the CTC forward algorithm)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


def _sig(v):
    return 1.0 / (1.0 + np.exp(-v))


def _np_lstm(xg, w, bias, lens, peep=True):
    """Gate layout [c~, i, f, o]; returns padded hidden + last cell."""
    B, T, H4 = xg.shape
    H = H4 // 4
    b = bias.reshape(-1)
    gate_b = b[:4 * H]
    ckI = b[4 * H:5 * H] if peep else 0.0
    ckF = b[5 * H:6 * H] if peep else 0.0
    ckO = b[6 * H:7 * H] if peep else 0.0
    hid = np.zeros((B, T, H), np.float32)
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    cT = np.zeros((B, H), np.float32)
    for bi in range(B):
        h_, c_ = h[bi], c[bi]
        for t in range(int(lens[bi])):
            g = xg[bi, t] + h_ @ w + gate_b
            cand = np.tanh(g[:H])
            i = _sig(g[H:2 * H] + c_ * ckI)
            f = _sig(g[2 * H:3 * H] + c_ * ckF)
            nc = cand * i + c_ * f
            o = _sig(g[3 * H:] + nc * ckO)
            h_ = o * np.tanh(nc)
            c_ = nc
            hid[bi, t] = h_
        cT[bi] = c_
    return hid, cT


def _np_gru(xg, w, bias, lens):
    B, T, H3 = xg.shape
    H = H3 // 3
    b = bias.reshape(-1)
    hid = np.zeros((B, T, H), np.float32)
    for bi in range(B):
        h = np.zeros(H, np.float32)
        for t in range(int(lens[bi])):
            xt = xg[bi, t] + b
            ur = xt[:2 * H] + h @ w[:, :2 * H]
            u, r = _sig(ur[:H]), _sig(ur[H:])
            cand = np.tanh(xt[2 * H:] + (r * h) @ w[:, 2 * H:])
            h = h - u * h + u * cand
            hid[bi, t] = h
    return hid


RNG = np.random.RandomState(3)
LENS = np.array([4, 2, 6], np.int32)
T, B, H = 6, 3, 5


class TestLSTM(OpTest):
    def setup(self):
        xg = (RNG.randn(B, T, 4 * H) * 0.5).astype(np.float32)
        w = (RNG.randn(H, 4 * H) * 0.3).astype(np.float32)
        bias = (RNG.randn(1, 7 * H) * 0.1).astype(np.float32)
        hid, cT = _np_lstm(xg, w, bias, LENS)
        self.op_type = "lstm"
        self.inputs = {"Input": xg, "Weight": w, "Bias": bias,
                       "SeqLen": LENS}
        self.attrs = {"use_peepholes": True}
        self.outputs = {"Hidden": hid, "Cell": cT}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["Input", "Weight"], "Hidden",
                        max_relative_error=0.02)


class TestLSTMReverse(OpTest):
    def setup(self):
        xg = (RNG.randn(B, T, 4 * H) * 0.5).astype(np.float32)
        w = (RNG.randn(H, 4 * H) * 0.3).astype(np.float32)
        bias = (RNG.randn(1, 4 * H) * 0.1).astype(np.float32)
        # oracle: reverse valid prefixes, run forward, reverse back
        xr = xg.copy()
        for bi in range(B):
            L = int(LENS[bi])
            xr[bi, :L] = xg[bi, :L][::-1]
        hid_r, _ = _np_lstm(xr, w, bias, LENS, peep=False)
        hid = hid_r.copy()
        for bi in range(B):
            L = int(LENS[bi])
            hid[bi, :L] = hid_r[bi, :L][::-1]
        self.op_type = "lstm"
        self.inputs = {"Input": xg, "Weight": w, "Bias": bias,
                       "SeqLen": LENS}
        self.attrs = {"use_peepholes": False, "is_reverse": True}
        self.outputs = {"Hidden": hid}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5, no_check=("Cell",))


class TestGRU(OpTest):
    def setup(self):
        xg = (RNG.randn(B, T, 3 * H) * 0.5).astype(np.float32)
        w = (RNG.randn(H, 3 * H) * 0.3).astype(np.float32)
        bias = (RNG.randn(1, 3 * H) * 0.1).astype(np.float32)
        hid = _np_gru(xg, w, bias, LENS)
        self.op_type = "gru"
        self.inputs = {"Input": xg, "Weight": w, "Bias": bias,
                       "SeqLen": LENS}
        self.outputs = {"Hidden": hid}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["Input", "Weight"], "Hidden",
                        max_relative_error=0.02)


class TestGRUUnit(OpTest):
    def setup(self):
        xt = (RNG.randn(B, 3 * H) * 0.5).astype(np.float32)
        hp = (RNG.randn(B, H) * 0.5).astype(np.float32)
        w = (RNG.randn(H, 3 * H) * 0.3).astype(np.float32)
        ur = xt[:, :2 * H] + hp @ w[:, :2 * H]
        u, r = _sig(ur[:, :H]), _sig(ur[:, H:])
        cand = np.tanh(xt[:, 2 * H:] + (r * hp) @ w[:, 2 * H:])
        h = hp - u * hp + u * cand
        self.op_type = "gru_unit"
        self.inputs = {"Input": xt, "HiddenPrev": hp, "Weight": w}
        self.outputs = {"Hidden": h,
                        "Gate": np.concatenate([u, r, cand], 1),
                        "ResetHiddenPrev": r * hp}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)
        self.check_grad(["Input", "HiddenPrev", "Weight"], "Hidden",
                        max_relative_error=0.02)


def _np_ctc_loss(logits, labels, tlen, llen, blank=0):
    """Textbook CTC forward algorithm in probability space."""
    def softmax(z):
        e = np.exp(z - z.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    out = []
    for b in range(logits.shape[0]):
        p = softmax(logits[b, :int(tlen[b])])
        lab = labels[b, :int(llen[b])]
        ext = [blank]
        for l in lab:
            ext += [int(l), blank]
        S = len(ext)
        a = np.zeros((int(tlen[b]), S))
        a[0, 0] = p[0, blank]
        if S > 1:
            a[0, 1] = p[0, ext[1]]
        for t in range(1, int(tlen[b])):
            for s in range(S):
                tot = a[t - 1, s]
                if s >= 1:
                    tot += a[t - 1, s - 1]
                if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                    tot += a[t - 1, s - 2]
                a[t, s] = tot * p[t, ext[s]]
        ll = a[-1, S - 1] + (a[-1, S - 2] if S > 1 else 0.0)
        out.append(-np.log(max(ll, 1e-300)))
    return np.array(out, np.float32).reshape(-1, 1)


class TestWarpCTC(OpTest):
    def setup(self):
        Bc, Tc, C, L = 3, 8, 6, 3
        logits = (RNG.randn(Bc, Tc, C) * 2).astype(np.float32)
        labels = RNG.randint(1, C, (Bc, L)).astype(np.int64)
        tlen = np.array([8, 6, 7], np.int32)
        llen = np.array([3, 1, 2], np.int32)
        self.op_type = "warpctc"
        self.inputs = {"Logits": logits, "Label": labels,
                       "LogitsLength": tlen, "LabelLength": llen}
        self.attrs = {"blank": 0}
        self.outputs = {"Loss": _np_ctc_loss(logits, labels, tlen, llen)}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-4)
        # fp32 central differences on a ~10-valued loss have ~1e-4 noise;
        # CTC grads here are ~1e-3, so use a larger delta + loose rel bound
        self.check_grad(["Logits"], "Loss", delta=0.02,
                        max_relative_error=0.06)


def test_cudnn_lstm_matches_stacked_reference():
    """2-layer cudnn_lstm == manually stacking the numpy LSTM oracle with
    the flat-weight packing."""
    import jax

    D, Hs, L = 4, 5, 2
    lens = np.array([5, 3], np.int32)
    xv = (RNG.randn(2, 6, D) * 0.5).astype(np.float32)
    pieces, np_weights = [], []
    for layer in range(L):
        ind = D if layer == 0 else Hs
        w_ih = (RNG.randn(4 * Hs, ind) * 0.3).astype(np.float32)
        w_hh = (RNG.randn(4 * Hs, Hs) * 0.3).astype(np.float32)
        b_ih = (RNG.randn(4 * Hs) * 0.1).astype(np.float32)
        b_hh = (RNG.randn(4 * Hs) * 0.1).astype(np.float32)
        pieces += [w_ih.ravel(), w_hh.ravel(), b_ih, b_hh]
        np_weights.append((w_ih, w_hh, b_ih + b_hh))
    wflat = np.concatenate(pieces)

    # numpy stacked reference: gates = x@W_ih^T + b; recurrent h@W_hh^T
    seq = xv
    for w_ih, w_hh, b in np_weights:
        out = np.zeros((2, 6, Hs), np.float32)
        for bi in range(2):
            h = np.zeros(Hs, np.float32)
            c = np.zeros(Hs, np.float32)
            for t in range(int(lens[bi])):
                g = seq[bi, t] @ w_ih.T + h @ w_hh.T + b
                cand = np.tanh(g[:Hs])
                i = _sig(g[Hs:2 * Hs])
                f = _sig(g[2 * Hs:3 * Hs])
                o = _sig(g[3 * Hs:])
                c = cand * i + c * f
                h = o * np.tanh(c)
                out[bi, t] = h
        seq = out

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block
        mk = lambda n, a: blk.create_var(name=n, shape=a.shape,
                                         dtype=str(a.dtype), is_data=True)
        vx, vw = mk("x", xv), mk("w", wflat)
        vl = mk("lens", lens)
        o1 = blk.create_var(name="o1", dtype="float32")
        o2 = blk.create_var(name="o2", dtype="float32")
        o3 = blk.create_var(name="o3", dtype="float32")
        blk.append_op("cudnn_lstm",
                      inputs={"Input": "x", "W": "w", "SeqLen": "lens"},
                      outputs={"Out": "o1", "LastH": "o2", "LastC": "o3"},
                      attrs={"hidden_size": Hs, "num_layers": L})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": xv, "w": wflat, "lens": lens},
                         fetch_list=["o1"])
    np.testing.assert_allclose(got, seq, rtol=1e-4, atol=1e-5)


def test_dynamic_rnn_varlen_training():
    """DynamicRNN (reference control_flow.py) over genuinely variable-length
    batches: trains, and the loss is invariant to the padding width."""
    import paddle_tpu.unique_name as un

    def build():
        with un.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[6], dtype="float32",
                                      lod_level=1)
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                drnn = fluid.layers.DynamicRNN()
                with drnn.block():
                    w = drnn.step_input(x)
                    prev = drnn.memory(shape=[8])
                    h = fluid.layers.fc(
                        fluid.layers.concat([w, prev], axis=1), 8,
                        act="tanh", name="cell",
                        param_attr=fluid.ParamAttr(name="cell_w"),
                        bias_attr=False)
                    drnn.update_memory(prev, h)
                    drnn.output(h)
                hidden = drnn()                       # [B, T, 8] masked
                last = fluid.layers.sequence_pool(hidden, "last")
                pred = fluid.layers.fc(last, 1, name="out",
                                       param_attr=fluid.ParamAttr(name="o_w"),
                                       bias_attr=False)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.05).minimize(loss)
        main.random_seed = 31
        return main, startup, loss, (x, y)

    rng = np.random.RandomState(4)
    samples = []
    for _ in range(8):
        L = int(rng.randint(2, 7))
        seq = rng.randn(L, 6).astype(np.float32)
        samples.append((seq, np.array([seq.sum() * 0.1], np.float32)))

    def run(buckets, steps):
        main, startup, loss, feed_vars = build()
        feeder = fluid.DataFeeder(feed_list=list(feed_vars), program=main,
                                  seq_buckets=buckets)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(steps):
                (lv,) = exe.run(main, feed=feeder.feed(samples),
                                fetch_list=[loss.name])
                out.append(float(np.asarray(lv).reshape(-1)[0]))
        return out

    a = run((8,), 10)
    b = run((16,), 10)
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)  # pad-invariant
    assert a[-1] < a[0] * 0.8
