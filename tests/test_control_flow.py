"""Control flow: While -> lax.while_loop, conditional_block -> lax.cond,
StaticRNN -> lax.scan, tensor arrays, beam search (VERDICT round-2 item #2)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(main, startup, feed=None, fetch=None, scope=None):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = scope or fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        return exe.run(main, feed=feed or {}, fetch_list=fetch or [])


def test_while_sum():
    """sum(0..9) computed with a While loop."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        acc = layers.fill_constant([1], "float32", 0.0)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            fi = layers.cast(i, "float32")
            layers.assign(layers.elementwise_add(acc, fi), acc)
            layers.increment(i, value=1)
            layers.assign(layers.less_than(i, n), cond)
        out = layers.elementwise_add(acc, layers.fill_constant(
            [1], "float32", 0.0))
    (res,) = _run(main, startup, fetch=[out])
    assert float(res[0]) == 45.0


def test_while_tensor_array():
    """Collect i^2 into a tensor array inside While, stack after the loop."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 5)
        arr = layers.create_array("float32")
        zero = layers.fill_constant([1], "float32", 0.0)
        layers.array_write(zero, i, arr)  # seed entry fixes element shape
        cond = layers.less_than(i, n)
        w = layers.While(cond, max_len=8)
        with w.block():
            fi = layers.cast(i, "float32")
            sq = layers.elementwise_mul(fi, fi)
            layers.array_write(sq, i, arr)
            layers.increment(i, value=1)
            layers.assign(layers.less_than(i, n), cond)
        stacked = layers.tensor_array_to_tensor(arr)
        length = layers.array_length(arr)
    got, ln = _run(main, startup, fetch=[stacked, length])
    np.testing.assert_allclose(got[:5, 0], [0.0, 1.0, 4.0, 9.0, 16.0])
    assert int(ln[0]) == 5


def test_cond_branches():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32")
        thr = layers.fill_constant([1], "float32", 0.0)
        pred = layers.greater_than(
            layers.reduce_sum(x), layers.reduce_sum(thr))
        out = layers.cond(pred,
                          lambda: layers.scale(x, scale=2.0),
                          lambda: layers.scale(x, scale=-1.0))
    (pos,) = _run(main, startup, feed={"x": np.array([[3.0]], np.float32)},
                  fetch=[out])
    (neg,) = _run(main, startup, feed={"x": np.array([[-3.0]], np.float32)},
                  fetch=[out])
    assert float(pos[0][0]) == 6.0
    assert float(neg[0][0]) == 3.0


def test_switch_piecewise():
    """Switch as used by piecewise LR decay."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        step = fluid.layers.data("step", shape=[1], dtype="float32")
        lr = layers.fill_constant([1], "float32", 0.0)
        b1 = layers.fill_constant([1], "float32", 10.0)
        b2 = layers.fill_constant([1], "float32", 20.0)
        s_scalar = layers.reduce_sum(step)
        with layers.Switch() as sw:
            with sw.case(layers.less_than(s_scalar, layers.reduce_sum(b1))):
                layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
            with sw.case(layers.less_than(s_scalar, layers.reduce_sum(b2))):
                layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 0.001), lr)
    for step_val, want in ((5.0, 0.1), (15.0, 0.01), (25.0, 0.001)):
        (got,) = _run(main, startup,
                      feed={"step": np.array([step_val], np.float32)},
                      fetch=[lr])
        assert abs(float(got[0]) - want) < 1e-7, (step_val, got)


def test_static_rnn_forward_matches_numpy():
    """h_t = tanh(x_t W + h_{t-1} U): StaticRNN vs explicit numpy loop."""
    seq, batch, din, dh = 4, 2, 3, 5
    rng = np.random.RandomState(0)
    xv = rng.randn(seq, batch, din).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[seq, batch, din], dtype="float32",
                              append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h_pre = rnn.memory(shape=[dh], batch_ref=x, init_value=0.0)
            xw = layers.fc(xt, dh, bias_attr=False, name="rnn_xw")
            hu = layers.fc(h_pre, dh, bias_attr=False, name="rnn_hu")
            h = layers.tanh(layers.elementwise_add(xw, hu))
            rnn.update_memory(h_pre, h)
            rnn.step_output(h)
        out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        W = scope.numpy("rnn_xw.w_0")
        U = scope.numpy("rnn_hu.w_0")
    h = np.zeros((batch, dh), np.float32)
    want = []
    for t in range(seq):
        h = np.tanh(xv[t] @ W + h @ U)
        want.append(h)
    np.testing.assert_allclose(got, np.stack(want), rtol=1e-5, atol=1e-5)


def test_static_rnn_trains():
    """Grads flow through the scan: RNN regression loss decreases."""
    seq, batch, din, dh = 6, 8, 4, 8
    rng = np.random.RandomState(1)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[seq, batch, din], dtype="float32",
                              append_batch_size=False)
        y = fluid.layers.data("y", shape=[batch, 1], dtype="float32",
                              append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h_pre = rnn.memory(shape=[dh], batch_ref=x, init_value=0.0)
            h = layers.tanh(layers.fc(layers.concat([xt, h_pre], axis=1),
                                      dh, bias_attr=False, name="cell"))
            rnn.update_memory(h_pre, h)
            rnn.step_output(h)
        hs = rnn()                      # [seq, batch, dh]
        last = layers.slice(hs, axes=[0], starts=[seq - 1], ends=[seq])
        last = layers.reshape(last, [batch, dh])
        pred = layers.fc(last, 1, name="head")
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(1e-2).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xv = rng.randn(seq, batch, din).astype(np.float32)
    yv = np.sum(xv[-1], axis=1, keepdims=True).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": xv, "y": yv},
                                fetch_list=[loss])[0]) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_beam_search_step():
    """2 beams, known scores: top-2 of accumulated candidates."""
    main, startup = fluid.Program(), fluid.Program()
    blk_scores = np.array([[0.6, 0.5, 0.1],     # beam 0 candidates
                           [0.9, 0.3, 0.2]],    # beam 1 candidates
                          np.float32)
    with fluid.program_guard(main, startup):
        pre_ids = fluid.layers.data("pre_ids", shape=[2, 1], dtype="int64",
                                    append_batch_size=False)
        pre_sc = fluid.layers.data("pre_sc", shape=[2, 1], dtype="float32",
                                   append_batch_size=False)
        sc = fluid.layers.data("sc", shape=[2, 3], dtype="float32",
                               append_batch_size=False)
        blk = main.global_block
        sel_ids = blk.create_var(name="sel_ids", shape=(2, 1), dtype="int64")
        sel_sc = blk.create_var(name="sel_sc", shape=(2, 1), dtype="float32")
        par = blk.create_var(name="par", shape=(2,), dtype="int64")
        blk.append_op("beam_search",
                      inputs={"pre_ids": pre_ids, "pre_scores": pre_sc,
                              "scores": sc},
                      outputs={"selected_ids": sel_ids,
                               "selected_scores": sel_sc,
                               "parent_idx": par},
                      attrs={"beam_size": 2, "end_id": -1})
    ids, scores, parents = _run(
        main, startup,
        feed={"pre_ids": np.array([[5], [7]], np.int64),
              "pre_sc": np.array([[0.0], [0.0]], np.float32),
              "sc": blk_scores},
        fetch=["sel_ids", "sel_sc", "par"])
    # best two: beam1/id0 (0.9), beam0/id0 (0.6)
    np.testing.assert_array_equal(ids.reshape(-1), [0, 0])
    np.testing.assert_allclose(scores.reshape(-1), [0.9, 0.6])
    np.testing.assert_array_equal(parents, [1, 0])


def test_beam_search_decode_backtrack():
    """Two steps, parents chain: decode returns chronological token rows."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        arr_ids = layers.create_array("int64")
        arr_sc = layers.create_array("float32")
        arr_par = layers.create_array("int64")
        i0 = layers.fill_constant([1], "int64", 0)
        i1 = layers.fill_constant([1], "int64", 1)
        # step 0: beams pick tokens [11, 22]; parents [0, 1]
        layers.array_write(layers.assign(np.array([[11], [22]], np.int64)),
                           i0, arr_ids)
        layers.array_write(layers.assign(
            np.array([[0.1], [0.2]], np.float32)), i0, arr_sc)
        layers.array_write(layers.assign(np.array([0, 1], np.int64)),
                           i0, arr_par)
        # step 1: both surviving beams descend from beam 1
        layers.array_write(layers.assign(np.array([[33], [44]], np.int64)),
                           i1, arr_ids)
        layers.array_write(layers.assign(
            np.array([[0.3], [0.4]], np.float32)), i1, arr_sc)
        layers.array_write(layers.assign(np.array([1, 1], np.int64)),
                           i1, arr_par)
        blk = main.global_block
        s_ids = blk.create_var(name="s_ids", shape=(2, 2), dtype="int64")
        s_sc = blk.create_var(name="s_sc", shape=(2, 2), dtype="float32")
        blk.append_op("beam_search_decode",
                      inputs={"Ids": arr_ids, "Scores": arr_sc,
                              "ParentIdx": arr_par},
                      outputs={"SentenceIds": s_ids, "SentenceScores": s_sc},
                      attrs={"beam_size": 2, "end_id": 0})
    ids, sc = _run(main, startup, fetch=["s_ids", "s_sc"])
    # beam 0 final: token 33 at t1, parent 1 -> token 22 at t0
    np.testing.assert_array_equal(ids[:, 0], [22, 33])
    # beam 1 final: token 44 at t1, parent 1 -> token 22 at t0
    np.testing.assert_array_equal(ids[:, 1], [22, 44])


def test_cond_carries_side_effects():
    """Round-2 advisor: assigns to outer vars inside a cond branch must
    survive lowering even when the branch returns nothing."""
    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        acc = layers.create_tensor("float32", persistable=True)
        layers.assign(np.zeros(4, np.float32), acc)
        pred = layers.less_than(layers.reduce_sum(x),
                                layers.fill_constant([1], "float32", 0.0))

        def neg_branch():
            layers.assign(x * 2.0, acc)

        def pos_branch():
            layers.assign(x * 3.0, acc)

        res = layers.cond(pred, neg_branch, pos_branch)
        assert res is None
        out = acc + 1.0

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        xv = np.array([1.0, 2.0, 3.0, 4.0], np.float32)  # sum > 0
        r = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        np.testing.assert_allclose(r, xv * 3.0 + 1.0, rtol=1e-6)
        xn = -xv
        r = exe.run(main, feed={"x": xn}, fetch_list=[out])[0]
        np.testing.assert_allclose(r, xn * 2.0 + 1.0, rtol=1e-6)


def test_conditional_block_shape_mismatch_clear_error():
    """Round-2 advisor: reshaping an outer var inside a branch must raise a
    clear error naming the variable, not an opaque lax.cond structure error."""
    import pytest

    import paddle_tpu.layers as layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        y = layers.create_tensor("float32", persistable=True)
        layers.assign(np.zeros(4, np.float32), y)
        pred = layers.less_than(layers.reduce_sum(x),
                                layers.fill_constant([1], "float32", 0.0))

        def bad_branch():
            layers.assign(layers.reshape(x, [2, 2]), y)

        layers.cond(pred, bad_branch, None)
        out = layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(Exception, match="conditional_block output"):
            exe.run(main, feed={"x": np.ones(4, np.float32)},
                    fetch_list=[out])
