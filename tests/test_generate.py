"""Generative inference end-to-end: GPT decoder, paged KV cache,
prefill/decode serving (ISSUE 11).

Layers under test:
* kernels — decode flash attention vs. the reference softmax oracle
  across positions/pages, paged KV append at page boundaries, shape
  classification;
* ops/models — sampling determinism, prefill->decode logits continuity
  (decoding token t+1 from the cache equals the full-sequence forward),
  donated-KV proof through ``run_chained``'s scan + PT71x cleanliness;
* serving — streaming futures (partial results vs. exactly-one terminal
  outcome), mid-stream deadline expiry, the bucketed-recompile guard, and
  chaos (a killed in-flight batch settles every affected stream typed).
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, serving
from paddle_tpu.core.types import np_dtype
from paddle_tpu.kernels import (classify_shapes, decode_attention_reference,
                                flash_attention_decode, paged_kv_append,
                                supports_shapes)
from paddle_tpu.models.gpt import (GptConfig, build_gpt_decode,
                                   build_gpt_generative)
from paddle_tpu.resilience import fault_plan_guard

RNG = np.random.RandomState(11)


# ---------------------------------------------------------------------------
# kernel layer
# ---------------------------------------------------------------------------

def test_classify_shapes_decode_and_prefill():
    kind, why = classify_shapes(1, 32, block_k=8)
    assert kind == "decode" and "page" in why
    assert classify_shapes(256, 256)[0] == "prefill"
    # unsupported decode tiling refuses with a clear message, never a
    # silent dense fallback
    kind, why = classify_shapes(1, 33, block_k=8)
    assert kind == "unsupported"
    assert "page" in why and "33" in why
    kind, why = classify_shapes(100, 256, block_q=64)
    assert kind == "unsupported" and "divide" in why
    assert supports_shapes(1, 32) and not supports_shapes(1, 33, block_k=8)
    assert supports_shapes(128, 256) \
        and not supports_shapes(100, 256, block_q=64)


def test_route_always_refuses_unsupported_decode_shape():
    from paddle_tpu.ops.generation import _route_decode

    fluid.set_flags({"FLAGS_use_flash_attention": "always"})
    try:
        with pytest.raises(ValueError, match="no kernel tiling"):
            _route_decode(33, 8)
        assert _route_decode(32, 8) in ("pallas", "pallas-interpret")
    finally:
        fluid.set_flags({"FLAGS_use_flash_attention": "auto"})


@pytest.mark.parametrize("lengths", [(1, 5, 8), (8, 9, 16), (24, 31, 32)])
def test_decode_kernel_matches_reference_across_positions(lengths):
    """Bit-level agreement sweep: early, page-boundary and cache-full
    positions, q_len=1 against a block-tiled cache with a length mask."""
    B, H, S, D, P = 3, 2, 32, 64, 8
    BH = B * H
    q = jnp.asarray(RNG.randn(BH, 1, D).astype(np.float32))
    k = jnp.asarray(RNG.randn(BH, S, D).astype(np.float32))
    v = jnp.asarray(RNG.randn(BH, S, D).astype(np.float32))
    lens = np.asarray(lengths, np.int32)
    o = flash_attention_decode(q, k, v, lens, num_heads=H, page_size=P,
                               interpret=True)
    o_ref = decode_attention_reference(
        q, k, v, jnp.asarray(np.repeat(lens, H)), D ** -0.5)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               atol=2e-5, rtol=1e-4)


def test_decode_kernel_refuses_bad_shapes():
    q = jnp.zeros((2, 1, 16), np.float32)
    with pytest.raises(ValueError, match="whole pages"):
        flash_attention_decode(q, jnp.zeros((2, 33, 16)),
                               jnp.zeros((2, 33, 16)), np.array([1, 1]),
                               num_heads=1, page_size=8, interpret=True)
    # q_len 2..8 is the legal chunk range since ISSUE 20; past one
    # sublane tile the kernel refuses (the op routes to the primitive)
    with pytest.raises(ValueError, match="q_len<=8"):
        flash_attention_decode(jnp.zeros((2, 9, 16)),
                               jnp.zeros((2, 32, 16)),
                               jnp.zeros((2, 32, 16)), np.array([1, 1]),
                               num_heads=1, page_size=8, interpret=True)


def test_paged_kv_append_at_page_boundaries():
    """Single-row appends at positions straddling a page edge, bulk
    (prompt) appends, and the saturation clamp on the last row."""
    B, H, S, D, P = 3, 2, 32, 4, 8
    cache = jnp.asarray(RNG.randn(B, H, S, D).astype(np.float32))
    new = jnp.asarray(RNG.randn(B, H, 1, D).astype(np.float32))
    # last row of page 0, first row of page 1, last row of the cache
    pos = np.array([7, 8, 31], np.int32)
    out = np.asarray(paged_kv_append(cache, new, jnp.asarray(pos)))
    base = np.asarray(cache)
    for b in range(B):
        np.testing.assert_array_equal(out[b, :, pos[b]],
                                      np.asarray(new)[b, :, 0])
        untouched = [s for s in range(S) if s != pos[b]]
        np.testing.assert_array_equal(out[b, :, untouched],
                                      base[b, :, untouched])
    # bulk write of a whole page at position 0 (the prefill path)
    bulk = jnp.asarray(RNG.randn(B, H, P, D).astype(np.float32))
    out2 = np.asarray(paged_kv_append(cache, bulk,
                                      jnp.zeros((B,), jnp.int32)))
    np.testing.assert_array_equal(out2[:, :, :P], np.asarray(bulk))
    np.testing.assert_array_equal(out2[:, :, P:], base[:, :, P:])
    # out-of-range start clamps onto the final row (retired-slot shape)
    out3 = np.asarray(paged_kv_append(cache, new,
                                      jnp.full((B,), S + 5, jnp.int32)))
    for b in range(B):
        np.testing.assert_array_equal(out3[b, :, S - 1],
                                      np.asarray(new)[b, :, 0])


def test_kv_cache_append_op_slot_mask():
    """The op face: a slot-masked append touches only masked sequences'
    rows (the continuous-batching refill invariant)."""
    from paddle_tpu.core.registry import get_op_def
    from paddle_tpu.lowering import LowerCtx

    B, H, S, D = 2, 1, 16, 4
    cache = jnp.asarray(RNG.randn(B, H, S, D).astype(np.float32))
    new = jnp.asarray(RNG.randn(B, H, 4, D).astype(np.float32))
    ins = {"Cache": [cache], "New": [new],
           "Positions": [jnp.zeros((B, 1), jnp.int32)],
           "SlotMask": [jnp.asarray([[1.0], [0.0]], jnp.float32)]}
    out = get_op_def("kv_cache_append").lower(LowerCtx(), ins, {})["Out"][0]
    out = np.asarray(out)
    np.testing.assert_array_equal(out[0, :, :4], np.asarray(new)[0])
    np.testing.assert_array_equal(out[1], np.asarray(cache)[1])


# ---------------------------------------------------------------------------
# model layer
# ---------------------------------------------------------------------------

def _plant_state(net, scope):
    for name, (shape, dt) in net["state_vars"].items():
        scope.set_var(name, np.zeros(shape, np_dtype(dt)))


def _build_net(**kw):
    with un.guard():
        return build_gpt_generative(GptConfig.tiny(), **kw)


@pytest.fixture(scope="module")
def gpt_net():
    """Shared tiny GPT (2 slots, 32-token KV in 8-token pages, one 16
    prompt bucket) with all-position logits for the continuity tests."""
    return _build_net(batch_slots=2, max_seq=32, page_size=8,
                      prompt_buckets=(16,), fetch_logits=True)


@pytest.fixture()
def gpt_session(gpt_net):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(gpt_net["startup"], scope=scope)
    _plant_state(gpt_net, scope)
    return exe, scope


def _prefill_feed(net, bucket, prompts, slot_mask=None):
    B = net["batch_slots"]
    S = bucket
    ids = np.zeros((B, S), np.int64)
    mask = np.zeros((B, S), np.float32)
    plen = np.ones((B, 1), np.int64)
    smask = np.zeros((B, 1), np.float32)
    for b, p in enumerate(prompts):
        if p is None:
            continue
        ids[b, :len(p)] = p
        mask[b, :len(p)] = 1.0
        plen[b, 0] = len(p)
        smask[b, 0] = 1.0
    if slot_mask is not None:
        smask = slot_mask
    return {"prompt_ids": ids, "prompt_mask": mask, "prompt_len": plen,
            "slot_mask": smask,
            "prompt_pos": np.tile(np.arange(S, dtype=np.int64), (B, 1))}


def test_prefill_decode_logits_continuity(gpt_net, gpt_session):
    """Decoding token t+1 from the KV cache must equal the full-sequence
    forward at the same position (teacher-forced) — the cache IS the
    prefix computation."""
    exe, scope = gpt_session
    pf = gpt_net["prefill"][16]
    dec = gpt_net["decode"]
    plen = np.array([5, 3])
    prompts = [RNG.randint(1, 128, L).astype(np.int64) for L in plen]
    feed = _prefill_feed(gpt_net, 16, prompts)
    first = exe.run(pf["main"], feed=feed,
                    fetch_list=[pf["first_token"]], scope=scope)[0]
    T = 3
    dec_logits, toks = [], [first.copy()]
    for _ in range(T):
        lg, nt = exe.run(dec["main"], feed={},
                         fetch_list=[dec["logits"], dec["next_token"]],
                         scope=scope)
        dec_logits.append(lg)
        toks.append(nt.copy())
    gen = np.concatenate(toks, axis=1)
    # teacher-forced forward of prompt + generated through the SAME
    # prefill program (slot_mask 0: state untouched)
    full = [np.concatenate([prompts[b], gen[b, :T + 1]]) for b in range(2)]
    feed2 = _prefill_feed(gpt_net, 16, full,
                          slot_mask=np.zeros((2, 1), np.float32))
    all_logits = exe.run(pf["main"], feed=feed2,
                         fetch_list=[pf["logits"]], scope=scope)[0]
    for t in range(T):
        for b in range(2):
            np.testing.assert_allclose(
                dec_logits[t][b], all_logits[b, plen[b] + t],
                atol=2e-4, rtol=1e-3,
                err_msg=f"decode step {t}, sequence {b}")


def test_kv_cache_proven_donated_through_chained_scan(gpt_net, gpt_session):
    """The acceptance-critical donation proof: every paged KV cache and
    the generation state ride ``run_chained``'s scan carry DONATED (the
    liveness pass proved in-place update is safe)."""
    exe, scope = gpt_session
    dec = gpt_net["decode"]
    exe.run_chained(dec["main"], feed={},
                    fetch_list=[dec["next_token"]], steps=2, scope=scope)
    key = next(k for k in exe._cache if k[0] == "chained")
    step = exe._cache[key]
    cfg = gpt_net["config"]
    for i in range(cfg.num_layers):
        assert f"gpt_kv_k_{i}" in step.donated_names
        assert f"gpt_kv_v_{i}" in step.donated_names
    assert "gpt_gen_tokens" in step.donated_names
    assert "gpt_gen_pos" in step.donated_names


def test_gpt_programs_pt71x_clean(gpt_net):
    """PT710-PT713 (donation races) must be silent on both phases — the
    fused append-and-attend op is exactly what keeps the caches free of
    read-after-write hazards."""
    from paddle_tpu.analysis import default_pass_manager, Severity

    mgr = default_pass_manager()
    pf = gpt_net["prefill"][16]
    # lint against the full declared fetch surface (this module's net is
    # built with fetch_logits=True, so the logits heads are live too)
    cases = [
        (pf["main"], [pf["first_token"].name, pf["logits"].name]),
        (gpt_net["decode"]["main"],
         [gpt_net["decode"]["next_token"].name,
          gpt_net["decode"]["logits"].name]),
    ]
    allowed_dead = {"reshape2", "transpose2", "unsqueeze2", "layer_norm"}
    for prog, fetches in cases:
        r = mgr.run_pipeline(prog, ("schema", "dataflow", "lowerability",
                                    "liveness", "donation_race",
                                    "dead_code"),
                             fetch_names=fetches, verify="none")
        pt71x = [d for d in r.diagnostics if d.code.startswith("PT71")]
        assert not pt71x, [f"{d.code}: {d.message}" for d in pt71x]
        errors = [d for d in r.diagnostics if d.severity == Severity.ERROR]
        assert not errors, [f"{d.code}: {d.message}" for d in errors]
        # dead-code findings must stay within the lint gate's allowlisted
        # schema-echo classes (XShape / layer_norm Mean/Variance)
        for d in r.diagnostics:
            if d.code in ("PT720", "PT721", "PT722"):
                assert d.op_type in allowed_dead, f"{d.code} {d.op_type}"


def test_sample_token_greedy_and_topk_determinism():
    """greedy == argmax; 'sample' draws only from the top-k set and is
    reproducible for a fixed program.random_seed."""
    from paddle_tpu import layers

    def build(strategy, top_k, seed):
        with un.guard():
            main, startup = fluid.Program(), fluid.Program()
            main.random_seed = seed
            with fluid.program_guard(main, startup):
                lg = layers.data("lg", shape=[4, 16], dtype="float32",
                                 append_batch_size=False)
                tok = layers.sample_token(lg, strategy=strategy,
                                          temperature=0.7, top_k=top_k)
            return main, tok

    logits = RNG.randn(4, 16).astype(np.float32)
    main, tok = build("greedy", 0, 1)
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe.run(main, feed={"lg": logits}, fetch_list=[tok])[0]
    np.testing.assert_array_equal(out.ravel(),
                                  logits.argmax(-1).astype(np.int64))

    draws = []
    for _ in range(2):
        main, tok = build("sample", 3, 7)
        e = fluid.Executor(fluid.CPUPlace())
        seqs = [e.run(main, feed={"lg": logits},
                      fetch_list=[tok])[0].ravel() for _ in range(3)]
        draws.append(np.stack(seqs))
    # same seed + same executor step sequence -> identical draws
    np.testing.assert_array_equal(draws[0], draws[1])
    top3 = np.argsort(logits, -1)[:, -3:]
    for s in draws[0]:
        for b in range(4):
            assert s[b] in top3[b]


# ---------------------------------------------------------------------------
# serving layer
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serving_net():
    return _build_net(batch_slots=2, max_seq=32, page_size=8,
                      prompt_buckets=(8, 16))


def _engine(serving_net, **gen_kw):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(serving_net["startup"], scope=scope)
    return serving.GenerativeEngine(
        serving_net, scope=scope, executor=exe,
        config=serving.ServingConfig(max_batch=2, queue_depth=64,
                                     deadline_s=0),
        gen_config=serving.GenerationConfig(decode_chunk=2, **gen_kw))


def test_generative_engine_end_to_end(serving_net):
    monitor.reset()
    eng = _engine(serving_net)
    # two prefill buckets + one decode + the chunked-prefill program
    # (prefix cache + chunked prefill are on by default since ISSUE 20)
    assert eng.warm_up() == 4
    rng = np.random.RandomState(3)
    with eng:
        futs = [eng.submit(rng.randint(1, 128, 3 + i % 9),
                           max_new_tokens=2 + i % 4, priority=1)
                for i in range(6)]
        stream0 = list(futs[0].stream(timeout=120))
        results = [f.result(timeout=120) for f in futs]
    for i, r in enumerate(results):
        assert r[0].shape == (2 + i % 4,), (i, r)
        assert list(futs[i].tokens()) == list(r[0])
    assert stream0 == list(results[0][0])
    acct = eng.accounting()
    assert acct["exact"] and acct["completed"] == 6 and acct["pending"] == 0
    # the position-bucketed decode compiled exactly once per (phase,
    # bucket) even though sequences sat at different positions
    assert eng.decode_recompiles == 0
    stats = eng.generation_stats()
    assert set(stats["compiled_buckets"]) == {"prefill:8", "prefill:16",
                                              "decode:2", "chunk:8"}
    assert monitor.metric_value("serving_decode_tokens_total", 0.0) \
        == sum(2 + i % 4 for i in range(6))
    it = monitor.metric_value("serving_intertoken_seconds", default=None)
    assert it and it["count"] > 0 and it["p99"] is not None


def test_recompile_guard_counts_warm_bucket_growth(serving_net):
    """Regression: a NEW executable appearing for an already-compiled
    (phase, bucket)'s program is a counted recompile — KV growth must
    never cause unbounded compiles. Compiles for OTHER programs on a
    shared executor must not count."""
    monitor.reset()
    eng = _engine(serving_net)
    eng.warm_up()
    # an unrelated program compiling on the shared executor: not ours
    with eng._exe._lock:
        eng._exe._cache[("chained", (999999, 0, 0), "other")] = object()
    eng._note_compiles("decode", len(eng._slots), eng._program)
    assert eng.decode_recompiles == 0
    # a NEW executable for the WARM decode program: a counted recompile
    serial = eng._program._serial
    with eng._exe._lock:
        eng._exe._cache[("chained", (serial, 1, 1), "forced")] = object()
    eng._note_compiles("decode", len(eng._slots), eng._program)
    assert eng.decode_recompiles == 1
    assert monitor.metric_value("serving_decode_recompiles_total", 0.0,
                                phase="decode",
                                bucket=str(len(eng._slots))) == 1.0
    # already-counted steps do not re-count
    eng._note_compiles("decode", len(eng._slots), eng._program)
    assert eng.decode_recompiles == 1


def test_streaming_future_unit():
    fut = serving.ServingFuture()
    fut._emit_tokens([1, 2])
    got = []
    it = fut.stream(timeout=5)
    got.append(next(it))
    got.append(next(it))
    fut._emit_tokens([3])
    fut._settle(result=[np.array([1, 2, 3])])
    got.extend(it)
    assert got == [1, 2, 3]
    assert fut.tokens() == [1, 2, 3]
    # emitting after the terminal outcome is an engine bug
    with pytest.raises(RuntimeError, match="after the request's terminal"):
        fut._emit_tokens([4])
    # error terminal: stream raises AFTER yielding the partials
    fut2 = serving.ServingFuture()
    fut2._emit_tokens([7])
    fut2._settle(error=serving.BatchFailed("boom"))
    out = []
    with pytest.raises(serving.BatchFailed):
        for t in fut2.stream(timeout=5):
            out.append(t)
    assert out == [7]


def test_mid_stream_deadline_settles_typed(serving_net):
    """A request whose deadline expires mid-generation reaches exactly one
    typed DeadlineExceeded; already-streamed tokens stay readable as
    partial results and the accounting stays exact."""
    import time

    monitor.reset()
    eng = _engine(serving_net)
    eng.warm_up()
    # pace the decode chunks so the deadline deterministically lands
    # MID-stream: after the first tokens, before the budget of 28
    orig = eng._run_decode_chunk

    def paced():
        time.sleep(0.06)
        orig()

    eng._run_decode_chunk = paced
    with eng:
        fut = eng.submit(np.array([5, 6, 7]), max_new_tokens=28,
                         deadline_s=0.16)
        err = fut.exception(timeout=120)
    assert isinstance(err, serving.DeadlineExceeded)
    partial = fut.tokens()
    assert 1 <= len(partial) < 28   # streamed some, then expired typed
    acct = eng.accounting()
    assert acct["exact"] and acct["deadline_exceeded"] == 1
    assert acct["completed"] == 0 and acct["pending"] == 0


def test_chaos_killed_batch_settles_typed_and_engine_continues(serving_net):
    monitor.reset()
    eng = _engine(serving_net)
    eng.warm_up()
    with eng:
        with fault_plan_guard("batch_dispatch:@2:RuntimeError"):
            f1 = eng.submit(np.array([5, 6, 7]), max_new_tokens=6)
            f2 = eng.submit(np.array([1, 2]), max_new_tokens=6)
            errs = [f.exception(timeout=120) for f in (f1, f2)]
        assert any(isinstance(e, serving.BatchFailed) for e in errs)
        for e in errs:
            assert e is None or isinstance(e, serving.BatchFailed)
        # the engine keeps serving after the kill
        f3 = eng.submit(np.array([9, 9]), max_new_tokens=3)
        assert len(f3.result(timeout=120)[0]) == 3
    acct = eng.accounting()
    assert acct["exact"] and acct["pending"] == 0
    assert acct["failed"] >= 1


def test_warm_up_refused_on_running_engine(serving_net):
    """warm_up resets the generation state, so on a running engine it
    would zero resident streams' caches mid-generation — refused."""
    eng = _engine(serving_net)
    eng.warm_up()
    with eng:
        with pytest.raises(RuntimeError, match="before start"):
            eng.warm_up()
    assert eng.accounting()["exact"]


def test_submit_validation(serving_net):
    eng = _engine(serving_net)
    # over-bucket prompts only refuse once chunked prefill is off
    # (default-on since ISSUE 20 they admit slice by slice instead)
    cold = _engine(serving_net, chunked_prefill=False, prefix_cache=False)
    with pytest.raises(ValueError, match="exceeds the largest prompt"):
        cold._build_gen_request(np.arange(40), 4, 0, None)
    with pytest.raises(ValueError, match="KV capacity"):
        eng._build_gen_request(np.arange(1, 9), 60, 0, None)
    with pytest.raises(ValueError, match="non-empty 1-D"):
        eng._build_gen_request(np.zeros((2, 3), np.int64), 4, 0, None)
    with pytest.raises(serving.EngineStopped):
        eng.submit(np.array([1, 2]))   # never started


def test_stop_without_drain_settles_resident_streams_typed(serving_net):
    eng = _engine(serving_net)
    eng.warm_up()
    eng.start()
    futs = [eng.submit(np.array([1, 2, 3]), max_new_tokens=24)
            for _ in range(3)]
    eng.stop(drain=False)
    outcomes = [f.exception(timeout=60) for f in futs]
    for e in outcomes:
        # either finished before the stop landed or typed EngineStopped
        assert e is None or isinstance(e, serving.EngineStopped)
    assert eng.accounting()["exact"]
