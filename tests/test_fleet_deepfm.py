"""Sharded embedding + fleet + DeepFM (VERDICT r2 item 5; BASELINE config #5).

Correctness bar (reference test_dist_fleet_base.py pattern): the DeepFM
model with mesh-sharded embedding tables must train to the same losses as
the plain replicated path, and the tables must actually be sharded."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models.deepfm import build_deepfm

VOCAB = 1024
FIELDS = 8


def _train(sharded, compiled, steps=8, batch=32):
    import paddle_tpu.unique_name as un

    with un.guard():
        m = build_deepfm(vocab=VOCAB, num_fields=FIELDS, emb_dim=8,
                         lr=0.02, sharded=sharded)
    m["main"].random_seed = 31
    prog = m["main"]
    if compiled:
        prog = fluid.CompiledProgram(m["main"]).with_data_parallel(
            loss_name=m["loss"].name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(2)
    ids = rng.randint(0, VOCAB, (batch, FIELDS)).astype(np.int64)
    y = (ids.sum(1) % 2).astype(np.float32).reshape(-1, 1)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(m["startup"])
        for _ in range(steps):
            (lv,) = exe.run(prog, feed={"feat_ids": ids, "label": y},
                            fetch_list=[m["loss"].name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses, scope


def test_deepfm_sharded_matches_replicated():
    base, _ = _train(sharded=False, compiled=True)
    shard, scope = _train(sharded=True, compiled=True)
    np.testing.assert_allclose(base, shard, rtol=5e-3, atol=1e-5)
    assert base[-1] < base[0]
    # the FM tables (and their Adam moments) must be dp-sharded in the scope
    sharded_names = [n for n, v in scope.vars.items()
                     if "dp" in str(getattr(v.sharding, "spec", ""))]
    assert any(n.startswith("fm_w") for n in sharded_names), sharded_names
    assert any(n.startswith("fm_v") for n in sharded_names), sharded_names
    assert any("moment" in n for n in sharded_names), sharded_names


def test_deepfm_single_device_trains():
    losses, _ = _train(sharded=False, compiled=False, steps=20)
    assert losses[-1] < losses[0] * 0.9


def test_fleet_collective_api():
    """fleet.init -> distributed_optimizer -> minimize -> run the compiled
    program (reference incubate/fleet/collective usage), single process."""
    from paddle_tpu.incubate.fleet.base.role_maker import PaddleCloudRoleMaker
    from paddle_tpu.incubate.fleet.collective import DistributedStrategy, fleet

    import paddle_tpu.unique_name as un

    os.environ["PADDLE_TRAINER_ID"] = "0"
    os.environ["PADDLE_TRAINERS_NUM"] = "1"
    try:
        fleet.init(PaddleCloudRoleMaker(is_collective=True))
        assert fleet.is_first_worker() and fleet.worker_index() == 0
        assert fleet.worker_num() == 1

        with un.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[16], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                pred = fluid.layers.fc(fluid.layers.fc(x, 32, act="relu"), 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                strategy = DistributedStrategy()
                strategy.use_sharding = True  # ZeRO via fleet
                opt = fleet.distributed_optimizer(
                    fluid.optimizer.Adam(learning_rate=0.05), strategy)
                opt.minimize(loss, startup_program=startup)

        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        xb = rng.randn(32, 16).astype(np.float32)
        yb = rng.randn(32, 1).astype(np.float32)
        losses = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(15):
                (lv,) = exe.run(fleet.main_program,
                                feed={"x": xb, "y": yb},
                                fetch_list=[loss.name])
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        assert losses[-1] < losses[0] * 0.3
        # use_sharding flowed through to ZeRO state sharding
        assert any("moment" in n and
                   "dp" in str(getattr(v.sharding, "spec", ""))
                   for n, v in scope.vars.items() if hasattr(v, "sharding"))
    finally:
        os.environ.pop("PADDLE_TRAINER_ID", None)
        os.environ.pop("PADDLE_TRAINERS_NUM", None)
        # reset the module singleton so later tests don't inherit state
        from paddle_tpu.incubate.fleet import collective as _c

        _c.fleet = _c.Fleet()
