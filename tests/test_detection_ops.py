"""Detection ops vs numpy oracles implementing the reference kernels
(operators/detection/)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest

RNG = np.random.RandomState(11)


def _np_iou(a, b):
    area = lambda bx: np.maximum(bx[:, 2] - bx[:, 0], 0) * \
        np.maximum(bx[:, 3] - bx[:, 1], 0)
    n, m = len(a), len(b)
    res = np.zeros((n, m), np.float32)
    for i in range(n):
        for j in range(m):
            ix0 = max(a[i, 0], b[j, 0]); iy0 = max(a[i, 1], b[j, 1])
            ix1 = min(a[i, 2], b[j, 2]); iy1 = min(a[i, 3], b[j, 3])
            inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
            u = area(a[i:i+1])[0] + area(b[j:j+1])[0] - inter
            res[i, j] = inter / u if u > 0 else 0.0
    return res


def _boxes(n, scale=1.0):
    xy = RNG.rand(n, 2).astype(np.float32) * 0.6 * scale
    wh = (RNG.rand(n, 2).astype(np.float32) * 0.3 + 0.05) * scale
    return np.concatenate([xy, xy + wh], 1).astype(np.float32)


class TestIouSimilarity(OpTest):
    def setup(self):
        a, b = _boxes(5), _boxes(7)
        self.op_type = "iou_similarity"
        self.inputs = {"X": a, "Y": b}
        self.outputs = {"Out": _np_iou(a, b)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)


class TestPriorBox(OpTest):
    def setup(self):
        feat = RNG.randn(1, 8, 4, 4).astype(np.float32)
        img = RNG.randn(1, 3, 64, 64).astype(np.float32)
        mins, maxs, ars = [20.0], [40.0], [2.0]
        # numpy oracle straight from prior_box_op.h default order
        exp_ars = [1.0, 2.0, 0.5]  # flip=True expansion
        step = 16.0
        P = len(exp_ars) + 1
        boxes = np.zeros((4, 4, P, 4), np.float32)
        for h in range(4):
            for w in range(4):
                cx, cy = (w + 0.5) * step, (h + 0.5) * step
                p = 0
                for ar in exp_ars:
                    bw = mins[0] * np.sqrt(ar) / 2
                    bh = mins[0] / np.sqrt(ar) / 2
                    boxes[h, w, p] = [(cx - bw) / 64, (cy - bh) / 64,
                                      (cx + bw) / 64, (cy + bh) / 64]
                    p += 1
                s = np.sqrt(mins[0] * maxs[0]) / 2
                boxes[h, w, p] = [(cx - s) / 64, (cy - s) / 64,
                                  (cx + s) / 64, (cy + s) / 64]
        var = np.broadcast_to(np.array([0.1, 0.1, 0.2, 0.2], np.float32),
                              boxes.shape)
        self.op_type = "prior_box"
        self.inputs = {"Input": feat, "Image": img}
        self.attrs = {"min_sizes": mins, "max_sizes": maxs,
                      "aspect_ratios": ars, "flip": True}
        self.outputs = {"Boxes": boxes, "Variances": np.array(var)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-6)


class TestBoxCoderDecode(OpTest):
    def setup(self):
        prior = _boxes(6, scale=10)
        pvar = (RNG.rand(6, 4).astype(np.float32) * 0.2 + 0.05)
        deltas = (RNG.randn(3, 6, 4) * 0.2).astype(np.float32)
        wantd = np.zeros((3, 6, 4), np.float32)
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + pw / 2
        pcy = prior[:, 1] + ph / 2
        for i in range(3):
            for j in range(6):
                d = deltas[i, j] * pvar[j]
                cx = d[0] * pw[j] + pcx[j]
                cy = d[1] * ph[j] + pcy[j]
                w = np.exp(d[2]) * pw[j]
                h = np.exp(d[3]) * ph[j]
                wantd[i, j] = [cx - w / 2, cy - h / 2, cx + w / 2,
                               cy + h / 2]
        self.op_type = "box_coder"
        self.inputs = {"PriorBox": prior, "PriorBoxVar": pvar,
                       "TargetBox": deltas}
        self.attrs = {"code_type": "decode_center_size"}
        self.outputs = {"OutputBox": wantd}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)


class TestYoloBox(OpTest):
    def setup(self):
        an, cls, H = 2, 3, 2
        anchors = [10, 14, 23, 27]
        xv = (RNG.randn(1, an * (5 + cls), H, H) * 0.5).astype(np.float32)
        img = np.array([[128, 128]], np.int32)
        down = 32
        v = xv.reshape(1, an, 5 + cls, H, H)
        sig = lambda z: 1 / (1 + np.exp(-z))
        boxes = np.zeros((1, an * H * H, 4), np.float32)
        scores = np.zeros((1, an * H * H, cls), np.float32)
        k = 0
        for a in range(an):
            for gy in range(H):
                for gx in range(H):
                    bx = (gx + sig(v[0, a, 0, gy, gx])) * 128 / H
                    by = (gy + sig(v[0, a, 1, gy, gx])) * 128 / H
                    bw = np.exp(v[0, a, 2, gy, gx]) * anchors[2 * a] * 128 \
                        / (down * H)
                    bh = np.exp(v[0, a, 3, gy, gx]) * anchors[2 * a + 1] \
                        * 128 / (down * H)
                    conf = sig(v[0, a, 4, gy, gx])
                    keep = conf >= 0.005
                    box = [max(bx - bw / 2, 0), max(by - bh / 2, 0),
                           min(bx + bw / 2, 127), min(by + bh / 2, 127)]
                    boxes[0, k] = [b * keep for b in box]
                    scores[0, k] = sig(v[0, a, 5:, gy, gx]) * conf * keep
                    k += 1
        self.op_type = "yolo_box"
        self.inputs = {"X": xv, "ImgSize": img}
        self.attrs = {"anchors": anchors, "class_num": cls,
                      "conf_thresh": 0.005, "downsample_ratio": down}
        self.outputs = {"Boxes": boxes, "Scores": scores}

    def test(self):
        self.check_output(rtol=1e-4, atol=1e-5)


def test_multiclass_nms_suppresses_overlaps():
    """Two heavily overlapping boxes + one separate: NMS keeps 2 per
    class; padding rows are -1."""
    bboxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                        [20, 20, 30, 30]]], np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # 1 class [N,C,M]
    scores = np.concatenate([np.zeros_like(scores), scores], 1)  # bg + c1
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b = fluid.layers.data("b", shape=[3, 4], dtype="float32")
        s = fluid.layers.data("s", shape=[2, 3], dtype="float32")
        o = fluid.layers.detection.multiclass_nms(
            b, s, score_threshold=0.05, nms_top_k=3, keep_top_k=3,
            nms_threshold=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (got,) = exe.run(main, feed={"b": bboxes, "s": scores},
                         fetch_list=[o.name])
    got = np.asarray(got)[0]
    kept = got[got[:, 0] >= 0]
    assert len(kept) == 2
    # highest score first; the 0.8 overlap was suppressed
    np.testing.assert_allclose(kept[0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(kept[1, 1], 0.7, rtol=1e-5)
    np.testing.assert_allclose(kept[1, 2:], [20, 20, 30, 30], rtol=1e-5)
    assert (got[2] == -1).all()


def test_roi_align_and_pool_shapes_and_values():
    feat = np.arange(2 * 1 * 4 * 4, dtype=np.float32).reshape(2, 1, 4, 4)
    rois = np.array([[0, 0, 3, 3], [1, 1, 3, 3]], np.float32)
    bidx = np.array([0, 1], np.int32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[1, 4, 4], dtype="float32")
        rv = fluid.layers.data("r", shape=[-1, 4], dtype="float32",
                               append_batch_size=False)
        bi = fluid.layers.data("bi", shape=[-1], dtype="int32",
                               append_batch_size=False)
        al = fluid.layers.detection.roi_align(xv, rv, 2, 2,
                                              rois_batch_idx=bi)
        pl = fluid.layers.detection.roi_pool(xv, rv, 2, 2,
                                             rois_batch_idx=bi)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        a, p = exe.run(main, feed={"x": feat, "r": rois, "bi": bidx},
                       fetch_list=[al.name, pl.name])
    assert np.asarray(a).shape == (2, 1, 2, 2)
    assert np.asarray(p).shape == (2, 1, 2, 2)
    # roi_pool on image 0, roi (0,0,3,3): quantized bins over 4x4 grid
    np.testing.assert_allclose(np.asarray(p)[0, 0],
                               [[5.0, 7.0], [13.0, 15.0]])
    # align values sit inside the feature's range and grow along the roi
    av = np.asarray(a)[0, 0]
    assert av[0, 0] < av[1, 1] and 0 <= av.min() and av.max() <= 15


class TestAnchorGenerator(OpTest):
    """Golden-value oracle mirroring the reference anchor_generator_op.h
    loop verbatim: legacy pixel conventions (offset*(stride-1) centers,
    round()-quantized base sizes, +/-0.5*(wh-1) corners)."""

    def setup(self):
        feat = RNG.randn(1, 8, 3, 2).astype(np.float32)  # H=3, W=2
        sizes, ars = [32.0, 64.0], [0.5, 1.0]
        sw, sh, offset = 16.0, 16.0, 0.5
        P = len(sizes) * len(ars)
        anchors = np.zeros((3, 2, P, 4), np.float32)
        for h in range(3):
            for w in range(2):
                x_ctr = w * sw + offset * (sw - 1)
                y_ctr = h * sh + offset * (sh - 1)
                idx = 0
                for ar in ars:
                    for size in sizes:
                        base_w = np.round(np.sqrt(sw * sh / ar))
                        base_h = np.round(base_w * ar)
                        aw = (size / sw) * base_w
                        ah = (size / sh) * base_h
                        anchors[h, w, idx] = [x_ctr - 0.5 * (aw - 1),
                                              y_ctr - 0.5 * (ah - 1),
                                              x_ctr + 0.5 * (aw - 1),
                                              y_ctr + 0.5 * (ah - 1)]
                        idx += 1
        var = np.broadcast_to(np.array([0.1, 0.1, 0.2, 0.2], np.float32),
                              anchors.shape)
        self.op_type = "anchor_generator"
        self.inputs = {"Input": feat}
        self.attrs = {"anchor_sizes": sizes, "aspect_ratios": ars,
                      "stride": [sw, sh], "offset": offset}
        self.outputs = {"Anchors": anchors, "Variances": np.array(var)}

    def test(self):
        self.check_output(rtol=1e-5, atol=1e-4)
