"""FleetAutoscaler: the control loop closing ROADMAP item 5.

Hysteresis, cooldown and every typed refusal are pinned with an
INJECTED clock (the SloBurnTracker idiom) against fake sensors and a
fake actuator — no sleeps, no processes. The scale-in race (drain a
replica mid-burst) runs against two REAL in-process engines behind a
real router, with a supervisor shim whose drain() is the engine's
graceful drain-stop: everything admitted on the victim completes,
nothing new lands on it, the fleet ledger stays exact, and a
concurrent scale-out decision during the drain is refused typed
``cooldown``. The multi-process version is the CI gate
(``tools/load_check.py --autoscale``)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.serving.fleet import (AutoscalerConfig, FleetAutoscaler,
                                      FleetRouter, Replica,
                                      ServingFrontend)
from paddle_tpu.serving.fleet.autoscaler import _worst


@pytest.fixture(autouse=True)
def _flags_reset():
    from paddle_tpu import flags as flags_mod

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    flags_mod._set_epoch += 1


# ---------------------------------------------------------------------------
# fakes: sensors + actuator the loop is pinned against
# ---------------------------------------------------------------------------

class FakeSupervisor:
    """Duck-typed actuator: records every act; tests move states."""

    def __init__(self, **states):
        self.states = dict(states)     # rid -> supervisor state
        self.added = []
        self.drained = []
        self.router = None

    def status(self):
        return {rid: {"state": s} for rid, s in self.states.items()}

    def add_replica(self, replica_id, model="mlp_tiny", aot_dir="",
                    extra_args=()):
        self.added.append(replica_id)
        self.states[replica_id] = "spawning"

    def drain(self, replica_id):
        self.drained.append(replica_id)
        # the real supervisor keeps the handle live until the process
        # exits; tests retire it explicitly


class FakeReplicaSensor:
    def __init__(self, replica_id, **snap):
        self.replica_id = replica_id
        self.snap = {"ok": True, "ready": True, "queue_depth": 0,
                     "degraded": False, "open_buckets": 0,
                     "slo_state": "ok", **snap}

    def snapshot(self):
        return dict(self.snap)


class FakeRouter:
    def __init__(self, *sensors):
        self.replicas = list(sensors)


def _cfg(**kw):
    base = dict(min_replicas=1, max_replicas=3, interval_s=0.01,
                cooldown_s=10.0, hot_sustain_s=2.0, calm_sustain_s=5.0,
                max_inflight_spawns=1, queue_high=4)
    base.update(kw)
    return AutoscalerConfig(**base)


def _loop(**kw):
    """(autoscaler, supervisor, sensor, clock) with one ready replica."""
    clk = [0.0]
    sup = FakeSupervisor(r0="ready")
    sensor = FakeReplicaSensor("r0")
    auto = FleetAutoscaler(sup, router=FakeRouter(sensor),
                           config=_cfg(**kw), _now=lambda: clk[0])
    return auto, sup, sensor, clk


# ---------------------------------------------------------------------------
# hysteresis: sustained signals only, no flap
# ---------------------------------------------------------------------------

def test_scale_out_needs_sustained_pressure_not_one_bad_tick():
    auto, sup, sensor, clk = _loop()
    sensor.snap["queue_depth"] = 9          # pressure
    assert auto.tick()["action"] == "hold"  # hot but not sustained
    clk[0] = 1.0
    assert auto.tick()["action"] == "hold"
    clk[0] = 2.5                            # past hot_sustain_s=2
    d = auto.tick()
    assert d["action"] == "scale_out" and "pressure" in d["reason"]
    assert sup.added == ["as1"]


def test_pressure_blip_resets_the_sustain_clock():
    auto, sup, sensor, clk = _loop()
    sensor.snap["queue_depth"] = 9
    auto.tick()
    clk[0] = 1.5
    sensor.snap["queue_depth"] = 0          # blip over: calm tick
    auto.tick()
    sensor.snap["queue_depth"] = 9          # hot again — clock restarts
    clk[0] = 3.0
    auto.tick()
    clk[0] = 4.9                            # 1.4s of heat only
    assert auto.tick()["action"] == "hold"
    assert sup.added == []


def test_oscillating_signal_never_scales():
    """Flap input, no flap output: a signal alternating faster than
    either sustain window produces holds forever."""
    auto, sup, sensor, clk = _loop()
    for i in range(40):
        clk[0] = i * 0.5
        sensor.snap["queue_depth"] = 9 if i % 2 else 0
        assert auto.tick()["action"] == "hold"
    assert sup.added == [] and sup.drained == []


def test_slo_burn_is_a_scale_out_signal():
    auto, sup, sensor, clk = _loop()
    sensor.snap["slo_state"] = "burning"
    auto.tick()
    clk[0] = 2.5
    d = auto.tick()
    assert d["action"] == "scale_out" and d["reason"] == "slo_burn"


def test_degraded_and_open_buckets_are_pressure():
    for key, val in (("degraded", True), ("open_buckets", 2)):
        auto, sup, sensor, clk = _loop()
        sensor.snap[key] = val
        auto.tick()
        clk[0] = 2.5
        assert auto.tick()["action"] == "scale_out"


# ---------------------------------------------------------------------------
# typed refusals — a decision is never silent
# ---------------------------------------------------------------------------

def _hot_sustained(auto, sensor, clk, t0=0.0):
    sensor.snap["queue_depth"] = 9
    clk[0] = t0
    auto.tick()
    clk[0] = t0 + 2.5


def test_refuse_at_max_replicas_typed_and_metered():
    auto, sup, sensor, clk = _loop(max_replicas=1)
    before = monitor.metric_value("autoscaler_decisions_total", 0.0,
                                  action="refuse_scale_out",
                                  reason="at_max_replicas")
    _hot_sustained(auto, sensor, clk)
    d = auto.tick()
    assert d["action"] == "refuse_scale_out"
    assert d["reason"] == "at_max_replicas"
    assert sup.added == []
    after = monitor.metric_value("autoscaler_decisions_total", 0.0,
                                 action="refuse_scale_out",
                                 reason="at_max_replicas")
    assert after == before + 1


def test_refuse_spawn_budget_spent_while_spawn_in_flight():
    auto, sup, sensor, clk = _loop(cooldown_s=1.0)
    _hot_sustained(auto, sensor, clk)
    assert auto.tick()["action"] == "scale_out"     # as1 now spawning
    clk[0] = 10.0                                   # cooldown long over
    d = auto.tick()
    assert d["action"] == "refuse_scale_out"
    assert d["reason"] == "spawn_budget_spent"
    sup.states["as1"] = "ready"                     # spawn lands
    clk[0] = 12.0
    assert auto.tick()["action"] == "scale_out"     # budget freed
    assert sup.added == ["as1", "as2"]


def test_refuse_cooldown_after_scale_out():
    auto, sup, sensor, clk = _loop()
    _hot_sustained(auto, sensor, clk)
    auto.tick()
    sup.states["as1"] = "ready"
    clk[0] = 5.0                                    # inside cooldown 10s
    d = auto.tick()
    assert d["action"] == "refuse_scale_out" and d["reason"] == "cooldown"
    clk[0] = 13.0                                   # cooldown elapsed
    assert auto.tick()["action"] == "scale_out"


def test_refuse_at_min_replicas_on_calm_floor():
    auto, sup, sensor, clk = _loop()
    auto.tick()                                     # calm clock starts
    clk[0] = 6.0                                    # calm > calm_sustain
    d = auto.tick()
    assert d["action"] == "refuse_scale_in"
    assert d["reason"] == "at_min_replicas"
    assert sup.drained == []


def test_scale_in_drains_the_lifo_autoscaler_spawn():
    auto, sup, sensor, clk = _loop()
    _hot_sustained(auto, sensor, clk)
    auto.tick()                                     # spawn as1
    sup.states["as1"] = "ready"
    sensor.snap["queue_depth"] = 0                  # calm
    clk[0] = 20.0
    auto.tick()                                     # calm clock starts
    clk[0] = 26.0                                   # calm 6s > 5s sustain
    d = auto.tick()
    assert d["action"] == "scale_in" and d["replica"] == "as1"
    assert sup.drained == ["as1"]


def test_drain_in_flight_refuses_concurrent_scale_out():
    """The scale-in race, unit form: while the victim drains, a hot
    signal must NOT scale out — typed cooldown until fully retired."""
    auto, sup, sensor, clk = _loop(cooldown_s=1.0)
    _hot_sustained(auto, sensor, clk)
    auto.tick()
    sup.states["as1"] = "ready"
    sensor.snap["queue_depth"] = 0
    clk[0] = 20.0
    auto.tick()
    clk[0] = 26.0
    assert auto.tick()["action"] == "scale_in"      # as1 draining
    sensor.snap["queue_depth"] = 9                  # burst returns NOW
    clk[0] = 27.0
    auto.tick()
    clk[0] = 30.0                                   # hot sustained, and
    d = auto.tick()                                 # cooldown_s=1 passed
    assert d["action"] == "refuse_scale_out" and d["reason"] == "cooldown"
    assert "drain" in d["detail"]
    sup.states["as1"] = "retired"                   # drain completes
    clk[0] = 31.0
    assert auto.tick()["action"] == "scale_out"     # loop breathes again
    assert sup.added == ["as1", "as2"]


def test_audit_coalesces_repeated_refusals():
    auto, sup, sensor, clk = _loop(max_replicas=1)
    _hot_sustained(auto, sensor, clk)
    for i in range(20):
        clk[0] = 3.0 + i * 0.1
        auto.tick()
    audit = auto.status()["audit"]
    refusals = [e for e in audit if e["action"] == "refuse_scale_out"]
    assert len(refusals) == 1 and refusals[0]["count"] == 20


def test_status_carries_sense_and_last_decision():
    auto, sup, sensor, clk = _loop()
    _hot_sustained(auto, sensor, clk)
    auto.tick()
    st = auto.status()
    assert st["sense"]["hot"] and st["sense"]["replicas"] == 1
    assert st["last_decision"]["action"] == "scale_out"
    assert st["spawned"] == ["as1"]


def test_config_validation_is_typed():
    with pytest.raises(ValueError):
        AutoscalerConfig(min_replicas=2, max_replicas=1).resolve()
    with pytest.raises(ValueError):
        _cfg(max_inflight_spawns=0).resolve()


def test_config_resolves_from_flags():
    fluid.set_flags({"FLAGS_serving_autoscale_max_replicas": 7,
                     "FLAGS_serving_autoscale_cooldown_s": 3.5})
    c = AutoscalerConfig().resolve()
    assert c.max_replicas == 7 and c.cooldown_s == 3.5


def test_worst_state_merge_order():
    assert _worst("ok", "burning") == "burning"
    assert _worst("warning", "ok") == "warning"
    assert _worst(None, "ok") == "ok"
    assert _worst(None, None) == "unknown"


def test_fleet_top_renders_autoscaler_and_tenant_table():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "fleet_top", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "fleet_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    snapshot = {
        "replicas": {"r0": {
            "up": True, "stale": False, "scrape_age_s": 0.1,
            "queue_depth": 2, "latency": {"p50": 0.01, "p99": 0.02},
            "slo": {"state": "ok",
                    "classes": {"interactive": {"state": "burning"},
                                "batch": {"state": "ok"}}},
            "rates": {}, "error": None}},
        "fleet": {"p50": 0.01, "p99": 0.02, "slo_state": "burning",
                  "outcomes": {"completed": 10},
                  "tenants": {"acme": {"outcomes": {"completed": 7,
                                                    "shed": 3},
                                       "quota_sheds": 3,
                                       "occupancy_s": 1.5}}},
    }
    auto, sup, sensor, clk = _loop()
    _hot_sustained(auto, sensor, clk)
    auto.tick()
    text = mod.render(snapshot, "12:00:00", autoscaler=auto.status())
    assert "interactive=burning" in text
    assert "autoscaler: replicas 1" in text
    assert "scale_out" in text
    assert "QUOTA_SHED" in text and "acme" in text
    # and the scrape-only CLI path still renders without an autoscaler
    assert "acme" in mod.render(snapshot, "12:00:00")


# ---------------------------------------------------------------------------
# the scale-in race against REAL engines (satellite regression test)
# ---------------------------------------------------------------------------

def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(**cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = serving.ServingConfig(max_batch=cfg_kw.pop("max_batch", 4),
                                **cfg_kw)
    return serving.ServingEngine(infer, feed_names=["x"],
                                 fetch_list=[pred], scope=scope,
                                 executor=exe, config=cfg)


def _feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


class EngineDrainSupervisor:
    """Supervisor shim over in-process engines: drain() IS the engine's
    graceful drain-stop (the preemption path), run on its own thread
    exactly like the real supervisor's SIGTERM."""

    def __init__(self, engines):
        self.engines = dict(engines)    # rid -> engine
        self.states = {rid: "ready" for rid in self.engines}
        self.added = []
        self.threads = []

    def status(self):
        return {rid: {"state": s} for rid, s in self.states.items()}

    def add_replica(self, replica_id, **kw):
        self.added.append(replica_id)
        self.states[replica_id] = "spawning"

    def drain(self, replica_id):
        def _drain():
            self.engines[replica_id].stop(drain=True)
            self.states[replica_id] = "retired"

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        self.threads.append(t)


@pytest.fixture()
def fleet2():
    engines, fronts = [], []
    for i in range(2):
        eng = _engine(batch_window_s=0.005, queue_depth=64)
        eng.warm_up()
        eng.start()
        fe = ServingFrontend(eng, replica_id=f"r{i}")
        fe.start()
        engines.append(eng)
        fronts.append(fe)
    router = FleetRouter([Replica(f"r{i}", "127.0.0.1", fe.port)
                          for i, fe in enumerate(fronts)])
    router.poll_now()
    yield router, engines, fronts
    router.stop()
    for fe in fronts:
        fe.stop(wait_inflight_s=2.0)
    for eng in engines:
        if not eng._stopped:
            eng.stop(drain=False)


def test_scale_in_mid_burst_drains_clean_and_refuses_concurrent_scale_out(
        fleet2):
    router, engines, fronts = fleet2
    sup = EngineDrainSupervisor({"r0": engines[0], "r1": engines[1]})
    clk = [0.0]
    auto = FleetAutoscaler(
        sup, router=router,
        config=_cfg(min_replicas=1, calm_sustain_s=1.0, cooldown_s=0.5),
        _now=lambda: clk[0])

    # a burst is in flight while the loop decides to scale in
    stop_burst = threading.Event()
    errors = []

    def _burst(seed):
        i = 0
        while not stop_burst.is_set():
            try:
                router.submit(_feed(seed=seed * 1000 + i))
            except serving.ServingError:
                pass   # typed sheds are legal under burst
            except Exception as e:   # noqa: BLE001 — fail the test
                errors.append(e)
            i += 1

    threads = [threading.Thread(target=_burst, args=(s,), daemon=True)
               for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)                     # requests in flight everywhere

    auto.tick()                         # calm clock starts (snapshots
    clk[0] = 1.5                        # predate the burst)
    d = auto.tick()
    assert d["action"] == "scale_in" and d["replica"] == "r1"

    # concurrent scale-out decision during the drain: typed cooldown
    clk[0] = 4.0   # cooldown_s long passed; only the drain holds it
    for rep in router.replicas:         # force a sustained-hot signal
        if rep.replica_id == "r0":
            rep._update({**rep.snapshot(), "queue_depth": 99})
    auto.tick()
    clk[0] = 7.0
    d = auto.tick()
    assert d["action"] == "refuse_scale_out"
    assert d["reason"] == "cooldown" and "drain" in d["detail"]
    assert sup.added == []

    # drain completes: victim finished everything it admitted
    for t in sup.threads:
        t.join(30.0)
    assert sup.states["r1"] == "retired"
    stop_burst.set()
    for t in threads:
        t.join(10.0)
    assert not errors

    victim = engines[1].accounting()
    assert victim["exact"] and victim["pending"] == 0
    assert victim["completed"] > 0 and victim["failed"] == 0

    # nothing new lands on the drained replica
    router.poll_now()
    before = engines[1].accounting()["submitted"]
    for i in range(5):
        router.submit(_feed(seed=9000 + i))
    assert engines[1].accounting()["submitted"] == before

    # the fleet ledger stays exact through the whole race
    acct = router.accounting()
    assert acct["exact"]
    assert acct["replica_lost"] == 0

    # and once the victim is retired, the loop can scale out again
    # (re-force the hot signal: the post-drain poll refreshed snapshots)
    for rep in router.replicas:
        if rep.replica_id == "r0":
            rep._update({**rep.snapshot(), "queue_depth": 99})
    clk[0] = 8.0
    decisions = [auto.tick()]
    clk[0] = 11.0
    decisions.append(auto.tick())
    assert any(d["action"] == "scale_out" for d in decisions)
    assert sup.added == ["as1"]
