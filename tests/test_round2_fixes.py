"""Round-2 correctness fixes: decayed_adagrad, pool2d ceil/adaptive,
ModelAverage true windowed average, npz checkpoints, cache invalidation."""
import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest


class TestDecayedAdagradOp(OpTest):
    def setup(self):
        rng = np.random.RandomState(0)
        p = rng.rand(4, 3).astype(np.float32)
        g = rng.rand(4, 3).astype(np.float32)
        m = rng.rand(4, 3).astype(np.float32)
        lr = np.array([0.01], np.float32)
        decay, eps = 0.95, 1e-6
        m_out = decay * m + (1 - decay) * g * g
        p_out = p - lr * g / (np.sqrt(m_out) + eps)
        self.op_type = "decayed_adagrad"
        self.inputs = {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr}
        self.attrs = {"decay": decay, "epsilon": eps}
        self.outputs = {"ParamOut": p_out, "MomentOut": m_out}

    def test(self):
        self.check_output()


def test_decayed_adagrad_differs_from_adagrad():
    """The decayed rule must NOT monotonically accumulate like adagrad."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.fc(x, 1, name="da_fc")
        loss = fluid.layers.mean(y)
        opt = fluid.optimizer.DecayedAdagrad(learning_rate=0.1, decay=0.5)
        opt.minimize(loss)
    assert any(op.type == "decayed_adagrad" for op in main.global_block.ops)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(40):
            exe.run(main, feed={"x": np.ones((4, 2), np.float32)},
                    fetch_list=[loss])
        m_names = [n for n in scope.vars if n.startswith("moment_")]
        assert m_names, "moment accumulator missing"
        # decayed averaging keeps every moment bounded by max grad^2 (<= 1.0
        # here: bias grad is exactly 1); adagrad's monotone sum would reach
        # ~40 after 40 steps
        for n in m_names:
            m = scope.numpy(n)
            assert 0.0 < m.max() <= 1.0 + 1e-5, (
                f"moment '{n}' = {m.max()} exceeds max grad^2 — monotone "
                f"accumulation, not decayed averaging")


class TestPool2dCeilMode(OpTest):
    def setup(self):
        # ADVICE case: 6x6 input, k3 s2 ceil -> 3x3 output (floor gives 2x2)
        x = np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6)
        want = np.zeros((1, 1, 3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                want[0, 0, i, j] = x[0, 0, 2*i:2*i+3, 2*j:2*j+3].max()
        self.op_type = "pool2d"
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [3, 3],
                      "strides": [2, 2], "paddings": [0, 0],
                      "ceil_mode": True}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestPool2dCeilModeAvgExclusive(OpTest):
    def setup(self):
        x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
        want = np.zeros((1, 1, 3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                win = x[0, 0, 2*i:min(2*i+2, 5), 2*j:min(2*j+2, 5)]
                want[0, 0, i, j] = win.mean()  # exclusive: only real elements
        self.op_type = "pool2d"
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0],
                      "ceil_mode": True, "exclusive": True}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestPool2dAdaptiveGeneral(OpTest):
    def setup(self):
        # 5x5 -> 2x2 adaptive avg: non-uniform regions [0:3),[2:5) per torch/
        # paddle semantics floor(i*D/o)..ceil((i+1)*D/o)
        x = np.random.RandomState(3).rand(2, 3, 5, 5).astype(np.float32)
        oh = ow = 2
        want = np.zeros((2, 3, 2, 2), np.float32)
        for i in range(oh):
            h0, h1 = (i * 5) // oh, -((-(i + 1) * 5) // oh)
            for j in range(ow):
                w0, w1 = (j * 5) // ow, -((-(j + 1) * 5) // ow)
                want[:, :, i, j] = x[:, :, h0:h1, w0:w1].mean(axis=(2, 3))
        self.op_type = "pool2d"
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2], "adaptive": True}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


class TestPool2dAdaptiveUniform(OpTest):
    def setup(self):
        # 6x6 -> 3x3 adaptive max: uniform fast path (2x2 windows)
        x = np.random.RandomState(4).rand(1, 2, 6, 6).astype(np.float32)
        want = x.reshape(1, 2, 3, 2, 3, 2).max(axis=(3, 5))
        self.op_type = "pool2d"
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [3, 3], "adaptive": True}
        self.outputs = {"Out": want}

    def test(self):
        self.check_output()


def _build_sgd_model():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.fc(x, 1, name="ma_fc", bias_attr=False)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_model_average_true_windowed_mean():
    main, startup, loss = _build_sgd_model()
    with fluid.program_guard(main, startup):
        # window larger than the run so no roll happens: the applied value is
        # the plain mean over all 5 steps
        ma = fluid.optimizer.ModelAverage(
            average_window_rate=1.0, min_average_window=100,
            max_average_window=100)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    pname = main.all_parameters()[0].name
    with fluid.scope_guard(scope):
        exe.run(startup)
        param_history = []
        for _ in range(5):
            exe.run(main, feed={"x": np.ones((4, 2), np.float32)},
                    fetch_list=[loss])
            param_history.append(scope.numpy(pname).copy())
        final = scope.numpy(pname).copy()
        with ma.apply(exe):
            got = scope.numpy(pname)
            want = np.mean(param_history, axis=0)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
            assert not np.allclose(got, final), "average equals final weights"
        np.testing.assert_allclose(scope.numpy(pname), final)  # restored


def test_model_average_raises_without_training():
    main, startup, loss = _build_sgd_model()
    with fluid.program_guard(main, startup):
        ma = fluid.optimizer.ModelAverage(min_average_window=1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with pytest.raises(RuntimeError, match="never ran|not in"):
            with ma.apply(exe):
                pass


def test_checkpoint_npz_not_pickle(tmp_path):
    main, startup, loss = _build_sgd_model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    d = str(tmp_path / "ckpt")
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_checkpoint(exe, d, main, meta={"step": 7})
        import zipfile
        assert zipfile.is_zipfile(f"{d}/ckpt.npz"), "combined blob must be npz"
        pname = main.all_parameters()[0].name
        orig = scope.numpy(pname).copy()
        scope.set_var(pname, np.zeros_like(orig))
        meta = fluid.io.load_checkpoint(exe, d, main)
        assert meta["step"] == 7
        np.testing.assert_allclose(scope.numpy(pname), orig)


def test_executor_cache_invalidated_by_set_attr():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.dropout(x, dropout_prob=0.99)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones((4, 8), np.float32)}
    drop_op = next(op for op in main.global_block.ops
                   if op.type == "dropout")
    with fluid.scope_guard(scope):
        exe.run(startup)
        train_out = exe.run(main, feed=feed, fetch_list=[y])[0]
        drop_op.set_attr("is_test", True)  # must recompile, not reuse cache
        test_out = exe.run(main, feed=feed, fetch_list=[y])[0]
    assert np.count_nonzero(train_out) < train_out.size  # p=.99 zeroed most
    np.testing.assert_allclose(test_out, feed["x"] * 0.01, rtol=1e-5)


def test_dgc_decision_surface():
    """DGC (VERDICT r5 item 10): a raise-shim with a migration path, the
    way async-PS/GEO were closed."""
    import pytest

    with pytest.raises(NotImplementedError, match="local_sgd|Momentum"):
        fluid.optimizer.DGCMomentumOptimizer(
            learning_rate=0.1, momentum=0.9, rampup_begin_step=0)
