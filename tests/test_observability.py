"""Error provenance, FLAGS shim, check_nan_inf (VERDICT r2 item 9;
reference framework/op_call_stack.h, platform/flags.cc:44)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def test_op_callstack_recorded_and_in_lowering_errors():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, 8)  # THE user line
    ops = main.global_block.ops
    assert any("test_observability.py" in op.attrs.get("op_callstack", "")
               for op in ops)

    # a shape error at run time must name the op and the creation site
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(RuntimeError) as ei:
            exe.run(main, feed={"x": np.ones((2, 7), np.float32)},  # 7 != 4
                    fetch_list=[h.name])
    msg = str(ei.value)
    assert "mul" in msg and "test_observability.py" in msg, msg


def test_flags_shim():
    assert fluid.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        assert fluid.get_flags(["check_nan_inf"])["FLAGS_check_nan_inf"] is True
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})
    with pytest.raises(KeyError, match="unknown flag"):
        fluid.set_flags({"FLAGS_no_such_flag": 1})
    # inert compat flags are accepted
    fluid.set_flags({"FLAGS_fraction_of_gpu_memory_to_use": 0.5})


def test_check_nan_inf_names_the_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.log(x)   # log of a negative -> nan
        out = fluid.layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            # finite input: passes
            (v,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                           fetch_list=[out.name])
            assert np.isfinite(v).all()
            with pytest.raises(FloatingPointError) as ei:
                exe.run(main,
                        feed={"x": -np.ones((2, 4), np.float32)},
                        fetch_list=[out.name])
        assert "log" in str(ei.value), str(ei.value)
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})


def test_check_nan_inf_off_does_not_raise():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.mean(fluid.layers.log(x))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (v,) = exe.run(main, feed={"x": -np.ones((2, 4), np.float32)},
                       fetch_list=[out.name])
    assert np.isnan(v).all()


def test_check_nan_inf_keeps_scope_usable_after_error():
    """Review regression: inputs are donated — after a sanitizer error the
    scope must be restored to usable pre-step values, not deleted (or
    nan-poisoned) buffers. log(h*h) keeps the clean-input leg finite for
    any sign of the restored weights; the nan feed trips the sanitizer."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, 4, name="f")
        out = fluid.layers.mean(fluid.layers.log(h * h))
        fluid.optimizer.SGD(0.1).minimize(out)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            w0 = np.array(scope.find_var("f.w_0"))
            with pytest.raises(FloatingPointError):
                exe.run(main,
                        feed={"x": np.full((2, 4), np.nan, np.float32)},
                        fetch_list=[out.name])
            # the nan step's (poisoned) update must NOT have been applied
            w1 = np.array(scope.find_var("f.w_0"))
            assert np.array_equal(w0, w1)
            # the session must still run — and train — with clean input
            (v,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32) * 9},
                           fetch_list=[out.name])
        assert np.isfinite(v).all()
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})


def test_check_nan_inf_with_while_grad():
    """Review regression: sub-block replays (while_grad) must not leak
    tracers into the top-level check list."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            i = fluid.layers.fill_constant([1], "int64", 0)
            n = fluid.layers.fill_constant([1], "int64", 3)
            h = fluid.layers.fc(x, 4, name="g")
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond, max_len=3)
            with w.block():
                fluid.layers.assign(fluid.layers.scale(h, scale=0.5), h)
                fluid.layers.increment(i, value=1)
                fluid.layers.assign(fluid.layers.less_than(i, n), cond)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            (v,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                           fetch_list=[loss.name])
        assert np.isfinite(v).all()
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})


def test_check_nan_inf_compiled_program():
    """The flag works on the data-parallel path too."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.mean(fluid.layers.log(x))
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=out.name)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(FloatingPointError, match="log"):
                exe.run(compiled, feed={"x": -np.ones((8, 4), np.float32)},
                        fetch_list=[out.name])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": 0})
