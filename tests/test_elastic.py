"""resilience.elastic — elastic preemption-tolerant training: device-loss
classification, mesh rescale planning (PT61x refusals), composed-mesh
elastic restore, data-cursor resume, graceful SIGTERM shutdown, and the
interruptible retry backoff. End-to-end proof lives in
``tools/chaos_check.py --elastic``; these tests pin the pieces."""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor
from paddle_tpu.resilience import elastic as E
from paddle_tpu.resilience import faults, graceful
from paddle_tpu.resilience.retry import (RetryExhaustedError, RetryPolicy,
                                         call_with_retry,
                                         set_thread_stop_event)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_elastic_env():
    """Elastic tests flip flags, install fault plans and trip the global
    graceful-shutdown event; restore everything so later tests see a
    clean world."""
    from paddle_tpu import flags as flags_mod

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    faults.clear_plan()
    graceful.reset_shutdown_state()
    set_thread_stop_event(None)


# ---------------------------------------------------------------------------
# 1. device-loss classification
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("msg", [
    "TPU device 3 is halted",
    "device was lost during execution",
    "chip 2 became unhealthy",
    "worker preempted by scheduler",
    "ICI link down on slice 0",
    "failed to connect to worker host-7",
    "NCCL error: unhandled system error",
])
def test_classify_real_zoo(msg):
    err = E.classify_device_error(RuntimeError(msg), site="parallel_step")
    assert isinstance(err, E.DeviceLostError)
    assert err.site == "parallel_step"
    assert err.transient is False


def test_classify_rejects_non_device_errors():
    # a program bug whose message happens to say "device lost" is still a
    # program bug (ValueError is never a device loss)
    assert E.classify_device_error(ValueError("device lost")) is None
    assert E.classify_device_error(RuntimeError("shape mismatch")) is None
    assert E.classify_device_error(
        RuntimeError("compile failed: invalid HLO")) is None


def test_classify_walks_cause_chain():
    inner = RuntimeError("device 5 halted unexpectedly")
    try:
        try:
            raise inner
        except RuntimeError as e:
            raise RuntimeError("step dispatch failed") from e
    except RuntimeError as outer:
        got = E.classify_device_error(outer)
    assert isinstance(got, E.DeviceLostError)


def test_classify_gates_types_per_chain_element():
    # an Exception-typed wrapper around a runtime device loss must still
    # classify (the type gate applies per chain element) ...
    try:
        try:
            raise RuntimeError("TPU chip 2 became unhealthy")
        except RuntimeError as e:
            raise Exception("dispatch wrapper") from e
    except Exception as outer:
        assert isinstance(E.classify_device_error(outer),
                          E.DeviceLostError)
    # ... while a chain with no runtime-ish element stays unclassified
    # even when the text matches (a program bug quoting the zoo)
    try:
        try:
            raise ValueError("device lost")
        except ValueError as e:
            raise Exception("wrapper") from e
    except Exception as outer:
        assert E.classify_device_error(outer) is None


def test_device_loss_classification_context_manager():
    with pytest.raises(E.DeviceLostError) as ei:
        with E.device_loss_classification("collective"):
            raise RuntimeError("ICI link down on slice 1")
    assert ei.value.site == "collective"
    # non-device errors pass through untouched
    with pytest.raises(ValueError):
        with E.device_loss_classification("collective"):
            raise ValueError("bad shape")


def test_classify_passes_existing_device_lost_through():
    orig = E.DeviceLostError("chip gone", site="collective")
    assert E.classify_device_error(orig) is orig


def test_injected_device_lost_site_classifies():
    assert "device_lost" in faults.SITES
    with faults.fault_plan_guard("device_lost:1:RuntimeError"):
        with pytest.raises(RuntimeError) as ei:
            faults.fault_point("device_lost")
    got = E.classify_device_error(ei.value)
    assert isinstance(got, E.DeviceLostError)


def test_retry_never_absorbs_device_loss():
    """The negative control the acceptance criteria demand: a dead chip
    must surface immediately — exactly one attempt, no backoff, no
    RetryExhaustedError wrapper."""
    attempts = {"n": 0}

    def dead_chip():
        attempts["n"] += 1
        raise E.DeviceLostError("chip gone")

    with pytest.raises(E.DeviceLostError):
        call_with_retry("step", dead_chip)
    assert attempts["n"] == 1


# ---------------------------------------------------------------------------
# 2. rescale planning (PT61x refusals)
# ---------------------------------------------------------------------------

def test_plan_rescale_pure_dp():
    assert E.plan_rescale({"dp": 8}, 4) == {"dp": 4}
    assert E.plan_rescale({"dp": 4}, 8) == {"dp": 8}   # capacity returned
    assert E.plan_rescale({"dp": 8}, 7) == {"dp": 7}


def test_plan_rescale_composed_mesh_keeps_non_dp_axes():
    assert E.plan_rescale({"dp": 4, "pp": 2}, 6) == {"dp": 3, "pp": 2}
    assert E.plan_rescale({"dp": 2, "pp": 2, "sp": 2}, 4) == \
        {"dp": 1, "pp": 2, "sp": 2}


def test_plan_rescale_refuses_unsatisfiable_non_dp_axes():
    with pytest.raises(E.ElasticRescaleError) as ei:
        E.plan_rescale({"dp": 4, "pp": 4}, 3)
    assert ei.value.code == "PT610"
    assert ei.value.transient is False


def test_plan_rescale_refuses_below_min_dp():
    with pytest.raises(E.ElasticRescaleError) as ei:
        E.plan_rescale({"dp": 8}, 1, min_dp=2)
    assert ei.value.code == "PT611"


def test_plan_rescale_global_batch_constraint():
    # 6 survivors but batch 16: dp=6 does not divide 16 -> fall to 4
    assert E.plan_rescale({"dp": 8}, 6, global_batch=16) == {"dp": 4}
    with pytest.raises(E.ElasticRescaleError) as ei:
        E.plan_rescale({"dp": 8}, 6, min_dp=5, global_batch=16)
    assert ei.value.code == "PT613"


def test_grad_accum_preserves_global_batch():
    assert E.grad_accum_steps(8, 4) == 2
    assert E.grad_accum_steps(8, 8) == 1
    assert E.grad_accum_steps(8, 3) == 3   # ceil
    assert E.grad_accum_steps(4, 8) == 1   # upscale never accumulates


def test_elastic_codes_documented():
    for code in ("PT610", "PT611", "PT612", "PT613", "PT614"):
        assert code in E.ELASTIC_CODES
    err = E.ElasticRescaleError("PT612", "budget spent")
    assert "PT612" in str(err) and err.code == "PT612"


def test_survivor_devices_prefix_and_refusal():
    devs = list(range(8))
    assert E.survivor_devices(devs, {"dp": 4}) == [0, 1, 2, 3]
    with pytest.raises(E.ElasticRescaleError) as ei:
        E.survivor_devices(devs[:3], {"dp": 2, "pp": 2})
    assert ei.value.code == "PT610"


# ---------------------------------------------------------------------------
# 3. composed-mesh elastic restore + post-rescale divergence sweep
# ---------------------------------------------------------------------------

class _VarStub:
    def __init__(self, name, shape):
        self.name = name
        self.shape = shape


def _composed_state(mesh):
    """State sharded over dp on a dp x pp mesh + a replicated var."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    sharded = rng.rand(8, 6).astype(np.float32)
    repl = rng.rand(5, 3).astype(np.float32)
    vals = {
        "moment": jax.device_put(sharded, NamedSharding(mesh, P("dp"))),
        "weight": jax.device_put(repl, NamedSharding(mesh, P())),
    }
    return vals, {"moment": sharded, "weight": repl}


def test_elastic_restore_across_composed_mesh(tmp_path):
    """A checkpoint saved from a dp x pp mesh restores byte-equal into a
    fresh scope (the full-gather-equivalent reassembly), and the PT610
    refusal fires when the surviving devices cannot satisfy the
    checkpoint's pp axis."""
    import jax

    from paddle_tpu.resilience import checkpoint as rck
    from paddle_tpu.resilience import distributed as dist
    from paddle_tpu.parallel.sharding import make_mesh

    mesh = make_mesh({"dp": 4, "pp": 2})
    vals, host = _composed_state(mesh)
    scope = fluid.Scope()
    for n, v in vals.items():
        scope.set_var(n, v)
    vars_ = [_VarStub("moment", (8, 6)), _VarStub("weight", (5, 3))]
    d = str(tmp_path / "ck")
    os.makedirs(d)
    manifest = dist.save_sharded_vars(d, vars_, scope, mesh)
    rck.finalize_manifest(d)
    # the dp-sharded var went out as per-shard slices, the replicated one
    # to common.npz; the manifest records the composed mesh
    assert manifest["sharding"]["mesh"] == {"dp": 4, "pp": 2}
    assert manifest["sharding"]["specs"]["moment"]["parts"] == 4
    assert "weight" not in manifest["sharding"]["specs"]

    # elastic restore on a DIFFERENT (smaller) world: byte-equal
    manifest2 = rck.verify_checkpoint(d)
    scope2 = fluid.Scope()
    dist.load_sharded_vars(d, manifest2, vars_, scope2)
    for n in ("moment", "weight"):
        np.testing.assert_array_equal(np.asarray(scope2.find_var(n)),
                                      host[n])

    # refusal diagnostics: 3 survivors cannot satisfy pp=2 at all widths
    with pytest.raises(E.ElasticRescaleError) as ei:
        E.plan_rescale(manifest2["sharding"]["mesh"], 1)
    assert ei.value.code == "PT610"
    # 6 survivors can: dp shrinks, pp survives
    assert E.plan_rescale(manifest2["sharding"]["mesh"], 6) == \
        {"dp": 3, "pp": 2}

    # divergence-check pass immediately after a rescale: replicated state
    # on the post-rescale (smaller) mesh must compare clean
    small = make_mesh({"dp": 2, "pp": 2})
    vals2, _ = _composed_state(small)
    assert dist.replica_divergence_check(small, vals2) == []
    del jax


def test_compiled_program_rescale_clears_cache():
    import jax

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            pred = fluid.layers.fc(x, 2)
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=None)
    prog._cache[("sentinel",)] = object()
    prog._replica_steps = 7
    old_mesh = prog._mesh
    prog.rescale(jax.devices()[:4])
    assert prog._cache == {}
    assert prog._replica_steps == 0
    assert prog._mesh is not old_mesh
    assert dict(prog._mesh.shape) == {"dp": 4}


# ---------------------------------------------------------------------------
# 4. end-to-end: Trainer self-heals through an injected device loss
# ---------------------------------------------------------------------------

def _train_func():
    x = fluid.layers.data("x", shape=[6], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))


def _reader(n_batches=8, rows=16):
    def rd():
        for i in range(n_batches):
            rng = np.random.RandomState(50 + i)
            x = rng.rand(rows, 6).astype(np.float32)
            y = x.sum(axis=1, keepdims=True).astype(np.float32)
            yield [(x[j], y[j]) for j in range(rows)]
    return rd


@pytest.mark.known_flaky(
    reason="KNOWN_FAILURES.md 'Pre-existing flake': intermittent "
           "ReplicaDivergenceError on fc_0.b_0 after the dp=8->4 rescale "
           "in whole-file runs only (1-ULP CPU-reduction "
           "nondeterminism); passes standalone. Expect ±1 on the tier-1 "
           "count; do NOT chase the gloo/1-ULP root cause here")
def test_trainer_elastic_recovery_end_to_end(tmp_path):
    """dp=8 -> injected device loss -> automatic rescale to dp=4,
    restore from the last verified serial, exact fast-forward, rescale
    counter + event recorded, divergence sweep armed across the rescale
    and silent."""
    import jax

    fluid.set_flags({
        "FLAGS_fault_plan": "device_lost:@4:RuntimeError",
        "FLAGS_replica_check_interval": "2",
    })
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          max_num_checkpoints=0,
                                          step_interval=2, sharded=True)
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
    trainer.elastic_devices_fn = lambda: jax.devices()[:4]
    trace = []

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            trace.append(ev.step)

    before = monitor.metric_value("elastic_rescales_total", default=0.0,
                                  old="dp=8", new="dp=4",
                                  direction="down")
    trainer.train(num_epochs=1, event_handler=handler,
                  reader=_reader(8), feed_order=["x", "y"])
    # loss at dispatch 4 (step idx 3); last verified serial at step 2
    assert len(trainer.elastic_events) == 1
    ev = trainer.elastic_events[0]
    assert ev["old"] == "dp=8" and ev["new"] == "dp=4"
    assert ev["direction"] == "down" and ev["step"] == 2
    assert ev["grad_accum_steps"] == 2
    assert ev["serial"] is not None
    # steps 0,1,2 ran, the loss preempted step 3 before it committed;
    # resume fast-forwards to batch 2 and consumes exactly 2..7 — no
    # duplicates, no gaps
    assert trace == [0, 1, 2] + list(range(2, 8))
    assert trainer._step == 8
    after = monitor.metric_value("elastic_rescales_total", default=0.0,
                                 old="dp=8", new="dp=4",
                                 direction="down")
    assert after == before + 1
    assert dict(trainer._train_mesh.shape) == {"dp": 4}


def test_trainer_recovers_untyped_async_device_loss(tmp_path):
    """A device loss that surfaces as an UNTYPED runtime error at result
    materialization (fully-async dispatch, watchdog unarmed) must still
    classify and recover — the headline feature cannot depend on the
    watchdog being armed or on the error being raised synchronously."""
    import jax

    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          max_num_checkpoints=0,
                                          step_interval=2, sharded=True)
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
    trainer.elastic_devices_fn = lambda: jax.devices()[:4]
    real_run = trainer.exe.run
    calls = {"n": 0}

    def flaky_run(*a, **k):
        calls["n"] += 1
        if calls["n"] == 4:
            # what jax surfaces when the loss is only seen at a late
            # result read: an untyped runtime error, no probe involved
            raise RuntimeError("TPU device 2 is halted")
        return real_run(*a, **k)

    trainer.exe.run = flaky_run
    trace = []

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            trace.append(ev.step)

    trainer.train(num_epochs=1, event_handler=handler,
                  reader=_reader(6), feed_order=["x", "y"])
    assert len(trainer.elastic_events) == 1
    assert trainer.elastic_events[0]["new"] == "dp=4"
    # loss preempted step 3; checkpoint at step 2 -> resume 2..5 exact
    assert trace == [0, 1, 2] + list(range(2, 6))


def test_elastic_recover_legacy_checkpoint_continues_forward(tmp_path):
    """A restored checkpoint WITHOUT a data_cursor (pre-elastic writer)
    must not rewind the data stream to batch 0 — it keeps the historic
    continue-forward semantics, like the divergence path."""
    import jax

    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=1)
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
        prog = fluid.CompiledProgram(trainer.main_program) \
            .with_data_parallel(loss_name=trainer.loss.name)
    trainer._full_dp = int(prog._mesh.shape.get("dp", 1))
    trainer._full_ndev = int(prog._mesh.devices.size)
    # a legacy checkpoint: meta has step but NO data_cursor
    with fluid.scope_guard(trainer.scope):
        fluid.io.save_checkpoint(trainer.exe,
                                 str(tmp_path / "ck" / "checkpoint_0"),
                                 trainer.main_program,
                                 scope=trainer.scope, meta={"step": 5})
    trainer._cursor = E.DataCursor(epoch=0, batch=5)   # pre-loss position
    trainer.elastic_devices_fn = lambda: jax.devices()[:4]
    trainer._elastic_recover(E.DeviceLostError("chip gone"), prog)
    assert (trainer._resume_cursor.epoch,
            trainer._resume_cursor.batch) == (0, 5)


def test_graceful_shutdown_skips_duplicate_interval_save(tmp_path):
    """SIGTERM landing on a step that just wrote its interval checkpoint
    must not write a second byte-identical serial in the grace window."""
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=2,
                                          max_num_checkpoints=0)

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent) and ev.step == 1:
            graceful.request_shutdown("test")   # _step == 2: interval hit

    with un.guard():
        t = fluid.contrib.Trainer(_train_func,
                                  lambda: fluid.optimizer.SGD(0.1),
                                  checkpoint_config=ckpt)
        t.train(num_epochs=1, event_handler=handler,
                reader=_reader(6, rows=8), feed_order=["x", "y"])
    assert t.interrupted
    from paddle_tpu import resilience

    assert len(resilience.iter_serials(str(tmp_path / "ck"))) == 1


def test_trainer_elastic_disabled_dies_typed(tmp_path):
    fluid.set_flags({
        "FLAGS_fault_plan": "device_lost:@2:RuntimeError",
        "FLAGS_elastic": "0",
    })
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=2, sharded=True)
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
    with pytest.raises(E.DeviceLostError):
        trainer.train(num_epochs=1, event_handler=lambda ev: None,
                      reader=_reader(4), feed_order=["x", "y"])


def test_trainer_watchdog_hang_on_parallel_step_escalates(tmp_path):
    """Composition with the PR 6 watchdog: a WatchdogTimeout whose
    section is the parallel step enters the elastic path; any other
    section re-raises untouched."""
    from paddle_tpu.resilience.distributed import WatchdogTimeout

    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=1, sharded=True)
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
        prog = fluid.CompiledProgram(trainer.main_program) \
            .with_data_parallel(loss_name=trainer.loss.name)
    trainer._full_dp = int(prog._mesh.shape.get("dp", 1))
    trainer._full_ndev = int(prog._mesh.devices.size)
    trainer._train_mesh = prog._mesh
    import jax

    trainer.elastic_devices_fn = lambda: jax.devices()[:4]
    # nothing checkpointed yet -> PT614 escalation even for the right
    # section (recovery is never silent: a typed refusal, not a wedge)
    with pytest.raises(E.ElasticRescaleError) as ei:
        trainer._elastic_recover(WatchdogTimeout("parallel_step", 1.0),
                                 prog)
    assert ei.value.code == "PT614"
    # a compile-section hang is NOT a device loss: re-raised untouched
    with pytest.raises(WatchdogTimeout):
        trainer._elastic_recover(WatchdogTimeout("compile", 1.0), prog)
    # with a verified checkpoint present the same escalation recovers
    trainer._save_checkpoint()
    prog2 = trainer._elastic_recover(
        WatchdogTimeout("parallel_step", 1.0), prog)
    assert dict(prog2._mesh.shape) == {"dp": 4}
    assert trainer.elastic_events[-1]["cause"] == "WatchdogTimeout"


def test_trainer_rescale_budget_escalates(tmp_path):
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=1, sharded=True)
    fluid.set_flags({"FLAGS_elastic_max_rescales": "1"})
    with un.guard():
        trainer = fluid.contrib.Trainer(
            _train_func, lambda: fluid.optimizer.SGD(0.1),
            checkpoint_config=ckpt, parallel=True)
        prog = fluid.CompiledProgram(trainer.main_program) \
            .with_data_parallel(loss_name=trainer.loss.name)
    trainer._full_dp = int(prog._mesh.shape.get("dp", 1))
    trainer._full_ndev = int(prog._mesh.devices.size)
    trainer._save_checkpoint()
    import jax

    trainer.elastic_devices_fn = lambda: jax.devices()[:4]
    trainer._elastic_recover(E.DeviceLostError("first"), prog)
    with pytest.raises(E.ElasticRescaleError) as ei:
        trainer._elastic_recover(E.DeviceLostError("second"), prog)
    assert ei.value.code == "PT612"


# ---------------------------------------------------------------------------
# 5. deterministic data resume (cursor + seeded shuffle)
# ---------------------------------------------------------------------------

def test_data_cursor_roundtrip():
    c = E.DataCursor(epoch=2, batch=7, reader_state={"seed": 5,
                                                     "epoch": 3})
    c2 = E.DataCursor.from_dict(c.to_dict())
    assert (c2.epoch, c2.batch) == (2, 7)
    assert c2.reader_state == {"seed": 5, "epoch": 3}
    assert E.DataCursor.from_dict(None) is None
    assert E.DataCursor.from_dict("junk") is None


def test_seeded_shuffle_is_deterministic_per_epoch():
    from paddle_tpu.reader import shuffle

    base = lambda: iter(range(20))  # noqa: E731
    a = shuffle(base, 8, seed=42)
    b = shuffle(base, 8, seed=42)
    ep0_a, ep1_a = list(a()), list(a())
    ep0_b, ep1_b = list(b()), list(b())
    assert ep0_a == ep0_b and ep1_a == ep1_b
    assert ep0_a != ep1_a           # epochs differ from each other
    assert sorted(ep0_a) == list(range(20))
    # unseeded keeps the legacy reader (no resume state)
    legacy = shuffle(base, 8)
    assert not hasattr(legacy, "state_dict")


def test_cursor_realigns_shuffle_epoch_on_resume():
    """Mid-epoch capture: the reader has already advanced its epoch
    counter past the epoch being re-entered; apply_to_reader realigns so
    the resumed epoch replays the SAME order."""
    from paddle_tpu.reader import shuffle

    base = lambda: iter(range(12))  # noqa: E731
    r = shuffle(base, 6, seed=9)
    epoch1_order = (list(r()), list(r()))[1]   # play epochs 0 and 1
    # crash "mid epoch 1": cursor captured after 3 batches of epoch 1
    cur = E.DataCursor.capture(epoch=1, batch=3, reader=r)
    # fresh process: new reader, state epoch starts at 0
    r2 = shuffle(base, 6, seed=9)
    cur2 = E.DataCursor.from_dict(cur.to_dict())
    cur2.apply_to_reader(r2)
    assert list(r2()) == epoch1_order   # epoch 1 replays identically


def test_trainer_checkpoints_data_cursor(tmp_path):
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=3)
    with un.guard():
        t1 = fluid.contrib.Trainer(_train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
        t1.train(num_epochs=1, event_handler=lambda ev: None,
                 reader=_reader(5, rows=8), feed_order=["x", "y"])
    # end-of-epoch save: cursor points at the next epoch's first batch
    with un.guard():
        t2 = fluid.contrib.Trainer(_train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
    assert t2._step == 5
    assert t2._resume_cursor is not None
    assert (t2._resume_cursor.epoch, t2._resume_cursor.batch) == (1, 0)


def test_trainer_resume_fast_forwards_mid_epoch(tmp_path):
    """Kill-after-checkpoint resume: the second incarnation consumes
    exactly the batches after the cursor (positional fast-forward)."""
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=2,
                                          max_num_checkpoints=0)
    consumed = []

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            consumed.append((ev.epoch, ev.step))

    class _Stop(Exception):
        pass

    def killing_handler(ev):
        handler(ev)
        if isinstance(ev, fluid.contrib.EndStepEvent) and ev.step == 2:
            raise _Stop()   # die AFTER step 2 (checkpoint at step 2)

    with un.guard():
        t1 = fluid.contrib.Trainer(_train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
        with pytest.raises(_Stop):
            t1.train(num_epochs=1, event_handler=killing_handler,
                     reader=_reader(6, rows=8), feed_order=["x", "y"])
    consumed.clear()
    with un.guard():
        t2 = fluid.contrib.Trainer(_train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
        t2.train(num_epochs=1, event_handler=handler,
                 reader=_reader(6, rows=8), feed_order=["x", "y"])
    # checkpoint was at step 2 (cursor batch=2): resume consumes 2..5
    assert consumed == [(0, s) for s in range(2, 6)]


# ---------------------------------------------------------------------------
# 6. graceful shutdown (SIGTERM / preemption notice)
# ---------------------------------------------------------------------------

def test_trainer_graceful_shutdown_finishes_step_and_checkpoints(tmp_path):
    """An in-process shutdown request (what the SIGTERM handler issues):
    the in-flight step completes, a final checkpoint lands, train()
    returns with .interrupted set."""
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=100,
                                          max_num_checkpoints=0)
    steps = []

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            steps.append(ev.step)
            if ev.step == 1:
                graceful.request_shutdown("test")

    with un.guard():
        t = fluid.contrib.Trainer(_train_func,
                                  lambda: fluid.optimizer.SGD(0.1),
                                  checkpoint_config=ckpt)
        t.train(num_epochs=2, event_handler=handler,
                reader=_reader(6, rows=8), feed_order=["x", "y"])
    assert t.interrupted is True
    assert steps == [0, 1]           # finished the in-flight step, no more
    from paddle_tpu import resilience

    serials = resilience.iter_serials(str(tmp_path / "ck"))
    assert len(serials) == 1         # the final shutdown checkpoint
    meta = fluid.io.load_checkpoint(t.exe, serials[0][1],
                                    main_program=t.main_program,
                                    scope=fluid.Scope())
    assert meta["step"] == 2
    assert meta["data_cursor"]["batch"] == 2


_SIGTERM_SCRIPT = r"""
import os, signal, sys
import numpy as np
sys.path.insert(0, {repo!r})
import paddle_tpu as fluid

def train_func():
    x = fluid.layers.data("x", shape=[6], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

def reader():
    for i in range(50):
        rng = np.random.RandomState(i)
        x = rng.rand(8, 6).astype(np.float32)
        y = x.sum(axis=1, keepdims=True).astype(np.float32)
        yield [(x[j], y[j]) for j in range(8)]

ckpt = fluid.contrib.CheckpointConfig({ckpt_dir!r}, step_interval=1000,
                                      max_num_checkpoints=0)
trainer = fluid.contrib.Trainer(train_func,
                                lambda: fluid.optimizer.SGD(0.1),
                                checkpoint_config=ckpt)

def handler(ev):
    if isinstance(ev, fluid.contrib.EndStepEvent) and ev.step == 2:
        # the preemption notice arrives mid-training
        os.kill(os.getpid(), signal.SIGTERM)

trainer.train(num_epochs=1, event_handler=handler, reader=reader,
              feed_order=["x", "y"])
assert trainer.interrupted, "SIGTERM did not unwind train()"
print("GRACEFUL_EXIT step=%d" % trainer._step)
"""


def test_trainer_sigterm_self_delivered_exits_zero(tmp_path):
    """The satellite's end-to-end proof: a self-delivered SIGTERM makes
    the process finish the in-flight step, write a final verified
    checkpoint and exit 0."""
    ckpt_dir = str(tmp_path / "ck")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c",
         _SIGTERM_SCRIPT.format(repo=REPO, ckpt_dir=ckpt_dir)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "GRACEFUL_EXIT" in proc.stdout
    from paddle_tpu import resilience

    serials = resilience.iter_serials(ckpt_dir)
    assert len(serials) == 1
    # the final checkpoint VERIFIES (manifest complete, nothing torn)
    resilience.verify_checkpoint(serials[0][1])


def test_serving_engine_drains_on_shutdown_request():
    """ServingEngine + install_preemption_handler: a shutdown request
    drains the queue (every request reaches its terminal outcome) and
    flips ready() false."""
    from paddle_tpu import serving

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[5], dtype="float32")
            pred = fluid.layers.fc(x, 3)
        infer = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    eng = serving.ServingEngine(
        infer, feed_names=["x"], fetch_list=[pred.name], scope=scope,
        executor=exe, config=serving.ServingConfig(max_batch=4))
    eng.start()
    eng.install_preemption_handler()
    futs = [eng.submit({"x": np.random.RandomState(i)
                        .rand(1, 5).astype(np.float32)})
            for i in range(6)]
    graceful.request_shutdown("test")
    # the drain-stop runs in a daemon thread; every future must settle
    for f in futs:
        r = f.result(timeout=30)
        assert np.asarray(r[0]).shape == (1, 3)
    deadline = time.monotonic() + 30
    while eng.ready() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not eng.ready()
    acct = eng.accounting()
    assert acct["exact"] and acct["pending"] == 0
    # this test dispatches full batches; clear the registry so absolute
    # histogram assertions elsewhere (serving occupancy max) see a
    # fresh window — the test_monitor.py idiom
    monitor.reset()


def test_retry_backoff_wakes_on_thread_stop_event():
    """Satellite fix: a backoff in progress aborts (typed) when the
    thread's stop event fires instead of sleeping out the delay."""
    ev = threading.Event()
    set_thread_stop_event(ev)
    threading.Timer(0.15, ev.set).start()
    pol = RetryPolicy(max_attempts=5, base_delay=30.0, max_delay=30.0,
                      timeout=None)
    t0 = time.monotonic()
    with pytest.raises(RetryExhaustedError):
        call_with_retry("compile",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("transient")), policy=pol)
    assert time.monotonic() - t0 < 5.0   # not the 30s backoff


def test_retry_backoff_wakes_on_global_shutdown():
    threading.Timer(0.15, graceful.request_shutdown, args=("t",)).start()
    pol = RetryPolicy(max_attempts=5, base_delay=30.0, max_delay=30.0,
                      timeout=None)
    t0 = time.monotonic()
    with pytest.raises(RetryExhaustedError):
        call_with_retry("compile",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("transient")), policy=pol)
    assert time.monotonic() - t0 < 5.0


def test_signal_handlers_are_refcounted():
    """A scoped owner (Trainer.train) uninstalling must not tear down
    another owner's (ServingEngine) preemption handler."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    try:
        assert graceful.install_signal_handlers()   # engine's hold
        ours = signal.getsignal(signal.SIGTERM)
        assert ours is not prev
        assert graceful.install_signal_handlers()   # trainer's hold
        graceful.uninstall_signal_handlers()        # trainer exits
        assert signal.getsignal(signal.SIGTERM) is ours  # engine's stays
        graceful.uninstall_signal_handlers()        # last owner exits
        assert signal.getsignal(signal.SIGTERM) is prev
    finally:
        graceful.uninstall_signal_handlers()
        signal.signal(signal.SIGTERM, prev)


def test_divergence_restore_rewinds_data_cursor(tmp_path):
    """_recover_from_checkpoint (the divergence-restore walk) must adopt
    the checkpoint's data cursor so the step loop rewinds the data
    stream with the state — the same exactly-once contract as the
    elastic path."""
    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=2,
                                          max_num_checkpoints=0)
    with un.guard():
        t = fluid.contrib.Trainer(_train_func,
                                  lambda: fluid.optimizer.SGD(0.1),
                                  checkpoint_config=ckpt)
        t.train(num_epochs=1, event_handler=lambda ev: None,
                reader=_reader(4, rows=8), feed_order=["x", "y"])
    t._resume_cursor = None
    assert t._recover_from_checkpoint()
    assert t._restored_step == t._step
    assert t._resume_cursor is not None
    # newest serial is the end-of-epoch save: next batch = epoch 1/batch 0
    assert (t._resume_cursor.epoch, t._resume_cursor.batch) == (1, 0)


def test_graceful_on_shutdown_runs_late_registrations():
    graceful.request_shutdown("early")
    ran = threading.Event()
    unregister = graceful.on_shutdown(ran.set)
    assert ran.wait(5.0)   # registered after the fact: runs immediately
    unregister()
