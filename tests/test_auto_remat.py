"""FLAGS_auto_recompute — the Pass 6 auto-remat chooser (analysis/remat.py):
memory_plan-scored checkpoint selection over a rebuilt program, wired into
Executor.run / run_chained. Bit-identical training is the hard contract
(tests/test_recompute.py proves it for manual checkpoints; these prove the
automatic chooser inherits it), plus budget fitting, inference/manual
programs passing through untouched, and compile-cache separation.

Also hosts the dtype-truncation regression test for this round's satellite:
ops that request 64-bit dtypes from jax must canonicalize via jnp_dtype
BEFORE the jnp call, or every traced op warns under disabled x64."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.analysis.remat import (auto_recompute_program,
                                       remat_candidates)

WIDTH, DEPTH, BATCH = 128, 8, 256


def _build(width=WIDTH, depth=DEPTH, seed=11):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[width], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = x
            acts = []
            for _ in range(depth):
                h = fluid.layers.fc(h, width, act="relu")
                acts.append(h.name)
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    main.random_seed = seed
    return main, startup, loss, acts


def _feed(width=WIDTH, batch=BATCH):
    rng = np.random.RandomState(0)
    return {"x": rng.randn(batch, width).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}


@pytest.fixture
def _flags():
    prev = fluid.get_flags(["FLAGS_auto_recompute", "FLAGS_remat_budget_mb"])
    yield
    fluid.set_flags(prev)


def _train(auto, chained=False, steps=5, fetch_extra=None):
    main, startup, loss, acts = _build()
    fluid.set_flags({"FLAGS_auto_recompute": auto})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _feed()
    fetches = [loss.name] + (fetch_extra(acts) if fetch_extra else [])
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        if chained:
            outs = exe.run_chained(main, feed=feed, fetch_list=fetches,
                                   steps=steps, scope=scope)
            out = [float(np.asarray(outs[0]).reshape(-1)[i])
                   for i in range(steps)]
        else:
            for _ in range(steps):
                vals = exe.run(main, feed=feed, fetch_list=fetches)
                out.append(float(np.asarray(vals[0]).reshape(-1)[0]))
    ran = next((p for k, p in exe._remat_cache.items()
                if k[0][0] == main._serial), main)
    segs = sum(1 for op in ran.global_block.ops
               if op.type == "recompute_segment")
    return out, segs, exe, main, ran


def test_candidates_found():
    main, _, loss, _ = _build()
    cands = remat_candidates(main, batch_size=BATCH)
    assert len(cands) >= DEPTH  # at least one seam per fc layer
    for c in cands:
        assert c.nbytes > 0
        assert main.global_block.has_var(c.var_name)


def test_auto_remat_bit_identical_run(_flags):
    plain, seg0, _, _, _ = _train(False)
    remat, seg1, _, _, _ = _train(True)
    assert seg0 == 0
    assert seg1 > 0
    assert plain == remat  # bit-identical, not allclose
    assert plain[0] != plain[-1]  # params actually updated


def test_auto_remat_bit_identical_chained(_flags):
    plain, _, _, _, _ = _train(False, chained=True)
    remat, segs, _, _, _ = _train(True, chained=True)
    assert segs > 0
    assert plain == remat


def test_predicted_peak_drops(_flags):
    _, segs, exe, main, ran = _train(True)
    assert segs > 0 and ran is not main
    kw = dict(feed_names=["x", "y"], batch_size=BATCH)
    assert ran.memory_plan(**kw).peak_bytes < main.memory_plan(
        **kw).peak_bytes


def test_budget_respected():
    main, _, loss, _ = _build()
    free = auto_recompute_program(main, feed_names=["x", "y"],
                                  fetch_names=[loss.name], batch_size=BATCH)
    assert free.applied and free.n_segments > 0
    # a budget between the best-achievable and plain peaks must be honored
    budget_mb = max(1, (free.peak_after >> 20) + 1 +
                    ((free.peak_before - free.peak_after) >> 21))
    dec = auto_recompute_program(main, feed_names=["x", "y"],
                                 fetch_names=[loss.name], batch_size=BATCH,
                                 budget_mb=budget_mb)
    assert dec.applied
    assert dec.peak_after <= budget_mb << 20
    # cheapest-first: the fitting set should checkpoint at least as densely
    # as the unconstrained sqrt(N) pick
    assert len(dec.checkpoints) >= len(free.checkpoints)
    # a budget the PLAIN program already fits must refuse outright — the
    # cheapest fitting set is no checkpoints at all
    roomy = auto_recompute_program(
        main, feed_names=["x", "y"], fetch_names=[loss.name],
        batch_size=BATCH, budget_mb=(free.peak_before >> 20) + 64)
    assert not roomy.applied and "already fits" in roomy.reason


def test_inference_program_untouched(_flags):
    main, _, loss, _ = _build()
    infer = main.clone(for_test=True)
    fluid.set_flags({"FLAGS_auto_recompute": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    out = exe._maybe_auto_remat(infer, _feed(), [loss.name])
    assert out is infer  # no backward ops -> pass-through, same object
    dec = auto_recompute_program(infer, feed_names=["x", "y"],
                                 fetch_names=[loss.name], batch_size=BATCH)
    assert not dec.applied and "no backward" in dec.reason


def test_manual_recompute_program_refused():
    """A program the user already checkpointed via RecomputeOptimizer must
    pass through untouched — double-remat would recompute recomputes."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[32], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            h = x
            ckpts = []
            for i in range(4):
                h = fluid.layers.fc(h, 32, act="relu")
                if i % 2:
                    ckpts.append(h)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(h, 1), y))
            opt = fluid.optimizer.RecomputeOptimizer(
                fluid.optimizer.Adam(learning_rate=0.01))
            opt._set_checkpoints(ckpts)
            opt.minimize(loss)
    dec = auto_recompute_program(main, feed_names=["x", "y"],
                                 fetch_names=[loss.name], batch_size=64)
    assert not dec.applied and "recompute segments" in dec.reason


def test_run_chained_cache_separation(_flags):
    """One executor, same program, flag flipped between dispatches: the
    remat variant must compile into its OWN cache entry (fresh program
    serial), never alias the plain one."""
    main, startup, loss, _ = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    feed = _feed()

    def chained(auto):
        fluid.set_flags({"FLAGS_auto_recompute": auto})
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            # startup via a FRESH executor: the shared one's seed counter
            # advances per dispatch, which would re-roll the param init
            # between the plain and remat passes
            fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
            outs = exe.run_chained(main, feed=feed, fetch_list=[loss.name],
                                   steps=4, scope=scope)
        return [float(np.asarray(outs[0]).reshape(-1)[i]) for i in range(4)]

    plain = chained(False)
    remat = chained(True)
    assert plain == remat
    chained_serials = {k[1][0] for k in exe._cache if k[0] == "chained"}
    assert main._serial in chained_serials
    assert len(chained_serials) == 2  # plain + remat entries, disjoint


def test_fetching_intermediate_survives_auto_remat(_flags):
    """Transparent remat must never break a fetch: fetched activations are
    kept as segment outputs (extra_live), unlike the manual API where
    demotion is the documented trade."""
    def fetch_mid(acts):
        return [acts[len(acts) // 2]]

    plain, _, _, _, _ = _train(False, fetch_extra=fetch_mid)
    remat, segs, _, _, _ = _train(True, fetch_extra=fetch_mid)
    assert segs > 0
    assert plain == remat


def test_remat_rng_ops_replay(_flags):
    """Dropout inside a segment replays bit-identically (uid-keyed PRNG)."""
    def build_do():
        with un.guard():
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data("x", shape=[WIDTH], dtype="float32")
                y = fluid.layers.data("y", shape=[1], dtype="float32")
                h = x
                for _ in range(6):
                    h = fluid.layers.fc(h, WIDTH, act="relu")
                    h = fluid.layers.dropout(h, 0.3)
                loss = fluid.layers.mean(fluid.layers.square_error_cost(
                    fluid.layers.fc(h, 1), y))
                fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
        main.random_seed = 5
        return main, startup, loss

    feed = _feed()

    def train(auto):
        main, startup, loss = build_do()
        fluid.set_flags({"FLAGS_auto_recompute": auto})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        out = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(4):
                (lv,) = exe.run(main, feed=feed, fetch_list=[loss.name])
                out.append(float(np.asarray(lv).reshape(-1)[0]))
        segs = sum(1 for p in exe._remat_cache.values()
                   for op in p.global_block.ops
                   if op.type == "recompute_segment")
        return out, segs

    plain, _ = train(False)
    remat, segs = train(True)
    assert segs > 0
    assert plain == remat


def test_changed_fetch_list_gets_its_own_transform(_flags):
    """The remat cache is keyed on the fetch list: a transform built for
    fetch=[loss] keeps only loss alive across segments, so a later run
    fetching a mid activation must trigger its own rebuild instead of
    hitting a cached program that demoted that activation."""
    main, startup, loss, acts = _build()
    fluid.set_flags({"FLAGS_auto_recompute": 1})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = _feed()
    mid = acts[len(acts) // 2]
    with fluid.scope_guard(scope):
        exe.run(startup)
        (l1,) = exe.run(main, feed=feed, fetch_list=[loss.name])
        l2, mid_val = exe.run(main, feed=feed, fetch_list=[loss.name, mid])
    assert np.isfinite(np.asarray(mid_val)).all()
    assert np.asarray(mid_val).shape == (BATCH, WIDTH)
    # two distinct transforms were cached for MAIN (one per fetch list)
    assert len({k[3] for k in exe._remat_cache
                if k[0][0] == main._serial}) == 2


def test_bert_tiny_bit_identical(_flags):
    """The acceptance shape: a BERT training program (embeddings with tied
    weights, attention, layer_norm, dropout, AMP policy) auto-remats with
    no user checkpoints, drops the predicted peak, and trains
    bit-identically."""
    from paddle_tpu.models.bert import BertConfig, build_bert_pretrain

    cfg = BertConfig.tiny()
    seq, batch = 32, 8
    rng = np.random.RandomState(0)
    feed = {
        "src_ids": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "pos_ids": np.tile(np.arange(seq), (batch, 1)),
        "sent_ids": np.zeros((batch, seq)),
        "input_mask": np.ones((batch, seq), np.float32),
        "mask_label": rng.randint(0, cfg.vocab_size, (batch, seq)),
        "next_sent_label": rng.randint(0, 2, (batch, 1)),
    }
    for k in ("src_ids", "pos_ids", "sent_ids", "mask_label",
              "next_sent_label"):
        feed[k] = feed[k].astype(np.int64)

    def train(auto):
        with un.guard():
            model = build_bert_pretrain(cfg, seq_len=seq, amp=True)
        model["main"].random_seed = 3
        fluid.set_flags({"FLAGS_auto_recompute": auto})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        out = []
        with fluid.scope_guard(scope):
            exe.run(model["startup"])
            for _ in range(3):
                (lv,) = exe.run(model["main"], feed=feed,
                                fetch_list=[model["loss"].name])
                out.append(np.asarray(lv).tobytes())
        ran = next((p for k, p in exe._remat_cache.items()
                    if k[0][0] == model["main"]._serial), model["main"])
        segs = sum(1 for op in ran.global_block.ops
                   if op.type == "recompute_segment")
        return out, segs, ran, model["main"]

    plain, seg0, _, _ = train(False)
    remat, seg1, ran, main = train(True)
    assert seg0 == 0 and seg1 > 0
    assert plain == remat  # loss bit patterns, step for step
    kw = dict(feed_names=sorted(feed), batch_size=batch)
    assert ran.memory_plan(**kw).peak_bytes < main.memory_plan(
        **kw).peak_bytes


# ---------------------------------------------------------------------------
# satellite: dtype-truncation warnings are gone at every jnp boundary
# ---------------------------------------------------------------------------

def test_no_dtype_truncation_warnings():
    """cast / fill_constant / sequence_mask / one_hot requesting int64 must
    canonicalize via jnp_dtype before the jnp call: with x64 disabled the
    old np_dtype path emitted one UserWarning per traced op (bench/CI log
    spam). simplefilter('error') turns any regression into a hard fail."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            ids = fluid.layers.data("ids", shape=[1], dtype="int64")
            c = fluid.layers.cast(x, "int64")              # astype path
            fc64 = fluid.layers.fill_constant([8], "int64", 3)
            oh = fluid.layers.one_hot(ids, depth=4)
            sm = fluid.layers.sequence_mask(
                fluid.layers.cast(x, "int32"), maxlen=4, dtype="int64")
            s = (fluid.layers.cast(c, "float32")
                 + fluid.layers.cast(fc64, "float32")
                 + fluid.layers.reduce_mean(oh)
                 + fluid.layers.reduce_mean(
                     fluid.layers.cast(sm, "float32")))
            loss = fluid.layers.mean(s)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.zeros((4, 8), np.float32),
            "ids": np.zeros((4, 1), np.int64)}
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with fluid.scope_guard(scope):
            exe.run(startup)
            (out,) = exe.run(main, feed=feed, fetch_list=[loss.name])
    assert np.isfinite(np.asarray(out)).all()


def test_no_truncation_warning_on_argmax_astype_path():
    """The astype flavour of the BENCH-tail spam (ISSUE 13 satellite):
    argmax/top_k cast their indices to int64 via ``Array.astype`` — with a
    failed-open x64 probe that emitted one UserWarning per traced op."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            am = fluid.layers.arg_max(x, axis=1)
            fc64 = fluid.layers.fill_constant([4], "int64", 3)
            s = (fluid.layers.cast(am, "float32")
                 + fluid.layers.reduce_mean(
                     fluid.layers.cast(fc64, "float32")))
            outv = fluid.layers.mean(s)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with fluid.scope_guard(scope):
            exe.run(startup)
            (out,) = exe.run(main, feed={"x": np.zeros((4, 8), np.float32)},
                             fetch_list=[outv.name])
    assert np.isfinite(np.asarray(out)).all()


def test_jnp_dtype_survives_broken_introspection(monkeypatch):
    """The axon-bench failure mode (ISSUE 13 satellite): on that backend's
    jax build ``jax.dtypes.canonicalize_dtype`` raised AND
    ``jax.config.jax_enable_x64`` was an always-truthy holder object, so
    jnp_dtype failed OPEN to int64 and every traced fill/astype warned.
    The behavioural probe must decide correctly even with both
    introspection paths broken."""
    import jax

    from paddle_tpu.core import types as t

    monkeypatch.setattr(t, "_X64_ACTIVE", None)

    def boom(*a, **k):
        raise TypeError("simulated: no canonicalize_dtype on this build")

    monkeypatch.setattr(jax.dtypes, "canonicalize_dtype", boom)
    try:
        assert t.jnp_dtype("int64") == np.dtype("int32")
        assert t.jnp_dtype("float64") == np.dtype("float32")
        assert t.jnp_dtype("uint64") == np.dtype("uint32")
        # narrow + float dtypes pass through untouched
        assert t.jnp_dtype("int32") == np.dtype("int32")
        assert t.jnp_dtype("bfloat16").name == "bfloat16"
    finally:
        t._X64_ACTIVE = None  # drop the probe memo poisoned by this test
