"""paddle_tpu.resilience: crash-safe checkpoints (atomic publish + manifest
verification + torn-checkpoint fallback), deterministic fault injection,
retry/backoff at the transient executor sites, and FLAGS_nan_inf_policy
step degradation. The real-kill end-to-end lives in tools/chaos_check.py
(CI); these tests cover the same machinery in-process."""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, resilience
from paddle_tpu.resilience import (CheckpointCorruptError, FaultPlan,
                                   RetryExhaustedError, call_with_retry,
                                   fault_plan_guard)


@pytest.fixture
def flags_guard():
    """Snapshot/restore set_flags overrides so a failing test can't leak
    resilience flags into the rest of the suite."""
    from paddle_tpu import flags as F

    saved = dict(F._overrides)
    yield fluid.set_flags
    F._overrides.clear()
    F._overrides.update(saved)


def _build_regression():
    x = fluid.layers.data("x", shape=[4], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _feed(batch=8, nan=False):
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 4).astype(np.float32)
    if nan:
        x = np.full_like(x, np.nan)
    return {"x": x, "y": rng.rand(batch, 1).astype(np.float32)}


def _scope_image(scope):
    return {n: np.asarray(scope.find_var(n)).copy() for n in scope.vars}


def _scopes_equal(a, b):
    return set(a) == set(b) and all(
        np.array_equal(a[n], b[n], equal_nan=True) for n in a)


class _Session:
    """One built regression program + executor + initialized scope."""

    def __init__(self):
        self.guard = un.guard()
        self.guard.__enter__()
        self.main, self.startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(self.main, self.startup):
            self.loss = _build_regression()
        self.exe = fluid.Executor(fluid.CPUPlace())
        self.scope = fluid.Scope()
        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)
        self.guard.__exit__(None, None, None)

    def run(self, feed, **kw):
        with fluid.scope_guard(self.scope):
            return self.exe.run(self.main, feed=feed,
                                fetch_list=[self.loss], **kw)

    def save(self, dirname, meta=None):
        with fluid.scope_guard(self.scope):
            fluid.io.save_checkpoint(self.exe, dirname, self.main,
                                     scope=self.scope, meta=meta or {})

    def load(self, dirname, **kw):
        with fluid.scope_guard(self.scope):
            return fluid.io.load_checkpoint(self.exe, dirname, self.main,
                                            scope=self.scope, **kw)


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------

def test_checkpoint_manifest_and_verify_roundtrip(tmp_path):
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.run(_feed())
    s.save(ck, meta={"step": 1})
    manifest = resilience.verify_checkpoint(ck)
    # plain checkpoints stay format 1 (rollback-loadable by older builds);
    # only the sharded layout (resilience.distributed) stamps 2
    assert manifest["format_version"] == 1
    assert resilience.FORMAT_VERSION >= manifest["format_version"]
    assert set(manifest["files"]) == {"ckpt.npz", "meta.json"}
    assert all("sha256" in f and "bytes" in f
               for f in manifest["files"].values())
    assert manifest["framework_version"] == fluid.__version__
    assert s.load(ck)["step"] == 1


def test_tampered_blob_is_detected_not_loaded(tmp_path):
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, meta={"step": 3})
    blob = os.path.join(ck, "ckpt.npz")
    raw = bytearray(open(blob, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(blob, "wb") as f:
        f.write(raw)
    before = _scope_image(s.scope)
    with pytest.raises(CheckpointCorruptError) as ei:
        s.load(ck)
    assert ei.value.code == "PT603"
    # verification failed BEFORE loading: not a byte reached the scope
    assert _scopes_equal(before, _scope_image(s.scope))


def test_corruption_codes_name_what_failed(tmp_path):
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck)
    # missing file listed in the manifest
    os.remove(os.path.join(ck, "meta.json"))
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT602" and "meta.json" in str(ei.value)
    # unreadable manifest
    with open(os.path.join(ck, "manifest.json"), "w") as f:
        f.write("{ not json")
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT601"
    # no manifest at all (torn pre-manifest write)
    os.remove(os.path.join(ck, "manifest.json"))
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT600"
    # future format version
    s.save(ck)
    mpath = os.path.join(ck, "manifest.json")
    m = json.load(open(mpath))
    m["format_version"] = resilience.FORMAT_VERSION + 1
    with open(mpath, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointCorruptError) as ei:
        resilience.verify_checkpoint(ck)
    assert ei.value.code == "PT604"


def test_failed_save_preserves_previous_checkpoint(tmp_path):
    """An injected fault mid-write (the exception flavour of the chaos
    kill) must leave the previously published checkpoint intact and leak
    no temp dir."""
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, meta={"step": 1})
    with fault_plan_guard("ckpt_write:1:RuntimeError"):
        with pytest.raises(RuntimeError):
            s.save(ck, meta={"step": 2})
    resilience.verify_checkpoint(ck)
    assert s.load(ck)["step"] == 1
    assert [p for p in os.listdir(str(tmp_path)) if ".tmp." in p] == []


def test_save_checkpoint_over_nonempty_dir_replaces_atomically(tmp_path):
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.save(ck, meta={"step": 1})
    s.run(_feed())
    s.save(ck, meta={"step": 2})
    resilience.verify_checkpoint(ck)
    assert s.load(ck)["step"] == 2
    assert [p for p in os.listdir(str(tmp_path)) if "replaced" in p] == []


def test_dirname_exists_as_file_raises_clear_error(tmp_path):
    s = _Session()
    as_file = tmp_path / "not_a_dir"
    as_file.write_text("occupied")
    with pytest.raises(ValueError, match="exists as a FILE"):
        s.save(str(as_file))
    with pytest.raises(ValueError, match="exists as a FILE"):
        with fluid.scope_guard(s.scope):
            fluid.io.save_persistables(s.exe, str(as_file), s.main,
                                       scope=s.scope)
    with pytest.raises(ValueError, match="exists as a FILE"):
        with fluid.scope_guard(s.scope):
            fluid.io.save_inference_model(str(as_file), ["x"], [s.loss],
                                          s.exe, main_program=s.main,
                                          scope=s.scope)


# ---------------------------------------------------------------------------
# Trainer recovery walk
# ---------------------------------------------------------------------------

def _train_func():
    x = fluid.layers.data("x", shape=[4], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1, name="fit")
    return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))


def _make_trainer(ckpt_dir, max_num=3):
    cfg = fluid.contrib.CheckpointConfig(str(ckpt_dir),
                                         max_num_checkpoints=max_num)
    with un.guard():
        return fluid.contrib.Trainer(_train_func,
                                     lambda: fluid.optimizer.SGD(0.05),
                                     checkpoint_config=cfg)


def test_trainer_tolerates_empty_and_garbage_ckpt_dir(tmp_path):
    d = tmp_path / "ckpts"
    # missing dir
    t = _make_trainer(d)
    assert t._step == 0
    # garbage entries: stray file, non-numeric serial, torn temp dir
    d.mkdir(exist_ok=True)
    (d / "README").write_text("junk")
    (d / "checkpoint_notanumber").mkdir()
    (d / ".checkpoint_7.tmp.123").mkdir()
    (d / "checkpoint_3_old").mkdir()
    t2 = _make_trainer(d)
    assert t2._step == 0 and t2._serials() == []


def test_trainer_falls_back_past_torn_checkpoint(tmp_path):
    d = tmp_path / "ckpts"
    t = _make_trainer(d)
    t._step = 7
    t._save_checkpoint()              # checkpoint_0, verified
    good = {n: np.asarray(t.scope.find_var(n)).copy()
            for n in t.scope.vars}
    # newest serial is torn: blobs but no integrity manifest (what a kill
    # between blob write and manifest/rename leaves if an old non-atomic
    # writer had published it)
    torn = d / "checkpoint_1"
    torn.mkdir()
    (torn / "ckpt.npz").write_bytes(b"\x00\x01garbage")
    (torn / "meta.json").write_text('{"step": 999}')
    before = monitor.metric_value("trainer_ckpt_fallback_total",
                                  default=0.0, code="PT600")
    t2 = _make_trainer(d)
    assert t2._step == 7, "must resume from checkpoint_0, not the torn 1"
    after = monitor.metric_value("trainer_ckpt_fallback_total",
                                 default=0.0, code="PT600")
    assert after == before + 1
    for n, v in good.items():
        got = t2.scope.find_var(n)
        if got is not None:
            np.testing.assert_array_equal(np.asarray(got), v)


def test_recovery_falls_back_to_legacy_checkpoint_when_nothing_verifies(
        tmp_path):
    """Upgrade path: a dir holding only pre-resilience checkpoints
    (manifest without the 'files' integrity section) must still resume —
    unverified, loudly — instead of silently restarting at step 0. A
    verified serial always wins over a NEWER legacy-shaped one (that one
    is indistinguishable from a torn write)."""
    d = tmp_path / "ckpts"
    t = _make_trainer(d)
    t._step = 11
    t._save_checkpoint()              # checkpoint_0
    # strip the integrity section: exactly what the old writer produced
    mpath = d / "checkpoint_0" / "manifest.json"
    m = json.load(open(mpath))
    del m["files"]
    with open(mpath, "w") as f:
        json.dump(m, f)
    t2 = _make_trainer(d)
    assert t2._step == 11, "legacy checkpoint must load as last resort"
    # but once a verified serial exists, a newer legacy dir is skipped
    t2._save_checkpoint()             # checkpoint_1, verified, step 11
    torn = d / "checkpoint_5"
    torn.mkdir()
    (torn / "ckpt.npz").write_bytes(b"junk")
    t3 = _make_trainer(d)
    assert t3._step == 11
    assert t3._load_latest() == 1


def test_shape_mismatch_load_leaves_scope_untouched(tmp_path):
    """A checkpoint that verifies but cannot load (program changed shape)
    must not half-mutate the scope: validation happens before the first
    set_var."""
    s = _Session()
    ck = str(tmp_path / "checkpoint_0")
    s.run(_feed())
    s.save(ck)
    # tamper the recorded shape of ONE var inside the npz-declared program
    # contract by rebuilding a program with a different fc width
    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[2], dtype="float32")
        pred = fluid.layers.fc(x, 2)   # width 2, checkpoint has width 1:
        loss2 = fluid.layers.mean(    # same var names, different shapes
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss2)
        main2 = fluid.default_main_program()
        startup2 = fluid.default_startup_program()
    s2 = fluid.Scope()
    with fluid.scope_guard(s2):
        s.exe.run(startup2)
        before = _scope_image(s2)
        with pytest.raises(RuntimeError, match="shape mismatch"):
            fluid.io.load_checkpoint(s.exe, ck, main2, scope=s2)
    assert _scopes_equal(before, _scope_image(s2))


def test_trainer_rotation_keep_all_when_max_is_zero(tmp_path):
    """max_num_checkpoints<=0 keeps full history (the pre-resilience [:-0]
    slice semantics, preserved on purpose)."""
    t = _make_trainer(tmp_path / "ckpts", max_num=0)
    for step in (1, 2, 3):
        t._step = step
        t._save_checkpoint()
    assert t._serials() == [0, 1, 2]


def test_trainer_rotation_never_deletes_what_it_just_wrote(tmp_path):
    t = _make_trainer(tmp_path / "ckpts", max_num=1)
    for step in (1, 2, 3):
        t._step = step
        t._save_checkpoint()
        serials = t._serials()
        assert len(serials) == 1, serials
        assert t._load_latest() == serials[-1]
        assert t._step == step


# ---------------------------------------------------------------------------
# fault plans + retry
# ---------------------------------------------------------------------------

def test_fault_plan_parsing_and_determinism():
    plan = FaultPlan("compile:2:RuntimeError,ckpt_write:@3:kill", seed=7)
    assert set(plan.rules) == {"compile", "ckpt_write"}
    with pytest.raises(ValueError, match="unknown site"):
        FaultPlan("teleport:1:RuntimeError")
    with pytest.raises(ValueError, match="unknown action"):
        FaultPlan("compile:1:SegFault")
    with pytest.raises(ValueError, match="cannot parse"):
        FaultPlan("compile:whenever:RuntimeError")
    # probabilistic rules replay identically for the same seed
    fires = []
    for _ in range(2):
        p = FaultPlan("step:p0.5:RuntimeError", seed=13)
        seq = []
        for _ in range(20):
            try:
                p.hit("step")
                seq.append(False)
            except RuntimeError:
                seq.append(True)
        fires.append(seq)
    assert fires[0] == fires[1] and any(fires[0]) and not all(fires[0])


def test_retry_transient_then_succeed(flags_guard):
    flags_guard({"FLAGS_retry_base_delay": 0.0})
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TimeoutError("transient")
        return "done"

    before = monitor.metric_value("resilience_retries_total", default=0.0,
                                  site="device_put")
    assert call_with_retry("device_put", flaky) == "done"
    after = monitor.metric_value("resilience_retries_total", default=0.0,
                                 site="device_put")
    assert calls["n"] == 3 and after == before + 2


def test_retry_exhausted_raises_with_cause(flags_guard):
    flags_guard({"FLAGS_retry_base_delay": 0.0,
                 "FLAGS_retry_max_attempts": 2})

    def always():
        raise ConnectionError("still down")

    before = monitor.metric_value("resilience_giveups_total", default=0.0,
                                  site="compile")
    with pytest.raises(RetryExhaustedError) as ei:
        call_with_retry("compile", always)
    assert ei.value.attempts == 2
    assert isinstance(ei.value.last_error, ConnectionError)
    after = monitor.metric_value("resilience_giveups_total", default=0.0,
                                 site="compile")
    assert after == before + 1


def test_nontransient_errors_never_retry(flags_guard):
    flags_guard({"FLAGS_retry_base_delay": 0.0})
    calls = {"n": 0}

    def shape_bug():
        calls["n"] += 1
        raise ValueError("shape mismatch — a bug, not weather")

    with pytest.raises(ValueError):
        call_with_retry("compile", shape_bug)
    assert calls["n"] == 1
    # the PT* verifier error is a ValueError subclass: also never retried
    from paddle_tpu.analysis import ProgramVerificationError

    assert not resilience.is_transient(ProgramVerificationError([]))
    assert not resilience.is_transient(FloatingPointError("nan"))
    assert resilience.is_transient(RuntimeError("xla transport flake"))
    # a RuntimeError wrapper chained onto a deterministic bug (lowering's
    # "while lowering op ..." pattern) must NOT retry
    try:
        try:
            raise AttributeError("no such attr")
        except AttributeError as cause:
            raise RuntimeError("while lowering op 'x'") from cause
    except RuntimeError as wrapped:
        assert not resilience.is_transient(wrapped)


def test_executor_compile_site_retries_injected_faults(flags_guard):
    flags_guard({"FLAGS_retry_base_delay": 0.0})
    before = monitor.metric_value("resilience_retries_total", default=0.0,
                                  site="compile")
    with fault_plan_guard("compile:2:RuntimeError"):
        s = _Session()
        (lv,) = s.run(_feed())
    assert np.isfinite(np.asarray(lv)).all()
    after = monitor.metric_value("resilience_retries_total", default=0.0,
                                 site="compile")
    assert after == before + 2


def test_executor_device_put_site_retries(flags_guard):
    flags_guard({"FLAGS_retry_base_delay": 0.0})
    s = _Session()
    before = monitor.metric_value("resilience_retries_total", default=0.0,
                                  site="device_put")
    with fault_plan_guard("device_put:1:RuntimeError"):
        s.run(_feed())
    after = monitor.metric_value("resilience_retries_total", default=0.0,
                                 site="device_put")
    assert after == before + 1


def test_step_site_fault_leaves_scope_usable(flags_guard):
    s = _Session()
    s.run(_feed())
    before = _scope_image(s.scope)
    with fault_plan_guard("step:1:RuntimeError"):
        with pytest.raises(RuntimeError, match="injected"):
            s.run(_feed())
    # probe fires before donation: nothing was consumed or half-written
    assert _scopes_equal(before, _scope_image(s.scope))
    s.run(_feed())   # and the session still trains


# ---------------------------------------------------------------------------
# FLAGS_nan_inf_policy
# ---------------------------------------------------------------------------

def _nan_flags(flags_guard, policy, limit=5):
    flags_guard({"FLAGS_check_nan_inf": 1,
                 "FLAGS_nan_inf_policy": policy,
                 "FLAGS_nan_inf_max_consecutive_skips": limit})


def test_nan_policy_skip_is_bit_exact_on_run_path(flags_guard):
    s = _Session()
    s.run(_feed())
    _nan_flags(flags_guard, "skip")
    before = _scope_image(s.scope)
    skipped0 = monitor.metric_value("steps_skipped_nonfinite_total",
                                    default=0.0, path="run", policy="skip")
    out = s.run(_feed(nan=True))     # dropped, not raised
    assert not np.isfinite(np.asarray(out[0])).all()
    assert _scopes_equal(before, _scope_image(s.scope))
    assert monitor.metric_value("steps_skipped_nonfinite_total",
                                default=0.0, path="run",
                                policy="skip") == skipped0 + 1
    # a clean step afterwards still updates params
    s.run(_feed())
    assert not _scopes_equal(before, _scope_image(s.scope))


def test_nan_policy_skip_is_bit_exact_on_chained_path(flags_guard):
    s = _Session()
    s.run(_feed())
    _nan_flags(flags_guard, "skip")
    before = _scope_image(s.scope)
    with fluid.scope_guard(s.scope):
        stacked = s.exe.run_chained(s.main, feed=_feed(nan=True),
                                    fetch_list=[s.loss], steps=3)
    assert np.asarray(stacked[0]).shape[0] == 3
    assert _scopes_equal(before, _scope_image(s.scope))
    assert monitor.metric_value("steps_skipped_nonfinite_total",
                                default=0.0, path="chained",
                                policy="skip") >= 1


def test_nan_policy_skip_is_bit_exact_on_parallel_path(flags_guard):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_regression()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        prog = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(prog, feed=_feed(), fetch_list=[loss])
            _nan_flags(flags_guard, "skip")
            before = _scope_image(scope)
            exe.run(prog, feed=_feed(nan=True), fetch_list=[loss])
            assert _scopes_equal(before, _scope_image(scope))
            assert monitor.metric_value(
                "steps_skipped_nonfinite_total", default=0.0,
                path="parallel", policy="skip") >= 1
            # clean parallel step still trains
            exe.run(prog, feed=_feed(), fetch_list=[loss])
            assert not _scopes_equal(before, _scope_image(scope))


def test_nan_policy_raise_is_default_behavior(flags_guard):
    s = _Session()
    _nan_flags(flags_guard, "raise")
    with pytest.raises(FloatingPointError, match="non-finite"):
        s.run(_feed(nan=True))


def test_nan_skip_escalates_after_consecutive_trips(flags_guard):
    s = _Session()
    s.run(_feed())
    _nan_flags(flags_guard, "skip", limit=2)
    before = _scope_image(s.scope)
    s.run(_feed(nan=True))           # skip #1
    with pytest.raises(FloatingPointError, match="escalated"):
        s.run(_feed(nan=True))       # skip #2 == limit -> raise
    # even the escalation left the rolled-back state
    assert _scopes_equal(before, _scope_image(s.scope))
    # a clean step resets the consecutive counter
    s.run(_feed())
    s.run(_feed(nan=True))           # counter restarted: skip, no raise


def test_nan_zero_grad_never_escalates(flags_guard):
    s = _Session()
    s.run(_feed())
    _nan_flags(flags_guard, "zero_grad", limit=1)
    before = _scope_image(s.scope)
    for _ in range(3):
        s.run(_feed(nan=True))
    assert _scopes_equal(before, _scope_image(s.scope))
    assert monitor.metric_value("steps_skipped_nonfinite_total",
                                default=0.0, path="run",
                                policy="zero_grad") >= 3


def test_unknown_nan_policy_rejected(flags_guard):
    s = _Session()
    flags_guard({"FLAGS_check_nan_inf": 1,
                 "FLAGS_nan_inf_policy": "shrug"})
    with pytest.raises(ValueError, match="nan_inf_policy"):
        s.run(_feed())


# ---------------------------------------------------------------------------
# the shared Deadline (resilience.deadline): one implementation for retry
# budgets and serving request deadlines
# ---------------------------------------------------------------------------

def test_deadline_basics():
    from paddle_tpu.resilience import Deadline, DeadlineExceeded

    dl = Deadline(30.0, what="unit test")
    assert not dl.expired
    assert 0 < dl.remaining() <= 30.0
    dl.check()                                   # plenty of budget: no-op
    fast = Deadline(0.005, what="tiny")
    time.sleep(0.02)
    assert fast.expired and fast.remaining() < 0
    with pytest.raises(DeadlineExceeded, match="tiny"):
        fast.check()


def test_deadline_unbounded_never_expires():
    from paddle_tpu.resilience import Deadline

    for budget in (None, 0, -1.0):
        dl = Deadline(budget)
        assert dl.remaining() is None and not dl.expired
        dl.check()


def test_deadline_context_manager_flags_overrun():
    from paddle_tpu.resilience import Deadline, DeadlineExceeded

    with Deadline(30.0, what="fits"):
        pass                                     # within budget: clean
    with pytest.raises(DeadlineExceeded, match="overran"):
        with Deadline(0.005, what="overran"):
            time.sleep(0.02)
    # an in-flight exception wins over the deadline re-check
    with pytest.raises(KeyError):
        with Deadline(0.005, what="masked"):
            time.sleep(0.02)
            raise KeyError("real failure")


def test_deadline_exceeded_is_never_transient():
    from paddle_tpu.resilience import DeadlineExceeded, is_transient

    err = DeadlineExceeded("x", 1.0, 2.0)
    assert isinstance(err, TimeoutError)         # stdlib-compatible
    assert not is_transient(err), \
        "retrying an expired deadline only makes it later"


def test_retry_budget_uses_shared_deadline(flags_guard):
    """The per-site retry timeout is the SAME Deadline implementation:
    a site whose budget is spent gives up even with attempts left."""
    from paddle_tpu.resilience import RetryExhaustedError, RetryPolicy
    from paddle_tpu.resilience.retry import call_with_retry

    calls = []

    def always_down():
        calls.append(1)
        raise RuntimeError("down")

    pol = RetryPolicy(max_attempts=50, base_delay=0.02, max_delay=0.02,
                      jitter=0.0, timeout=0.05)
    with pytest.raises(RetryExhaustedError):
        call_with_retry("unit_site", always_down, policy=pol)
    assert 2 <= len(calls) < 50, \
        "the deadline, not the attempt count, must end the loop"
