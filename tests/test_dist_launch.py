"""Multi-process distributed correctness (reference test_dist_base.py:628
_run_cluster + check_with_place:827): the same model trained (a) single
process over a 2-device dp mesh and (b) 2 launcher-spawned processes x 1
device with gloo collectives must produce matching loss curves."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "dist_runner.py")


def _clean_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith("PADDLE_"):
            del env[k]
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _parse_losses(out: str):
    for line in out.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(f"no LOSSES line in output:\n{out}")


def test_two_process_loss_equality():
    env = _clean_env()
    single = subprocess.run([sys.executable, "-u", RUNNER], env=env,
                            capture_output=True, text=True, timeout=600)
    assert single.returncode == 0, single.stdout + single.stderr
    base = _parse_losses(single.stdout)

    dist = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices", "1", RUNNER],
        env=env, capture_output=True, text=True, timeout=600)
    assert dist.returncode == 0, dist.stdout + dist.stderr
    got = _parse_losses(dist.stdout)

    assert len(base) == len(got) == 10
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)
    # training must actually progress
    assert base[-1] < base[0]


def test_two_process_zero1_loss_equality():
    """ZeRO-1 under the launcher: 2 processes, Adam state sharded over the
    cross-process dp mesh, must match the single-process AllReduce curve."""
    env = _clean_env()
    env["DIST_OPT"] = "adam"
    single = subprocess.run([sys.executable, "-u", RUNNER], env=env,
                            capture_output=True, text=True, timeout=600)
    assert single.returncode == 0, single.stdout + single.stderr
    base = _parse_losses(single.stdout)

    env["DIST_REDUCE"] = "1"
    dist = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices", "1", RUNNER],
        env=env, capture_output=True, text=True, timeout=600)
    assert dist.returncode == 0, dist.stdout + dist.stderr
    got = _parse_losses(dist.stdout)
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)


def test_two_process_sharded_deepfm():
    """DeepFM with its embedding tables row-sharded across the 2-process
    mesh must match the single-process run (the PS-table replacement under
    real multi-process collectives)."""
    env = _clean_env()
    env["DIST_MODEL"] = "deepfm"
    single = subprocess.run([sys.executable, "-u", RUNNER], env=env,
                            capture_output=True, text=True, timeout=600)
    assert single.returncode == 0, single.stdout + single.stderr
    base = _parse_losses(single.stdout)

    dist = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices", "1", RUNNER],
        env=env, capture_output=True, text=True, timeout=600)
    assert dist.returncode == 0, dist.stdout + dist.stderr
    got = _parse_losses(dist.stdout)
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)
    assert base[-1] < base[0]


def test_two_process_dygraph_data_parallel():
    """Eager DataParallel: 2 ranks on half-batches with collective grad
    averaging must land on the same params as 1 process on the full batch
    (reference TestParallelDyGraphRunnerBase)."""
    env = _clean_env()
    runner = os.path.join(REPO, "tests", "dygraph_dist_runner.py")

    def read_w(out):
        for line in out.splitlines():
            if line.startswith("WFINAL "):
                return json.loads(line[len("WFINAL "):])
        raise AssertionError(f"no WFINAL line:\n{out}")

    single = subprocess.run([sys.executable, "-u", runner], env=env,
                            capture_output=True, text=True, timeout=600)
    assert single.returncode == 0, single.stdout + single.stderr
    base = read_w(single.stdout)

    dist = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices", "1", runner],
        env=env, capture_output=True, text=True, timeout=600)
    assert dist.returncode == 0, dist.stdout + dist.stderr
    got = read_w(dist.stdout)
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-6)


def test_two_process_local_sgd():
    """LocalSGD: ranks train independently on different slices; after the
    periodic parameter average both ranks hold IDENTICAL params and the
    run converges (reference transpiler/collective.py:269)."""
    env = _clean_env()
    env["DIST_LOCALSGD"] = "2"  # sync every 2 steps; STEPS=10 ends synced
    dist = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "--local_devices", "1", RUNNER],
        env=env, capture_output=True, text=True, timeout=600)
    assert dist.returncode == 0, dist.stdout + dist.stderr
    params = {}
    for line in dist.stdout.splitlines():
        if line.startswith("PARAMS"):
            rank = int(line[6])
            params[rank] = json.loads(line.split(" ", 1)[1])
    assert set(params) == {0, 1}, dist.stdout
    np.testing.assert_allclose(params[0], params[1], rtol=1e-6)
    losses = _parse_losses(dist.stdout)
    assert losses[-1] < losses[0]


def test_launcher_propagates_failure():
    env = _clean_env()
    bad = os.path.join(REPO, "tests", "conftest.py")  # not a runnable trainer
    r = subprocess.run(
        [sys.executable, "-u", "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--backend", "cpu",
         "/nonexistent_script.py"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode != 0


def test_multinode_launch_requires_explicit_port():
    """Round-2 advisor: auto-discovered ports disagree across nodes."""
    import pytest

    from paddle_tpu.distributed.launch import _parse_args, launch

    args = _parse_args(["--cluster_node_ips", "10.0.0.1,10.0.0.2",
                        "--node_ip", "10.0.0.1", "dummy.py"])
    with pytest.raises(ValueError, match="started_port"):
        launch(args)
