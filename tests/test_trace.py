"""paddle_tpu.trace: span model, cross-thread context propagation,
serving/trainer wiring, flight-recorder incidents, the cost-model pass
and its monitor MFU gauges, and the disabled-path overhead contract.
CI end-to-end proof: tools/trace_check.py (docs/OBSERVABILITY.md)."""
import os
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as layers
import paddle_tpu.unique_name as un
from paddle_tpu import monitor, serving, trace
from paddle_tpu.framework import Program, program_guard
from paddle_tpu.resilience import fault_plan_guard


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Tracing is process-global (flag + collector): every test starts
    disabled with an empty collector and leaves it that way."""
    fluid.set_flags({"FLAGS_trace": 0, "FLAGS_flight_recorder_size": 256})
    trace.get_collector().reset()
    yield
    fluid.set_flags({"FLAGS_trace": 0, "FLAGS_flight_recorder_size": 256})
    trace.get_collector().reset()


def _traced():
    fluid.set_flags({"FLAGS_trace": 1})


def _mlp():
    with un.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", shape=[6], dtype="float32")
            y = layers.fc(x, size=3)
    return main, startup, y


def _engine(**cfg):
    main, startup, y = _mlp()
    infer = main.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup, scope=scope)
    eng = serving.ServingEngine(
        infer, feed_names=["x"], fetch_list=[y.name], scope=scope,
        executor=exe,
        config=serving.ServingConfig(
            **{"max_batch": 4, "queue_depth": 32, **cfg}))
    return eng


def _feed(rows=1, seed=0):
    return {"x": np.random.RandomState(seed).rand(rows, 6)
            .astype(np.float32)}


# ---------------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------------

def test_span_parentage_ids_and_status():
    _traced()
    with trace.root_span("root", kind="test") as root:
        with trace.span("child") as child:
            child.set_attribute("k", 1)
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
    assert root.duration_s is not None and root.status == "ok"
    tree = trace.trace_tree(root.trace_id)
    assert [s.name for s in tree] == ["root", "child"]
    # error status + message captured on an exception exit
    with pytest.raises(ValueError):
        with trace.span("boom") as sp:
            raise ValueError("nope")
    assert sp.status == "error" and "ValueError" in sp.error


def test_root_span_ignores_ambient():
    _traced()
    with trace.span("outer") as outer:
        r = trace.root_span("fresh")
        assert r.trace_id != outer.trace_id and r.parent_id is None
        r.end()


def test_span_end_is_idempotent():
    _traced()
    sp = trace.start_span("once", parent=False)
    sp.end()
    d = sp.duration_s
    sp.end(error=RuntimeError("late"))
    assert sp.duration_s == d and sp.status == "ok"
    assert sum(1 for s in trace.spans() if s.span_id == sp.span_id) == 1


def test_disabled_is_noop_singleton_no_collection():
    assert not trace.enabled()
    spans = [trace.span("a"), trace.root_span("b"),
             trace.start_span("c")]
    assert all(s is trace.NOOP_SPAN for s in spans)
    with trace.span("d") as sp:
        sp.set_attribute("x", 1)
    assert trace.spans() == []
    # flag flips through set_flags are observed (epoch-cached read)
    _traced()
    assert trace.enabled()
    fluid.set_flags({"FLAGS_trace": 0})
    assert not trace.enabled()


def test_cross_thread_attach_parentage():
    _traced()
    root = trace.start_span("request", parent=False)
    seen = {}

    def worker():
        with trace.attach(root):
            with trace.span("dispatch") as d:
                seen["trace"] = d.trace_id
                seen["parent"] = d.parent_id
                seen["thread"] = d.thread
    t = threading.Thread(target=worker)
    t.start()
    t.join()
    root.end()
    assert seen["trace"] == root.trace_id
    assert seen["parent"] == root.span_id
    assert seen["thread"] != root.thread


def test_exporters_chrome_and_jsonl(tmp_path):
    _traced()
    with trace.root_span("a"):
        with trace.span("b"):
            pass
    chrome = tmp_path / "t.json"
    jl = tmp_path / "t.jsonl"
    assert trace.export_chrome(str(chrome)) == 2
    assert trace.export_jsonl(str(jl)) == 2
    import json

    evs = json.load(open(chrome))["traceEvents"]
    assert all(e["ph"] == "X" and e["cat"] == "trace" for e in evs)
    assert all("trace_id" in e["args"] for e in evs)
    # epoch-anchored timestamps (merge contract with the profiler dump)
    assert all(e["ts"] > 1e15 for e in evs)   # µs since epoch


def test_timeline_merges_trace_and_profiler(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import timeline

    _traced()
    with trace.root_span("span_side"):
        pass
    jl = tmp_path / "spans.jsonl"
    trace.export_jsonl(str(jl))
    # a profiler host dump with the epoch anchor
    import json
    import time

    (tmp_path / "host_events.json").write_text(json.dumps(
        [{"name": "prof_side", "t0": 1.0, "t1": 1.5, "tid": 0,
          "epoch": time.time()}]))
    out = tmp_path / "merged.json"
    assert timeline.convert(str(tmp_path), str(out),
                            trace_path=str(jl)) == 0
    evs = json.load(open(out))["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {0, 1}
    # both sides on the epoch clock: within minutes of each other
    ts = sorted(e["ts"] for e in evs)
    assert ts[-1] - ts[0] < 300e6


# ---------------------------------------------------------------------------
# serving wiring
# ---------------------------------------------------------------------------

def test_serving_request_chain_cross_thread():
    _traced()
    eng = _engine()
    with eng:
        fut = eng.submit(_feed())
        fut.result(timeout=60)
    assert fut.trace_id
    tree = trace.trace_tree(fut.trace_id)
    names = [s.name for s in tree]
    assert names[0] == "serving.request"
    assert {"serving.submit", "serving.enqueue",
            "serving.dispatch"} <= set(names)
    root = tree[0]
    assert root.attrs["outcome"] == "completed"
    assert root.parent_id is None and root.duration_s is not None
    # submit-thread -> dispatch-thread propagation
    disp = next(s for s in tree if s.name == "serving.dispatch")
    assert disp.thread != root.thread
    assert disp.parent_id == root.span_id
    # the batch span links back to this request's trace
    batches = [s for s in trace.spans() if s.name == "serving.batch"]
    assert any(fut.trace_id in b.attrs.get("request_traces", "")
               for b in batches)
    # root closes after every child
    for s in tree[1:]:
        assert (root.t0_mono + root.duration_s) + 1e-6 >= \
            (s.t0_mono + s.duration_s)


def test_serving_typed_outcomes_carry_trace_ids():
    _traced()
    eng = _engine()
    # not started: typed EngineStopped at submit still ships a trace id
    with pytest.raises(serving.EngineStopped) as ei:
        eng.submit(_feed())
    assert ei.value.trace_id
    tree = trace.trace_tree(ei.value.trace_id)
    assert tree and tree[0].attrs["outcome"] == "rejected_stopped"
    acct = eng.accounting()
    assert acct["recent_outcomes"][-1]["trace_id"] == ei.value.trace_id
    assert acct["recent_outcomes"][-1]["outcome"] == "rejected_stopped"


def test_batch_failure_flight_recorder_dump():
    _traced()
    trace.clear_incidents()
    eng = _engine()
    with eng, fault_plan_guard("batch_dispatch:1:RuntimeError"):
        fut = eng.submit(_feed())
        with pytest.raises(serving.BatchFailed) as ei:
            fut.result(timeout=60)
    assert ei.value.trace_id == fut.trace_id
    incs = [i for i in trace.incidents() if i["kind"] == "batch_failed"]
    assert incs, "BatchFailed must dump the flight recorder"
    chain = {d["name"] for d in incs[-1]["recent_spans"]
             if d["trace_id"] == fut.trace_id}
    assert {"serving.request", "serving.submit", "serving.enqueue",
            "serving.dispatch"} <= chain
    req = next(d for d in incs[-1]["recent_spans"]
               if d["trace_id"] == fut.trace_id
               and d["name"] == "serving.request")
    assert req["attrs"]["outcome"] == "failed"
    assert req["status"] == "error"


def test_flight_recorder_disabled_loses_context():
    _traced()
    fluid.set_flags({"FLAGS_flight_recorder_size": 0})
    trace.get_collector().reset()   # re-derive ring sizing from flags
    trace.clear_incidents()
    eng = _engine()
    with eng, fault_plan_guard("batch_dispatch:1:RuntimeError"):
        fut = eng.submit(_feed())
        with pytest.raises(serving.BatchFailed):
            fut.result(timeout=60)
    incs = [i for i in trace.incidents() if i["kind"] == "batch_failed"]
    assert incs
    assert not incs[-1]["flight_recorder_enabled"]
    assert incs[-1]["recent_spans"] == []   # the negative control


def test_watchdog_hang_dumps_flight_recorder():
    _traced()
    trace.clear_incidents()
    eng = _engine()
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    try:
        with eng, fault_plan_guard("hang:@1:hang"):
            fut = eng.submit(_feed())
            with pytest.raises(serving.BatchFailed) as ei:
                fut.result(timeout=60)
    finally:
        fluid.set_flags({"FLAGS_step_timeout_s": 0.0,
                         "FLAGS_watchdog_hard_exit": 1})
    from paddle_tpu.resilience.distributed import WatchdogTimeout

    assert isinstance(ei.value.__cause__, WatchdogTimeout)
    incs = [i for i in trace.incidents()
            if i["kind"] == "watchdog_timeout"]
    assert incs, "watchdog expiry must dump the flight recorder"
    # the hung request's submit-side chain is in the expiry dump
    chain = {d["name"] for d in incs[-1]["recent_spans"]
             if d["trace_id"] == fut.trace_id}
    assert {"serving.submit", "serving.enqueue"} <= chain


# ---------------------------------------------------------------------------
# trainer wiring
# ---------------------------------------------------------------------------

def test_trainer_step_traces(tmp_path):
    _traced()

    def train_func():
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1)
        return layers.mean(layers.square_error_cost(pred, y))

    def reader():
        rng = np.random.RandomState(0)
        for _ in range(2):
            yield [(rng.rand(4).astype(np.float32),
                    rng.rand(1).astype(np.float32)) for _ in range(4)]

    ckpt = fluid.contrib.CheckpointConfig(str(tmp_path / "ck"),
                                          step_interval=2)
    with un.guard():
        tr = fluid.contrib.Trainer(train_func,
                                   lambda: fluid.optimizer.SGD(0.1),
                                   checkpoint_config=ckpt)
        tr.train(num_epochs=1, event_handler=lambda ev: None,
                 reader=lambda: reader(), feed_order=["x", "y"])
    roots = [s for s in trace.spans()
             if s.name == "trainer.step" and s.parent_id is None]
    assert len(roots) == 2
    for r in roots:
        assert r.attrs["outcome"] in ("ok", "graceful_exit")
        names = {s.name for s in trace.trace_tree(r.trace_id)}
        assert "trainer.data" in names and "executor.run" in names
    # the step_interval=2 save landed as a checkpoint child of step 2
    all_names = [s.name for s in trace.spans()]
    assert "trainer.checkpoint" in all_names


def test_trainer_post_dispatch_failure_not_labeled_ok(tmp_path):
    """A failure AFTER the dispatch (event handler, checkpoint write)
    must close the step trace with the error, never 'ok' — the flight
    recorder consulted for that incident would lie otherwise."""
    _traced()

    def train_func():
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, 1)
        return layers.mean(layers.square_error_cost(pred, y))

    def reader():
        rng = np.random.RandomState(0)
        yield [(rng.rand(4).astype(np.float32),
                rng.rand(1).astype(np.float32))]

    def handler(ev):
        if isinstance(ev, fluid.contrib.EndStepEvent):
            raise IOError("post-dispatch boom")

    with un.guard():
        tr = fluid.contrib.Trainer(train_func,
                                   lambda: fluid.optimizer.SGD(0.1))
        with pytest.raises(IOError):
            tr.train(num_epochs=1, event_handler=handler,
                     reader=lambda: reader(), feed_order=["x", "y"])
    root = next(s for s in trace.spans() if s.name == "trainer.step")
    assert root.status == "error"
    assert root.attrs["outcome"] == "OSError"
    assert "post-dispatch boom" in root.error


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_cost_model_exact_small_program():
    from paddle_tpu.analysis.cost_model import estimate_cost

    with un.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            y = layers.fc(x, size=3, bias_attr=False)  # mul only
    rep = estimate_cost(main, batch_size=4)
    # one mul: 2 * M(4) * K(8) * N(3) = 192 FLOPs
    assert rep.flops_by_op_type["mul"] == 192.0
    assert rep.flops_forward == rep.flops_total
    assert rep.param_bytes == 8 * 3 * 4
    assert rep.batch_size == 4 and rep.flops_per_byte > 0


def test_cost_model_conv_and_grads():
    from paddle_tpu.analysis.cost_model import estimate_cost

    with un.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            img = layers.data("img", shape=[3, 8, 8], dtype="float32")
            c = layers.conv2d(img, 4, 3, padding=1, bias_attr=False)
            loss = layers.mean(c)
        fluid.optimizer.SGD(0.1).minimize(loss)
    rep = estimate_cost(main, batch_size=2)
    # conv2d fwd: 2 * out(2*4*8*8) * (3*3*3) = 27648
    assert rep.flops_by_op_type["conv2d"] == 2 * (2 * 4 * 8 * 8) * 27
    # grad = exactly 2x forward for the matmul class
    assert rep.flops_by_op_type["conv2d_grad"] == \
        2 * rep.flops_by_op_type["conv2d"]
    assert rep.flops_backward > 0 and rep.flops_optimizer > 0


def test_cost_model_registered_as_pass():
    from paddle_tpu.analysis import CostReport
    from paddle_tpu.analysis.pass_manager import default_pass_manager

    with un.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", shape=[8], dtype="float32")
            y = layers.fc(x, size=3)
    res = default_pass_manager().run_pipeline(
        main, ["cost_model"], fetch_names=[y.name], batch_size=16,
        verify="none")
    rep = res.values["cost_model"]
    assert isinstance(rep, CostReport)
    assert rep.batch_size == 16 and rep.flops_total > 0
    assert res.diagnostics == []   # cost is information, not findings


def test_mfu_gauges_from_executor_and_serving():
    monitor.reset()
    main, startup, y = _mlp()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((4, 6), np.float32)},
                fetch_list=[y.name])
    g = monitor.metric_value("executor_mfu", None, path="run",
                             program=str(main._serial), batch="4")
    assert g is not None and 0 <= g < 1
    assert monitor.metric_value("executor_model_gflops_per_step", 0.0,
                                program=str(main._serial),
                                batch="4") > 0
    # serving bucket gauges
    eng = _engine()
    with eng:
        eng.submit(_feed()).result(timeout=60)
    snap = monitor.get_registry().to_dict()
    assert "serving_bucket_mfu" in snap
    assert "serving_bucket_achieved_tflops" in snap


def test_resnet18_cost_ratio_against_analytic():
    """The 2-FLOPs/MAC convention against a hand-derived per-layer count
    for the CIFAR ResNet-18 probe (full ResNet-50/BERT-base checks run
    in tools/trace_check.py)."""
    from paddle_tpu.analysis.cost_model import estimate_cost
    from paddle_tpu.models.resnet import build_resnet

    with un.guard():
        net = build_resnet(depth=18, class_num=10,
                           image_shape=(3, 32, 32),
                           build_optimizer=False)
    infer = net["main"].clone(for_test=True)
    rep = estimate_cost(infer, batch_size=1)
    # dominant conv sum, hand-derived (2/MAC): ~70.8 MF for this stack
    assert 0.5e8 < rep.flops_total < 1.5e8
    conv = rep.flops_by_op_type["conv2d"]
    assert conv / rep.flops_total > 0.9


# ---------------------------------------------------------------------------
# overhead contract
# ---------------------------------------------------------------------------

def test_disabled_span_no_allocation():
    assert not trace.enabled()
    a = trace.span("hot")
    b = trace.span("hot")
    assert a is b is trace.NOOP_SPAN   # identity: zero allocation
    # record_incident with tracing off still returns a (context-free)
    # incident record and never raises
    inc = trace.record_incident("unit_test", detail="off")
    assert inc["recent_spans"] == []
