"""Per-tenant quotas + weighted fair-share admission (docs/SERVING.md
"Fleet control loop").

The contract under test: with ``tenant_fair_share`` ON, a tenant over
its queue quota is shed typed ``Overloaded(reason="tenant_quota")``
while under-share tenants keep admitting; dispatch picks batch anchors
by stride scheduling (dispatched rows converge to the weight share);
and every shed reconciles exactly in the per-tenant ledger. With the
flag OFF (the default), admission and dispatch are bit-identical to the
pre-tenant engine — the whole feature is invisible."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving
from paddle_tpu.resilience import fault_plan_guard
from paddle_tpu.serving.engine import parse_tenant_weights
from paddle_tpu.serving.fleet import wire


@pytest.fixture(autouse=True)
def _flags_and_plan_reset():
    from paddle_tpu import flags as flags_mod
    from paddle_tpu.resilience import faults

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    faults.clear_plan()


def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(**cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = serving.ServingConfig(max_batch=cfg_kw.pop("max_batch", 4),
                                **cfg_kw)
    return serving.ServingEngine(infer, feed_names=["x"], fetch_list=[pred],
                                 scope=scope, executor=exe, config=cfg)


def _feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


def _hang_dispatcher():
    fluid.set_flags({"FLAGS_step_timeout_s": 2.0,
                     "FLAGS_watchdog_hard_exit": 0})
    return fault_plan_guard("hang:@1:hang")


def _wait_queue_empty(eng, timeout=10.0):
    import time

    until = time.monotonic() + timeout
    while time.monotonic() < until:
        if not eng._queue:
            return
        time.sleep(0.01)
    raise AssertionError("dispatcher never drained the queue")


# ---------------------------------------------------------------------------
# the weights spec
# ---------------------------------------------------------------------------

def test_parse_tenant_weights():
    assert parse_tenant_weights("") == {}
    assert parse_tenant_weights("acme:3,globex:1.5") == {"acme": 3.0,
                                                         "globex": 1.5}
    assert parse_tenant_weights(" acme : 2 ,") == {"acme": 2.0}
    for bad in ("acme", "acme:zero", "acme:0", ":-1", "acme:-2"):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)


def test_weights_validated_at_config_resolve_not_mid_admission():
    with pytest.raises(ValueError):
        serving.ServingConfig(tenant_weights="oops").resolve()
    with pytest.raises(ValueError):
        serving.ServingConfig(tenant_quota_frac=0.0).resolve()


def test_config_resolves_from_flags():
    fluid.set_flags({"FLAGS_serving_tenant_fair_share": 1,
                     "FLAGS_serving_tenant_weights": "acme:2",
                     "FLAGS_serving_tenant_quota_frac": 0.25})
    c = serving.ServingConfig().resolve()
    assert c.tenant_fair_share is True
    assert c.tenant_weights == "acme:2" and c.tenant_quota_frac == 0.25


# ---------------------------------------------------------------------------
# per-tenant queue quota
# ---------------------------------------------------------------------------

def test_hot_tenant_shed_typed_tenant_quota_while_others_admit():
    eng = _engine(max_batch=1, queue_depth=8, batch_window_s=0.0,
                  tenant_fair_share=True, tenant_quota_frac=0.25)
    eng.warm_up()
    futs = []
    with eng, _hang_dispatcher():
        futs.append(eng.submit(_feed(), tenant="hog"))   # dispatched, hangs
        _wait_queue_empty(eng)
        # quota = max(1, int(8 * 0.25)) = 2 queued slots for weight 1
        futs += [eng.submit(_feed(seed=i), tenant="hog") for i in range(2)]
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed(), tenant="hog")
        assert ei.value.reason == "tenant_quota"
        assert "hog" in str(ei.value)
        # the under-share tenant admits into the SAME queue right after
        futs.append(eng.submit(_feed(), tenant="small"))
        for f in futs:
            f.exception(timeout=60)
    acct = eng.accounting()
    assert acct["exact"] and acct["shed"] == 1
    tenants = eng.tenant_accounting()
    assert tenants["hog"]["quota_sheds"] == 1
    assert tenants["hog"]["outcomes"]["shed"] == 1
    assert tenants["small"].get("quota_sheds", 0) == 0
    assert monitor.metric_value("serving_tenant_quota_sheds_total", 0.0,
                                tenant="hog") >= 1
    assert monitor.metric_value("serving_shed_total", 0.0,
                                reason="tenant_quota") >= 1


def test_weighted_tenant_gets_a_larger_quota():
    eng = _engine(max_batch=1, queue_depth=8, batch_window_s=0.0,
                  tenant_fair_share=True, tenant_quota_frac=0.25,
                  tenant_weights="vip:2")
    eng.warm_up()
    futs = []
    with eng, _hang_dispatcher():
        futs.append(eng.submit(_feed(), tenant="vip"))
        _wait_queue_empty(eng)
        # weight 2 doubles the quota: 4 queued slots instead of 2
        futs += [eng.submit(_feed(seed=i), tenant="vip") for i in range(4)]
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed(), tenant="vip")
        assert ei.value.reason == "tenant_quota"
        for f in futs:
            f.exception(timeout=60)
    tenants = eng.tenant_accounting()
    assert tenants["vip"]["weight"] == 2.0 and tenants["vip"]["quota"] == 4


def test_fair_share_off_is_the_pre_tenant_engine():
    """Default config: no tenant ever sees tenant_quota — the queue_full
    bound is the only depth shed, exactly as before this feature."""
    eng = _engine(max_batch=1, queue_depth=2, batch_window_s=0.0)
    assert eng.config.tenant_fair_share is False
    eng.warm_up()
    futs = []
    with eng, _hang_dispatcher():
        futs.append(eng.submit(_feed(), tenant="hog"))
        _wait_queue_empty(eng)
        futs += [eng.submit(_feed(seed=i), tenant="hog") for i in range(2)]
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(_feed(), tenant="hog")
        assert ei.value.reason == "queue_full"
        for f in futs:
            f.exception(timeout=60)
    assert eng.accounting()["exact"]


def test_tenant_quota_reason_travels_the_wire():
    e = serving.Overloaded("over share", reason="tenant_quota")
    back = wire.error_from_body(wire.error_body(e))
    assert isinstance(back, serving.Overloaded)
    assert back.reason == "tenant_quota"
    assert wire.status_for(e) == 429   # unadmitted: safe sibling retry


# ---------------------------------------------------------------------------
# stride-scheduled dispatch (DWRR-equivalent)
# ---------------------------------------------------------------------------

def test_dispatch_interleaves_tenants_instead_of_fifo():
    """6 hog requests queued ahead of 2 small ones: strict FIFO would
    settle every hog first; stride scheduling alternates, so both small
    requests settle before the last two hogs."""
    eng = _engine(max_batch=1, queue_depth=32, batch_window_s=0.0,
                  tenant_fair_share=True)
    eng.warm_up()
    with eng, _hang_dispatcher():
        hang = eng.submit(_feed(), tenant="other")
        _wait_queue_empty(eng)
        hogs = [eng.submit(_feed(seed=i), tenant="hog") for i in range(6)]
        smalls = [eng.submit(_feed(seed=i), tenant="small")
                  for i in range(2)]
        hang.exception(timeout=60)
        for f in hogs + smalls:
            assert f.result(timeout=60)[0].shape == (1, 4)
    # settle order by seq: futures don't expose seq, but submissions are
    # sequential (hogs first, then smalls), so the sorted completed seqs
    # split into the hog six and the small two
    completed = [r["seq"] for r in eng.accounting()["recent_outcomes"]
                 if r["outcome"] == "completed"]
    assert len(completed) == 8
    hog_seqs = sorted(completed)[:6]
    small_seqs = sorted(completed)[6:]
    last_two_hogs = [completed.index(s) for s in hog_seqs[-2:]]
    small_positions = [completed.index(s) for s in small_seqs]
    assert max(small_positions) < max(last_two_hogs), (
        f"stride scheduling must not starve the small tenant: "
        f"completed order {completed}")


def test_weights_bias_the_dispatch_share():
    """vip at weight 2 vs std at weight 1: of the first 6 dispatches,
    vip gets 4 (its pass advances half as fast)."""
    eng = _engine(max_batch=1, queue_depth=32, batch_window_s=0.0,
                  tenant_fair_share=True, tenant_weights="vip:2")
    eng.warm_up()
    with eng, _hang_dispatcher():
        hang = eng.submit(_feed(), tenant="other")
        _wait_queue_empty(eng)
        vips = [eng.submit(_feed(seed=i), tenant="vip") for i in range(6)]
        stds = [eng.submit(_feed(seed=i), tenant="std") for i in range(3)]
        hang.exception(timeout=60)
        for f in vips + stds:
            f.result(timeout=60)
    completed = [r["seq"] for r in eng.accounting()["recent_outcomes"]
                 if r["outcome"] == "completed"]
    vip_seqs = set(sorted(completed)[:6])
    first6 = completed[:6]
    assert sum(1 for s in first6 if s in vip_seqs) == 4, (
        f"weight 2 should take 2/3 of early dispatches, got {first6}")


def test_fair_share_does_not_break_exact_accounting_or_coalescing():
    """Same-signature coalescing still fills the anchor's batch; the
    ledger reconciles with the engine accounting per outcome."""
    eng = _engine(max_batch=4, queue_depth=32, batch_window_s=0.1,
                  tenant_fair_share=True)
    eng.warm_up()
    with eng:
        futs = [eng.submit(_feed(seed=i), tenant=f"t{i % 3}")
                for i in range(9)]
        for f in futs:
            assert f.result(timeout=60)[0].shape == (1, 4)
    acct = eng.accounting()
    assert acct["exact"] and acct["completed"] == 9
    tenants = eng.tenant_accounting()
    total = sum(t["outcomes"].get("completed", 0)
                for t in tenants.values())
    assert total == acct["completed"]
