"""Profiler host-event path (ISSUE 3 satellites): RecordEvent aggregation,
span dump round-trip through tools/timeline.py into chrome-trace JSON,
stop_profiler's structured report + logging, lock-protected mutation, and
the executor's monitor spans landing in the same timeline."""
import json
import logging
import os
import threading
import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import profiler as prof
import tools.timeline as timeline


def test_record_event_aggregation_without_trace():
    prof.reset_profiler()
    with prof.RecordEvent("agg_test"):
        time.sleep(0.01)
    with prof.RecordEvent("agg_test"):
        pass
    cnt, tot = prof._host_events["agg_test"]
    assert cnt == 2
    assert tot >= 0.01


def test_profiler_roundtrip_to_chrome_trace(tmp_path):
    prof.reset_profiler()
    with prof.profiler(profile_path=str(tmp_path)):
        with prof.RecordEvent("span_outer"):
            with prof.RecordEvent("span_inner"):
                time.sleep(0.002)
        # executor activity inside the window: its monitor spans must land
        # in the same host timeline (the RecordEvent substrate)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2], dtype="float32")
            y = fluid.layers.fc(x, 2)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                    fetch_list=[y.name])
    assert (tmp_path / "host_events.json").exists()

    out = tmp_path / "timeline.json"
    assert timeline.convert(str(tmp_path), str(out)) == 0
    trace = json.loads(out.read_text())
    events = trace["traceEvents"]
    names = {e["name"] for e in events}
    assert {"span_outer", "span_inner", "executor::step",
            "executor::trace_lower", "executor::xla_compile"} <= names
    for e in events:
        assert e["ph"] == "X"
        assert e["ts"] >= 0 and e["dur"] >= 0
    # inner span nests inside outer on the same row
    outer = next(e for e in events if e["name"] == "span_outer")
    inner = next(e for e in events if e["name"] == "span_inner")
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


def test_timeline_handles_empty_span_dump(tmp_path):
    """Satellite: an empty host_events.json used to NameError on the
    unbound base timestamp; it must emit a valid empty trace and exit 0."""
    (tmp_path / "host_events.json").write_text("[]")
    out = tmp_path / "timeline.json"
    assert timeline.convert(str(tmp_path), str(out)) == 0
    data = json.loads(out.read_text())
    assert data["traceEvents"] == []
    assert timeline.main(["--profile_path", str(tmp_path),
                          "--timeline_path", str(out)]) == 0


def test_timeline_missing_dump_still_errors(tmp_path):
    assert timeline.convert(str(tmp_path), str(tmp_path / "o.json")) == 1


def test_stop_profiler_returns_structure_and_logs(tmp_path, caplog, capsys):
    prof.reset_profiler()
    with caplog.at_level(logging.INFO, logger="paddle_tpu.profiler"):
        prof.start_profiler(profile_path=str(tmp_path))
        with prof.RecordEvent("structured_event"):
            time.sleep(0.001)
        report = prof.stop_profiler(sorted_key="calls")
    names = [r["name"] for r in report["events"]]
    assert "structured_event" in names
    row = report["events"][names.index("structured_event")]
    assert row["calls"] >= 1
    assert row["total_s"] > 0 and row["avg_s"] > 0
    assert report["sorted_by"] == "calls"
    assert report["spans_path"] and os.path.exists(report["spans_path"])
    # logged for servers/test suites...
    assert any("host event report" in r.message for r in caplog.records)
    # ...and still printed for CLI compat with the reference
    assert "structured_event" in capsys.readouterr().out


def test_record_event_threadsafe_against_stop(tmp_path):
    """Satellite: worker threads in RecordEvent.__exit__ race
    stop_profiler's snapshot-and-clear; under the shared lock this must
    neither lose the report nor corrupt the span list."""
    prof.reset_profiler()
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            with prof.RecordEvent("worker_span"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    prof.start_profiler(profile_path=str(tmp_path))
    for t in threads:
        t.start()
    try:
        time.sleep(0.05)
        report = prof.stop_profiler()
    finally:
        stop.set()
        for t in threads:
            t.join()
    names = [r["name"] for r in report["events"]]
    assert "worker_span" in names
    spans = json.load(open(report["spans_path"]))
    # every dumped span is well-formed (no torn writes)
    for s in spans:
        assert s["t1"] >= s["t0"]
        assert isinstance(s["tid"], int)
