"""On-chip smoke suite (VERDICT r2 item 10): run with

    PADDLE_TPU_TESTS=1 python -m pytest tests/test_tpu_smoke.py -m tpu -q

on a host with a real accelerator. The CPU suite auto-skips these. Covers
the TPU-numerics policy (bf16 matmul tolerance), one real train step, and
the recompute remat surviving into the chip executable.
"""
import numpy as np
import pytest

import paddle_tpu as fluid

pytestmark = pytest.mark.tpu


def test_bf16_matmul_tolerance():
    """bf16 MXU matmul vs fp64-ish numpy oracle: the tolerance policy
    (SURVEY §7 hard-part 4) — bf16 has ~3 decimal digits; rtol 2e-2 over a
    256-deep contraction is the documented budget."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    a = rng.randn(128, 256).astype(np.float32)
    b = rng.randn(256, 128).astype(np.float32)
    got = np.asarray(jnp.matmul(a.astype(jnp.bfloat16),
                                b.astype(jnp.bfloat16)).astype(jnp.float32))
    want = a @ b
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-1)


def test_one_train_step_on_chip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[64], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, 64, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xb, yb = rng.randn(32, 64).astype(np.float32), rng.randn(32, 1).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        first = last = None
        for _ in range(10):
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            last = float(np.asarray(lv).reshape(-1)[0])
            first = first if first is not None else last
    assert np.isfinite(last) and last < first


def test_recompute_remat_survives_to_executable():
    """On TPU the jax.checkpoint remat must reach the binary: the recompute
    code makes the generated executable strictly larger while argument/out
    sizes stay equal (CPU CSE merges it away, so this only proves out here)."""
    import jax

    from test_recompute import _lowered

    plain = _lowered(False, width=256, depth=8, batch=256).compile()
    rc = _lowered(True, width=256, depth=8, batch=256).compile()
    pa, ra = plain.memory_analysis(), rc.memory_analysis()
    assert ra.argument_size_in_bytes == pa.argument_size_in_bytes
    assert ra.generated_code_size_in_bytes > pa.generated_code_size_in_bytes
