"""Deterministic data-order resume (SURVEY §5 failure/elastic — the gap
every verdict listed): DataLoader.state_dict()/set_state_dict() +
io.save/load_checkpoint restart training on the exact sample the crash
interrupted, and the resumed loss trajectory matches the uninterrupted
run bit-for-bit."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un


def _samples():
    rng = np.random.RandomState(0)
    w = rng.rand(4, 1).astype(np.float32)
    xs = rng.rand(64, 4).astype(np.float32)
    return [(x, x @ w) for x in xs]


def _build():
    x = fluid.layers.data("x", shape=[4], dtype="float32")
    y = fluid.layers.data("y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return x, y, loss


def _loader(x, y):
    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=2)
    loader.set_sample_generator(lambda: iter(_samples()), batch_size=8,
                                drop_last=True)
    return loader


def test_dataloader_state_dict_resumes_mid_epoch():
    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        x, y, loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        main.random_seed = 5

        # uninterrupted run: 2 epochs of 8 batches
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        full = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            loader = _loader(x, y)
            for _ in range(2):
                for batch in loader:
                    (lv,) = exe.run(main, feed=batch, fetch_list=[loss])
                    full.append(float(np.asarray(lv).reshape(-1)[0]))

        # interrupted run: crash after 5 batches, checkpoint, resume
        exe2 = fluid.Executor(fluid.CPUPlace())
        s2 = fluid.Scope()
        part = []
        with fluid.scope_guard(s2):
            exe2.run(startup)
            loader2 = _loader(x, y)
            served = 0
            for batch in loader2:
                (lv,) = exe2.run(main, feed=batch, fetch_list=[loss])
                part.append(float(np.asarray(lv).reshape(-1)[0]))
                served += 1
                if served == 5:
                    break  # "crash"
            ck = loader2.state_dict()
            assert ck == {"epoch": 0, "batch": 5}
            params = {n: np.asarray(s2.find_var(n)).copy()
                      for n in list(s2.vars)}

        # fresh process: restore params + loader position, continue
        exe3 = fluid.Executor(fluid.CPUPlace())
        s3 = fluid.Scope()
        with fluid.scope_guard(s3):
            exe3.run(startup)
            for n, v in params.items():
                s3.set_var(n, v)
            loader3 = _loader(x, y)
            loader3.set_state_dict(ck)
            for batch in loader3:     # finishes epoch 0 from batch 5
                (lv,) = exe3.run(main, feed=batch, fetch_list=[loss])
                part.append(float(np.asarray(lv).reshape(-1)[0]))
            for batch in loader3:     # epoch 1
                (lv,) = exe3.run(main, feed=batch, fetch_list=[loss])
                part.append(float(np.asarray(lv).reshape(-1)[0]))
    np.testing.assert_allclose(part, full, rtol=1e-6, atol=1e-7)


def test_resume_walks_past_torn_checkpoint(tmp_path):
    """Kill-mid-save resume (resilience): the newest serial is torn (blobs
    on disk, no integrity manifest — what a non-atomic writer's death
    leaves); recovery must report it and land on the last VERIFIED serial,
    restoring both params and the data-loader position recorded in meta."""
    import os

    from paddle_tpu import resilience

    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        x, y, loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            loader = _loader(x, y)
            it = iter(loader)
            for _ in range(4):
                exe.run(main, feed=next(it), fetch_list=[loss])
            fluid.io.save_checkpoint(
                exe, str(tmp_path / "checkpoint_4"), main, scope=scope,
                meta={"step": 4, "reader": loader.state_dict()})
            good = {n: np.asarray(scope.find_var(n)).copy()
                    for n in scope.vars}
        # the torn serial: valid-looking blobs, no integrity section
        torn = tmp_path / "checkpoint_9"
        torn.mkdir()
        (torn / "ckpt.npz").write_bytes(b"not really an npz")
        (torn / "meta.json").write_text('{"step": 9}')
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe.run(startup)
            meta, serial, skipped = resilience.load_latest_checkpoint(
                exe, str(tmp_path), main_program=main, scope=s2)
        assert serial == 4 and meta["step"] == 4
        assert [s["serial"] for s in skipped] == [9]
        assert str(skipped[0]["code"]).startswith("PT6")
        for n, v in good.items():
            np.testing.assert_array_equal(np.asarray(s2.find_var(n)), v)
        loader2 = _loader(x, y)
        loader2.set_state_dict(meta["reader"])
        assert sum(1 for _ in loader2) == 4  # 8 per epoch - 4 consumed
        assert not os.path.exists(str(tmp_path / "checkpoint_9" /
                                      "manifest.json"))


def test_tampered_checkpoint_refused_on_resume(tmp_path):
    """A bit-flip in the blob after a clean save must be detected by the
    manifest BEFORE anything loads (PT603), and verify=False documents the
    legacy escape hatch."""
    from paddle_tpu import resilience

    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        x, y, loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            fluid.io.save_checkpoint(exe, str(tmp_path), main, scope=scope,
                                     meta={"step": 2})
        blob = tmp_path / "ckpt.npz"
        raw = bytearray(blob.read_bytes())
        raw[len(raw) // 3] ^= 0x5A
        blob.write_bytes(bytes(raw))
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe.run(startup)
            with pytest.raises(resilience.CheckpointCorruptError) as ei:
                fluid.io.load_checkpoint(exe, str(tmp_path), main, scope=s2)
        assert ei.value.code == "PT603"


def test_checkpoint_roundtrip_with_loader_state(tmp_path):
    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        x, y, loss = _build()
        main, startup = (fluid.default_main_program(),
                         fluid.default_startup_program())
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            loader = _loader(x, y)
            it = iter(loader)
            for _ in range(3):
                batch = next(it)
                exe.run(main, feed=batch, fetch_list=[loss])
            fluid.io.save_checkpoint(
                exe, str(tmp_path), main_program=main, scope=scope,
                meta={"reader": loader.state_dict(), "step": 3})
        s2 = fluid.Scope()
        with fluid.scope_guard(s2):
            exe.run(startup)
            meta = fluid.io.load_checkpoint(exe, str(tmp_path),
                                            main_program=main, scope=s2)
            assert meta["step"] == 3
            assert meta["reader"]["batch"] == 3
            loader2 = _loader(x, y)
            loader2.set_state_dict(meta["reader"])
            remaining = sum(1 for _ in loader2)
        assert remaining == 5  # 8 per epoch - 3 consumed
