"""Data pipeline: DataLoader / PyReader / DataFeeder / datasets / prefetch
(VERDICT r2 item #2; reference python/paddle/fluid/reader.py:73,569,
data_feeder.py, reader/buffered_reader.cc, python/paddle/dataset/).
"""
import time

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer


def _mnist_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[784])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(img, 128, act="relu")
        logits = layers.fc(h, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        optimizer.Adam(1e-3).minimize(loss)
    return main, startup, img, label, loss


def test_dataloader_trains_mnist():
    main, startup, img, label, loss = _mnist_mlp()
    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=4)
    reader = fluid.reader.shuffle(fluid.dataset.mnist.train(), 1024)
    loader.set_sample_generator(reader, batch_size=64,
                                places=fluid.CPUPlace())
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for feed in loader:
            assert feed["img"].shape == (64, 784)
            assert feed["label"].shape == (64, 1)
            losses.append(float(exe.run(main, feed=feed,
                                        fetch_list=[loss])[0]))
    assert len(losses) == 8192 // 64
    assert np.mean(losses[-10:]) < losses[0] * 0.5, (losses[0], losses[-1])


def test_dataloader_batch_and_sample_list_generators():
    main, startup, img, label, loss = _mnist_mlp()
    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=2, return_list=True)
    loader.set_sample_list_generator(
        fluid.batch(fluid.dataset.mnist.test(), 32, drop_last=True))
    n = 0
    for img_v, lbl_v in loader:
        assert img_v.shape == (32, 784) and lbl_v.shape == (32, 1)
        n += 1
    assert n == 1024 // 32

    # batch generator mode: user yields ready numpy batches
    loader2 = fluid.DataLoader.from_generator(feed_list=[img, label],
                                              capacity=2)

    def batches():
        for _ in range(3):
            yield np.zeros((16, 784), np.float32), np.zeros((16, 1), np.int64)

    loader2.set_batch_generator(batches)
    assert sum(1 for _ in loader2) == 3


def test_dataloader_prefetch_overlaps_producer_and_consumer():
    """With capacity>=2 the generator runs ahead while the consumer works:
    wall clock ~ max(gen, consume), not the sum (BufferedReader's point)."""
    main, startup, img, label, _ = _mnist_mlp()
    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=4)
    n, gen_s, use_s = 12, 0.03, 0.03

    def slow_batches():
        for _ in range(n):
            time.sleep(gen_s)
            yield np.zeros((8, 784), np.float32), np.zeros((8, 1), np.int64)

    loader.set_batch_generator(slow_batches)
    t0 = time.perf_counter()
    for _ in loader:
        time.sleep(use_s)
    dt = time.perf_counter() - t0
    serial = n * (gen_s + use_s)
    assert dt < serial * 0.8, f"no overlap: {dt:.3f}s vs serial {serial:.3f}s"


def test_dataloader_propagates_generator_errors():
    import pytest

    main, startup, img, label, _ = _mnist_mlp()
    loader = fluid.DataLoader.from_generator(feed_list=[img, label],
                                             capacity=2)

    def bad():
        yield np.zeros((4, 784), np.float32), np.zeros((4, 1), np.int64)
        raise RuntimeError("boom in generator")

    loader.set_batch_generator(bad)
    with pytest.raises(RuntimeError, match="boom in generator"):
        for _ in loader:
            pass


def test_pyreader_start_next_api():
    main, startup, img, label, _ = _mnist_mlp()
    reader = fluid.PyReader(feed_list=[img, label], capacity=2)
    reader.decorate_sample_generator(fluid.dataset.mnist.test(),
                                     batch_size=128)
    reader.start()
    feed = reader.next()
    assert feed["img"].shape == (128, 784)


def test_data_feeder():
    main, startup, img, label, _ = _mnist_mlp()
    feeder = fluid.DataFeeder(feed_list=[img, label], place=fluid.CPUPlace())
    samples = list(fluid.dataset.mnist.test()())[:16]
    fd = feeder.feed(samples)
    assert fd["img"].shape == (16, 784) and fd["img"].dtype == np.float32
    assert fd["label"].shape == (16, 1) and fd["label"].dtype == np.int64


def test_datasets_shapes():
    x, y = next(iter(fluid.dataset.cifar.train10()()))
    assert x.shape == (3072,) and x.dtype == np.float32
    xs, price = next(iter(fluid.dataset.uci_housing.train()()))
    assert xs.shape == (13,) and price.shape == (1,)
    words, sent = next(iter(fluid.dataset.imdb.train()()))
    assert isinstance(words, list) and sent in (0, 1)
    assert len(fluid.dataset.imdb.word_dict()) > 5000


def test_imdb_signal_is_learnable():
    """The synthetic fallback plants a band signal: a mean-embedding bag of
    words model must beat chance comfortably."""
    import collections

    docs = list(fluid.dataset.imdb.train()())[:512]
    half = 5149 // 2
    correct = 0
    for words, label in docs:
        frac_low = np.mean([w < half for w in words])
        correct += int((frac_low > 0.5) == (label == 1))
    assert correct / len(docs) > 0.9
