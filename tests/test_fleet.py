"""paddle_tpu.serving.fleet: wire schema, HTTP front-end, load-aware
router, warm-start AOT executable cache, and the frozen health()/ready()
wire contract.

Everything here runs IN-process (engines + threaded HTTP servers on
loopback) so the suite stays fast; the multi-PROCESS kill-one-replica
scenario is the CI gate's job (``tools/load_check.py --fleet``)."""
import http.client
import os
import pickle
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor, serving, trace
from paddle_tpu.resilience.deadline import DeadlineExceeded
from paddle_tpu.serving.fleet import (FleetRouter, Replica, ReplicaLost,
                                      RouterConfig, ServingFrontend,
                                      WireError, wire)


@pytest.fixture(autouse=True)
def _flags_reset():
    from paddle_tpu import flags as flags_mod

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    flags_mod._set_epoch += 1   # trace.enabled() memo must re-read


def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(**cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = serving.ServingConfig(max_batch=cfg_kw.pop("max_batch", 4),
                                **cfg_kw)
    return serving.ServingEngine(infer, feed_names=["x"],
                                 fetch_list=[pred], scope=scope,
                                 executor=exe, config=cfg)


def _feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


# ---------------------------------------------------------------------------
# wire schema
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float16", "int64", "bool"])
def test_wire_array_roundtrip_bit_exact(dtype):
    rng = np.random.RandomState(0)
    a = (rng.rand(3, 5) * 100).astype(dtype)
    b = wire.decode_array(wire.encode_array(a))
    assert b.dtype == a.dtype and b.shape == a.shape
    assert np.array_equal(a, b)
    b[0] = 0   # decoded arrays must be writable (np.frombuffer is not)


def test_wire_status_distinct_per_typed_outcome():
    """Every typed terminal outcome travels as a DISTINCT HTTP status —
    the router's admitted/unadmitted classification depends on it."""
    cases = [serving.Overloaded("x"), serving.CircuitOpen("x"),
             serving.EngineStopped("x"),
             DeadlineExceeded("x", 1.0, 2.0), serving.BatchFailed("x"),
             WireError("x")]
    statuses = [wire.status_for(e) for e in cases]
    assert len(set(statuses)) == len(statuses)
    assert wire.status_for(serving.Overloaded("x")) == 429
    assert wire.status_for(serving.EngineStopped("x")) == 410
    assert set(wire.UNADMITTED_STATUSES) == {429, 410}


def test_wire_error_body_roundtrips_typed_exceptions():
    e = serving.Overloaded("queue full", reason="queue_age")
    e.trace_id = "abc123"
    back = wire.error_from_body(wire.error_body(e))
    assert isinstance(back, serving.Overloaded)
    assert back.reason == "queue_age" and back.trace_id == "abc123"

    d = DeadlineExceeded("req #7", 0.5, 0.8)
    back = wire.error_from_body(wire.error_body(d))
    assert isinstance(back, DeadlineExceeded)
    assert back.budget_s == 0.5 and back.elapsed_s == 0.8
    assert back.transient is False   # retry must never absorb it

    c = serving.CircuitOpen("open", bucket="b4(x)")
    assert wire.error_from_body(wire.error_body(c)).bucket == "b4(x)"
    # unknown types degrade to the typed base, never a bare RuntimeError
    alien = wire.error_from_body({"error": {"type": "Weird",
                                            "message": "m"}})
    assert isinstance(alien, serving.ServingError)


def test_wire_refuses_newer_schema_and_malformed_bodies():
    with pytest.raises(WireError):
        wire.loads(b'{"schema_version": 99}')
    # a non-integer version is the same typed refusal, never a raw
    # ValueError/TypeError (the router catches WireError only)
    with pytest.raises(WireError):
        wire.loads(b'{"schema_version": "garbage"}')
    with pytest.raises(WireError):
        wire.loads(b'{"schema_version": null}')
    with pytest.raises(WireError):
        wire.loads(b"not json")
    with pytest.raises(WireError):
        wire.loads(b"[1, 2]")
    with pytest.raises(WireError):
        wire.decode_feed("nope")
    with pytest.raises(WireError):
        wire.decode_array({"dtype": "float32", "shape": [2], "b64": "!"})


def test_wire_slo_class_resolution():
    assert wire.resolve_priority({}) == wire.SLO_CLASSES["standard"]
    assert wire.resolve_priority({"slo_class": "interactive"}) \
        == wire.SLO_CLASSES["interactive"]
    # explicit priority wins over the class
    assert wire.resolve_priority({"priority": 7,
                                  "slo_class": "batch"}) == 7
    with pytest.raises(WireError):
        wire.resolve_priority({"slo_class": "platinum"})


def test_wire_admitted_flag_overrides_status_classification():
    """The front-end's explicit ``admitted`` flag is authoritative over
    the status map: an ADMITTED request that settled EngineStopped also
    travels as 410, and the router must never redispatch it (one request
    could reach two outcomes)."""
    stopped = serving.EngineStopped("stopped mid-flight")
    assert wire.response_is_unadmitted(
        410, wire.error_body(stopped, admitted=True)) is False
    assert wire.response_is_unadmitted(
        410, wire.error_body(stopped, admitted=False)) is True
    # bodies without the flag fall back to the status map
    assert wire.response_is_unadmitted(410, {}) is True
    assert wire.response_is_unadmitted(429, None) is True
    assert wire.response_is_unadmitted(500, {}) is False


def test_span_context_wire_roundtrip():
    ctx = trace.SpanContext("tid123", "sid456")
    back = trace.SpanContext.from_wire(ctx.to_wire())
    assert back.trace_id == "tid123" and back.span_id == "sid456"
    assert trace.SpanContext.from_wire(None) is None
    assert trace.SpanContext.from_wire("") is None
    assert trace.SpanContext.from_wire("no-separator") is None


# ---------------------------------------------------------------------------
# the frozen health()/ready() wire contract
# ---------------------------------------------------------------------------

def test_health_schema_frozen():
    """health() is a versioned wire contract since the fleet tier: the
    documented key set (docs/SERVING.md "Health probe schema") must be
    EXACTLY what the payload carries — a missing key breaks deployed
    routers, an undocumented one is schema drift."""
    eng = _engine()
    h = eng.health()
    assert set(h) == set(serving.HEALTH_SCHEMA_KEYS)
    assert h["schema_version"] == serving.HEALTH_SCHEMA_VERSION == 1
    assert isinstance(h["ready"], bool) and isinstance(eng.ready(), bool)
    assert isinstance(h["queue_depth"], int)
    assert isinstance(h["open_buckets"], list)
    # the routing-relevant accounting sub-keys the gate reads
    for k in ("submitted", "completed", "shed", "pending", "exact"):
        assert k in h["accounting"], k


def test_health_schema_same_for_generative_engine():
    """GenerativeEngine inherits the same frozen payload (one schema for
    every replica kind the router polls)."""
    # no model build needed: the schema comes from the base class; use a
    # plain engine pre-start and post-stop to cover both status values
    eng = _engine()
    assert set(eng.health()) == set(serving.HEALTH_SCHEMA_KEYS)
    eng.start()
    try:
        assert eng.health()["ready"] is True
    finally:
        eng.stop()
    h = eng.health()
    assert h["status"] == "stopped" and h["ready"] is False
    assert set(h) == set(serving.HEALTH_SCHEMA_KEYS)


def test_submit_trace_parent_joins_caller_trace():
    """A trace context carried over the wire parents the request root:
    the engine-side outcome and the caller share ONE trace id."""
    fluid.set_flags({"FLAGS_trace": 1})
    eng = _engine()
    eng.warm_up()
    with eng:
        ctx = trace.SpanContext("feedf00d00000001", "feedf00d00000002")
        fut = eng.submit(_feed(), trace_parent=ctx)
        fut.result(timeout=60)
    assert fut.trace_id == "feedf00d00000001"
    ro = eng.accounting()["recent_outcomes"]
    assert ro[-1]["trace_id"] == "feedf00d00000001"


# ---------------------------------------------------------------------------
# front-end over HTTP
# ---------------------------------------------------------------------------

@pytest.fixture()
def frontend():
    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    fe = ServingFrontend(eng, replica_id="t0")
    fe.start()
    yield fe
    fe.stop(wait_inflight_s=2.0)
    eng.stop(drain=False)


def _post(port, path, body, headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=wire.dumps(body),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, wire.loads(resp.read())
    finally:
        conn.close()


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, wire.loads(resp.read())
    finally:
        conn.close()


def test_frontend_submit_roundtrip_bit_exact(frontend):
    feed = _feed(seed=3)
    status, body = _post(frontend.port, "/v1/submit",
                         {"schema_version": wire.WIRE_SCHEMA_VERSION,
                          "feed": wire.encode_feed(feed)})
    assert status == 200
    outs = wire.decode_outputs(body)
    # same engine, same feed, in-process: the wire must not perturb bits
    direct = frontend.engine.submit(_feed(seed=3)).result(timeout=60)
    assert np.array_equal(outs[0], direct[0])


def test_frontend_validation_is_400_not_an_outcome(frontend):
    eng = frontend.engine
    before = eng.accounting()["submitted"]
    status, body = _post(frontend.port, "/v1/submit",
                         {"feed": {"wrong_name":
                                   wire.encode_array(np.zeros((1, 13),
                                                              np.float32))}})
    assert status == 400
    assert body["error"]["type"] == "ValueError"
    # a caller bug never enters the accounting
    assert eng.accounting()["submitted"] == before
    status, _ = _post(frontend.port, "/v1/submit", {"feed": "garbage"})
    assert status == 400


def test_frontend_stopped_engine_maps_to_410(frontend):
    frontend.engine.stop(drain=False)
    status, body = _post(frontend.port, "/v1/submit",
                         {"feed": wire.encode_feed(_feed())})
    assert status == 410
    assert body["error"]["type"] == "EngineStopped"


def test_frontend_unknown_route_404(frontend):
    status, _ = _post(frontend.port, "/v1/nope", {})
    assert status == 404
    status, _ = _get(frontend.port, "/nope")
    assert status == 404


def test_frontend_healthz_readyz(frontend):
    status, h = _get(frontend.port, "/healthz")
    assert status == 200
    assert set(serving.HEALTH_SCHEMA_KEYS) <= set(h)
    assert h["replica_id"] == "t0"
    status, r = _get(frontend.port, "/readyz")
    assert status == 200 and r["ready"] is True
    frontend.engine.stop(drain=True)
    status, r = _get(frontend.port, "/readyz")
    assert status == 503 and r["ready"] is False
    # healthz keeps answering on a drained replica (the router's poll)
    status, h = _get(frontend.port, "/healthz")
    assert status == 200 and h["ready"] is False
    assert h["status"] == "stopped"


def test_frontend_trace_header_propagates(frontend):
    fluid.set_flags({"FLAGS_trace": 1})
    ctx = trace.SpanContext("cafecafe00000001", "cafecafe00000002")
    status, body = _post(frontend.port, "/v1/submit",
                         {"feed": wire.encode_feed(_feed())},
                         headers={wire.TRACE_HEADER: ctx.to_wire()})
    assert status == 200
    assert body["trace_id"] == "cafecafe00000001"
    ro = frontend.engine.accounting()["recent_outcomes"]
    assert ro[-1]["trace_id"] == "cafecafe00000001"


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------

@pytest.fixture()
def fleet2():
    """Two in-process replicas behind a router (no poll thread — tests
    drive poll_now() explicitly for determinism)."""
    engines, fronts = [], []
    for i in range(2):
        eng = _engine(batch_window_s=0.005)
        eng.warm_up()
        eng.start()
        fe = ServingFrontend(eng, replica_id=f"r{i}")
        fe.start()
        engines.append(eng)
        fronts.append(fe)
    router = FleetRouter([Replica(f"r{i}", "127.0.0.1", fe.port)
                          for i, fe in enumerate(fronts)])
    router.poll_now()
    yield router, engines, fronts
    router.stop()
    for fe in fronts:
        fe.stop(wait_inflight_s=2.0)
    for eng in engines:
        if not eng._stopped:
            eng.stop(drain=False)


def test_router_submit_completes_with_exact_accounting(fleet2):
    router, engines, _ = fleet2
    for i in range(6):
        outs = router.submit(_feed(seed=i))
        assert outs[0].shape == (1, 4)
    acct = router.accounting()
    assert acct["exact"] and acct["completed"] == 6
    assert acct["submitted"] == 6 and acct["pending"] == 0


def test_router_honors_drain(fleet2):
    """A drained replica stops receiving traffic; everything lands on
    the sibling. Nothing is shed, nothing errors."""
    router, engines, _ = fleet2
    engines[0].stop(drain=True)   # preemption: ready() flips false
    router.poll_now()
    before = engines[1].accounting()["submitted"]
    for i in range(5):
        router.submit(_feed(seed=i))
    assert engines[1].accounting()["submitted"] - before == 5
    acct = router.accounting()
    assert acct["completed"] == 5 and acct["exact"]
    assert acct["stopped"] == 0 and acct["replica_lost"] == 0


def test_router_all_draining_is_typed_overloaded_not_a_hang(fleet2):
    router, engines, _ = fleet2
    for eng in engines:
        eng.stop(drain=True)
    router.poll_now()
    t0 = time.monotonic()
    with pytest.raises(serving.Overloaded) as ei:
        router.submit(_feed())
    assert ei.value.reason == "no_ready_replica"
    assert time.monotonic() - t0 < 5.0
    acct = router.accounting()
    assert acct["shed"] == 1 and acct["exact"]


def test_router_dead_replica_between_poll_and_dispatch_retries(fleet2):
    """The replica dies AFTER the poll said ready: the connection
    refusal is provably unadmitted, so the router retries exactly once
    on the sibling and the request completes."""
    router, engines, fronts = fleet2
    router.poll_now()               # both look ready
    # kill r0 without a poll: its snapshot still says ready
    fronts[0].stop(wait_inflight_s=0.5)
    engines[0].stop(drain=False)
    retries0 = router.accounting()["retries"]
    completed = 0
    for i in range(6):
        router.submit(_feed(seed=i))
        completed += 1
    assert completed == 6
    acct = router.accounting()
    assert acct["completed"] == 6 and acct["exact"]
    assert acct["retries"] - retries0 >= 1     # some dispatches hit r0
    assert acct["replica_lost"] == 0


def test_router_retry_is_exactly_once_then_typed(fleet2):
    """Both replicas dead with stale-ready snapshots: one retry, then a
    typed outcome — never a loop, never a hang."""
    router, engines, fronts = fleet2
    router.poll_now()
    for fe in fronts:
        fe.stop(wait_inflight_s=0.5)
    for eng in engines:
        eng.stop(drain=False)
    retries0 = router.accounting()["retries"]
    t0 = time.monotonic()
    with pytest.raises((ReplicaLost, serving.Overloaded)):
        router.submit(_feed())
    assert time.monotonic() - t0 < 20.0
    acct = router.accounting()
    assert acct["retries"] - retries0 == 1
    assert acct["exact"]


def test_router_load_aware_pick_prefers_lower_pressure(fleet2):
    router, _, _ = fleet2
    r0, r1 = router.replicas
    base = {"ok": True, "ready": True, "degraded": False,
            "open_buckets": 0, "status": "ok", "polled_at": 0.0}
    r0._update({**base, "queue_depth": 9})
    r1._update({**base, "queue_depth": 2})
    assert router._pick() is r1
    # degradation outweighs a small queue edge
    r0._update({**base, "queue_depth": 3, "degraded": True})
    r1._update({**base, "queue_depth": 8})
    assert router._pick() is r1
    # open breakers push a replica down too
    r0._update({**base, "queue_depth": 0, "open_buckets": 2})
    r1._update({**base, "queue_depth": 5})
    assert router._pick() is r1


def test_router_negative_control_ignores_drain(fleet2):
    """The CI gate's negative control wiring: with honor_drain off the
    router keeps dispatching to a stopped replica and requests reach
    typed stopped outcomes (proving the gate detects a drain-blind
    router)."""
    router, engines, fronts = fleet2
    nc = FleetRouter(
        [Replica(f"r{i}", "127.0.0.1", fe.port)
         for i, fe in enumerate(fronts)],
        config=RouterConfig(honor_drain=False, retry_unadmitted=False))
    nc.poll_now()
    engines[0].stop(drain=True)
    nc.poll_now()
    outcomes = {"completed": 0, "stopped": 0}
    for i in range(8):
        try:
            nc.submit(_feed(seed=i))
            outcomes["completed"] += 1
        except serving.EngineStopped:
            outcomes["stopped"] += 1
    assert outcomes["stopped"] >= 1          # kept routing to the corpse
    assert nc.accounting()["exact"]


class _CannedReplica:
    """A fake front-end answering canned responses — for routing-policy
    tests that need wire-level control a real engine cannot give
    deterministically (e.g. a 410 whose body says the request WAS
    admitted)."""

    def __init__(self, responses=()):
        self.requests = 0
        self.responses = list(responses)
        self.health = {"schema_version": 1, "status": "ok", "ready": True,
                       "queue_depth": 0, "degraded": False,
                       "open_buckets": [], "generative": False}
        outer = self

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status, obj):
                raw = wire.dumps(obj)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                self._json(200, outer.health)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                outer.requests += 1
                if outer.responses:
                    status, body = outer.responses.pop(0)
                else:
                    status, body = 500, {"error": {
                        "type": "ServingError",
                        "message": "no canned response left"}}
                self._json(status, body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.port = self.server.server_address[1]

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_router_never_retries_an_admitted_410():
    """An engine that stops WITHOUT drain settles its admitted requests
    EngineStopped — the front-end ships that as 410 with
    ``admitted: true``. The router must raise it as-is: redispatching
    would run the request a second time on the sibling."""
    stopped = serving.EngineStopped("engine stopped holding the request")
    victim = _CannedReplica(responses=[
        (410, wire.error_body(stopped, admitted=True))])
    sibling = _CannedReplica(responses=[
        (200, wire.encode_outputs([np.zeros((1, 4), np.float32)]))])
    try:
        sibling.health["queue_depth"] = 50   # pin the pick to the victim
        router = FleetRouter([Replica("v", "127.0.0.1", victim.port),
                              Replica("s", "127.0.0.1", sibling.port)])
        router.poll_now()
        with pytest.raises(serving.EngineStopped):
            router.submit(_feed())
        assert victim.requests == 1
        assert sibling.requests == 0         # never redispatched
        acct = router.accounting()
        assert acct["retries"] == 0
        assert acct["stopped"] == 1 and acct["exact"]
    finally:
        victim.close()
        sibling.close()


def test_router_retries_unadmitted_410_on_a_sibling():
    """The same 410 status WITHOUT the admitted claim (a submit-time
    rejection from a draining engine) stays retryable — the request
    completes on the sibling, exactly one outcome."""
    draining = serving.EngineStopped("rejected at admission: draining")
    want = np.ones((1, 4), np.float32)
    victim = _CannedReplica(responses=[
        (410, wire.error_body(draining, admitted=False))])
    sibling = _CannedReplica(responses=[(200, wire.encode_outputs([want]))])
    try:
        sibling.health["queue_depth"] = 50   # victim picked first
        router = FleetRouter([Replica("v", "127.0.0.1", victim.port),
                              Replica("s", "127.0.0.1", sibling.port)])
        router.poll_now()
        outs = router.submit(_feed())
        assert np.array_equal(outs[0], want)
        assert victim.requests == 1 and sibling.requests == 1
        acct = router.accounting()
        assert acct["retries"] == 1
        assert acct["completed"] == 1 and acct["exact"]
    finally:
        victim.close()
        sibling.close()


def test_router_poll_tolerates_future_health_schema():
    """/healthz carries the HEALTH schema version (its own frozen
    contract), not the request wire version — a replica speaking a newer
    health schema must still poll as ready, not be refused through the
    wire-version gate."""
    rep = _CannedReplica()
    try:
        rep.health.update(schema_version=99, queue_depth=3)
        r = Replica("h0", "127.0.0.1", rep.port)
        FleetRouter([r]).poll_now()
        snap = r.snapshot()
        assert snap["ok"] and snap["ready"]
        assert snap["queue_depth"] == 3
    finally:
        rep.close()


def test_router_generate_requires_generative_capability(fleet2):
    """Mixed-fleet routing: request/response replicas advertise
    ``generative: false`` in /healthz, so generate() never dispatches to
    one — a fleet with none ready sheds typed instead of collecting a
    400 from a replica that cannot stream."""
    router, engines, _ = fleet2
    router.poll_now()
    with pytest.raises(serving.Overloaded) as ei:
        router.generate([1, 2, 3], max_new_tokens=2)
    assert ei.value.reason == "no_generative_replica"
    for eng in engines:                      # nothing was submitted
        assert eng.accounting()["submitted"] == 0
    acct = router.accounting()
    assert acct["shed"] == 1 and acct["exact"]


# ---------------------------------------------------------------------------
# streaming through the fleet (GenerativeEngine replica)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_fleet():
    from paddle_tpu.serving.fleet.replica import build_probe

    cfg = serving.ServingConfig(max_batch=4, queue_depth=64)
    eng, _ = build_probe("gpt_tiny", cfg)
    eng.warm_up()
    eng.start()
    fe = ServingFrontend(eng, replica_id="g0")
    fe.start()
    router = FleetRouter([Replica("g0", "127.0.0.1", fe.port)])
    router.poll_now()
    yield router, eng, fe
    router.stop()
    fe.stop(wait_inflight_s=2.0)
    if not eng._stopped:
        eng.stop(drain=False)


def test_router_generate_streams_exact_token_count(gpt_fleet):
    router, eng, _ = gpt_fleet
    toks = list(router.generate([5, 3, 1], max_new_tokens=6))
    assert len(toks) == 6
    assert all(isinstance(t, int) for t in toks)
    acct = router.accounting()
    assert acct["exact"] and acct["completed"] >= 1


def test_router_generate_mid_drain_partials_then_typed(gpt_fleet):
    """The satellite edge case: the streaming request's replica drains
    (stop without drain) mid-stream — partial tokens are delivered,
    then the typed terminal outcome surfaces; accounting stays exact."""
    router, eng, _ = gpt_fleet
    gen = router.generate([2, 2, 2], max_new_tokens=24)
    got = []
    with pytest.raises((serving.EngineStopped, serving.BatchFailed,
                        ReplicaLost)):
        for i, t in enumerate(gen):
            got.append(t)
            if i == 1:
                eng.stop(drain=False)
    assert len(got) >= 2            # partials were delivered first
    assert len(got) < 24            # and the stream really died early
    assert router.accounting()["exact"]


# ---------------------------------------------------------------------------
# warm-start AOT executable cache
# ---------------------------------------------------------------------------

@pytest.fixture()
def _no_jax_persistent_cache():
    """The suite's jax persistent compilation cache (conftest) would
    serve these tests' compiles, and an executable loaded FROM that
    cache serializes to an unloadable blob on XLA:CPU (the validated
    non-publish path). Disable it so the warm-start cache is actually
    exercised; restore after."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _aot_delta(fn):
    """(hits, misses, saves) deltas around fn()."""
    def read():
        return (monitor.metric_value("aot_cache_hits_total", 0.0),
                monitor.metric_value("aot_cache_misses_total", 0.0),
                monitor.metric_value("aot_cache_saves_total", 0.0))
    before = read()
    out = fn()
    after = read()
    return out, tuple(a - b for a, b in zip(after, before))


def test_aot_cache_roundtrip_fresh_executor_bit_exact(
        tmp_path, _no_jax_persistent_cache):
    fluid.set_flags({"FLAGS_aot_cache_dir": str(tmp_path)})
    infer, startup, pred = _build_infer()
    scope = fluid.Scope()
    exe1 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe1.run(startup)
    feed = _feed(seed=11)

    out1, d1 = _aot_delta(lambda: exe1.run(infer, feed=feed,
                                           fetch_list=[pred],
                                           scope=scope))
    assert d1[2] >= 1 and d1[0] == 0     # cold: saved, no hit
    assert any(f.endswith(".aotx") for f in os.listdir(tmp_path))

    # a FRESH executor (fresh step cache, same process) must load the
    # serialized executable instead of compiling — and match bit-exactly
    exe2 = fluid.Executor(fluid.CPUPlace())
    out2, d2 = _aot_delta(lambda: exe2.run(infer, feed=feed,
                                           fetch_list=[pred],
                                           scope=scope))
    assert d2[0] >= 1                     # warm: loaded
    assert np.array_equal(out1[0], out2[0])


def test_aot_cache_serves_run_chained(tmp_path,
                                      _no_jax_persistent_cache):
    fluid.set_flags({"FLAGS_aot_cache_dir": str(tmp_path)})
    infer, startup, pred = _build_infer()
    scope = fluid.Scope()
    exe1 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe1.run(startup)
    feed = _feed(seed=5)
    out1, d1 = _aot_delta(lambda: exe1.run_chained(
        infer, feed=feed, fetch_list=[pred], steps=3, scope=scope))
    assert d1[2] >= 1
    exe2 = fluid.Executor(fluid.CPUPlace())
    out2, d2 = _aot_delta(lambda: exe2.run_chained(
        infer, feed=feed, fetch_list=[pred], steps=3, scope=scope))
    assert d2[0] >= 1
    assert np.array_equal(np.asarray(out1[0]), np.asarray(out2[0]))


def test_aot_cache_key_changes_with_config_and_shape(tmp_path):
    from paddle_tpu import aot_cache

    infer, _, pred = _build_infer()
    args_a = ([np.zeros((1, 13), np.float32)], [], [], None)
    args_b = ([np.zeros((2, 13), np.float32)], [], [], None)
    parts = ("run", infer, (pred,), (), None)
    k1 = aot_cache.executable_key(parts, args_a)
    assert k1 == aot_cache.executable_key(parts, args_a)   # stable
    assert k1 != aot_cache.executable_key(parts, args_b)   # batch shape
    parts_opts = ("run", infer, (pred,),
                  (("xla_cpu_enable_fast_min_max", True),), None)
    assert k1 != aot_cache.executable_key(parts_opts, args_a)
    parts_chained = ("chained", infer, (pred,), (), None, 3)
    assert k1 != aot_cache.executable_key(parts_chained, args_a)


def test_aot_cache_corrupt_and_stale_entries_degrade(
        tmp_path, _no_jax_persistent_cache):
    """A torn/garbage/wrong-version entry is a MISS with one warning,
    never an error: the executor compiles as if uncached."""
    fluid.set_flags({"FLAGS_aot_cache_dir": str(tmp_path)})
    infer, startup, pred = _build_infer()
    scope = fluid.Scope()
    exe1 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe1.run(startup)
    feed = _feed(seed=2)
    out1 = exe1.run(infer, feed=feed, fetch_list=[pred], scope=scope)
    entries = [f for f in os.listdir(tmp_path) if f.endswith(".aotx")]
    assert entries
    # corrupt every entry
    for f in entries:
        with open(os.path.join(tmp_path, f), "wb") as fh:
            fh.write(b"not a pickle")
    exe2 = fluid.Executor(fluid.CPUPlace())
    out2 = exe2.run(infer, feed=feed, fetch_list=[pred], scope=scope)
    assert np.array_equal(out1[0], out2[0])
    # stale version: a well-formed entry from a "different jax" (exe2's
    # recompile re-published SOME entries over the garbage; the startup
    # program's entry stays corrupt — skip what cannot parse)
    for f in os.listdir(tmp_path):
        if not f.endswith(".aotx"):
            continue
        p = os.path.join(tmp_path, f)
        try:
            with open(p, "rb") as fh:
                blob = pickle.load(fh)
        except Exception:
            continue
        blob["jax"] = "0.0.1-alien"
        with open(p, "wb") as fh:
            pickle.dump(blob, fh)
    hits0 = monitor.metric_value("aot_cache_hits_total", 0.0)
    exe3 = fluid.Executor(fluid.CPUPlace())
    out3 = exe3.run(infer, feed=feed, fetch_list=[pred], scope=scope)
    assert np.array_equal(out1[0], out3[0])
    assert monitor.metric_value("aot_cache_hits_total", 0.0) == hits0


def test_aot_cache_off_by_default(tmp_path):
    """Without FLAGS_aot_cache_dir nothing is written anywhere."""
    infer, startup, pred = _build_infer()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    _, d = _aot_delta(lambda: exe.run(infer, feed=_feed(),
                                      fetch_list=[pred], scope=scope))
    assert d == (0.0, 0.0, 0.0)
