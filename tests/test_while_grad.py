"""Differentiable bounded While (VERDICT r2 item 8; reference
controlflow/while_op.cc WhileGradOp): an RNN written with layers.While must
train exactly like the same cell written as StaticRNN, including with a
runtime (data-dependent) trip count below the static bound."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from paddle_tpu.param_attr import ParamAttr

T, D, H, B = 5, 4, 8, 16


def _build_while(max_len=T, n_feed=False):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[T, B, D],
                                  append_batch_size=False)
            y = fluid.layers.data("y", shape=[B, 1], append_batch_size=False)
            if n_feed:
                n = fluid.layers.data("n", shape=[1], dtype="int64",
                                      append_batch_size=False)
            else:
                n = fluid.layers.fill_constant([1], "int64", T)
            i = fluid.layers.fill_constant([1], "int64", 0)
            h = fluid.layers.fill_constant([B, H], "float32", 0.0)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond, max_len=max_len)
            with w.block():
                xt = fluid.layers.squeeze(fluid.layers.gather(x, i), axes=[0])
                merged = fluid.layers.concat([xt, h], axis=1)
                nh = fluid.layers.tanh(fluid.layers.fc(
                    merged, H, bias_attr=False,
                    param_attr=ParamAttr(name="cell_w"), name="cell"))
                fluid.layers.assign(nh, h)
                fluid.layers.increment(i, value=1)
                fluid.layers.assign(fluid.layers.less_than(i, n), cond)
            pred = fluid.layers.fc(h, 1, param_attr=ParamAttr(name="out_w"),
                                   bias_attr=False, name="out")
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _build_static(steps=T):
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[steps, B, D],
                                  append_batch_size=False)
            y = fluid.layers.data("y", shape=[B, 1], append_batch_size=False)
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                xt = rnn.step_input(x)
                hp = rnn.memory(shape=[H], batch_ref=x)
                merged = fluid.layers.concat([xt, hp], axis=1)
                nh = fluid.layers.tanh(fluid.layers.fc(
                    merged, H, bias_attr=False,
                    param_attr=ParamAttr(name="cell_w"), name="cell"))
                rnn.update_memory(hp, nh)
                rnn.step_output(nh)
            states = rnn()
            h = fluid.layers.squeeze(
                fluid.layers.slice(states, axes=[0], starts=[steps - 1],
                                   ends=[steps]), axes=[0])
            pred = fluid.layers.fc(h, 1, param_attr=ParamAttr(name="out_w"),
                                   bias_attr=False, name="out")
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _train(model, feeds, steps=8, seed=9):
    main, startup, loss = model
    main.random_seed = seed
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            (lv,) = exe.run(main, feed=feeds, fetch_list=[loss.name])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


RNG = np.random.RandomState(0)
XB = RNG.randn(T, B, D).astype(np.float32)
YB = RNG.randn(B, 1).astype(np.float32)


def test_while_rnn_trains_like_static_rnn():
    lw = _train(_build_while(), {"x": XB, "y": YB})
    ls = _train(_build_static(), {"x": XB, "y": YB})
    np.testing.assert_allclose(lw, ls, rtol=1e-4, atol=1e-6)
    assert lw[-1] < lw[0]


def test_while_rnn_dynamic_trip_count():
    """Trip count fed at runtime (3 < max_len=5): grads must cover exactly
    the executed steps — equivalent to a StaticRNN over x[:3]."""
    n = np.array([3], np.int64)
    lw = _train(_build_while(n_feed=True), {"x": XB, "y": YB, "n": n})
    ls = _train(_build_static(steps=3), {"x": XB[:3], "y": YB})
    np.testing.assert_allclose(lw, ls, rtol=1e-4, atol=1e-6)


def test_while_grad_requires_max_len():
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            i = fluid.layers.fill_constant([1], "int64", 0)
            n = fluid.layers.fill_constant([1], "int64", 3)
            h = fluid.layers.fc(x, 4, name="f")
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond)  # no max_len
            with w.block():
                fluid.layers.assign(fluid.layers.scale(h, scale=2.0), h)
                fluid.layers.increment(i, value=1)
                fluid.layers.assign(fluid.layers.less_than(i, n), cond)
            loss = fluid.layers.mean(h)
            fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(Exception, match="max_len"):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss.name])


def test_while_max_len_bounds_forward_and_backward_consistently():
    """Review regression: a condition outliving max_len must see the SAME
    trip count forward (loss) and backward (grads) — max_len bounds both."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[1], append_batch_size=False,
                                  stop_gradient=False)
            i = fluid.layers.fill_constant([1], "int64", 0)
            n = fluid.layers.fill_constant([1], "int64", 4)  # wants 4 iters
            h = fluid.layers.assign(x)
            cond = fluid.layers.less_than(i, n)
            w = fluid.layers.While(cond, max_len=2)  # but bound is 2
            with w.block():
                fluid.layers.assign(
                    fluid.layers.elementwise_mul(h, h), h)  # h <- h^2
                fluid.layers.increment(i, value=1)
                fluid.layers.assign(fluid.layers.less_than(i, n), cond)
            loss = fluid.layers.mean(h)
            (gx,) = fluid.gradients([loss], [x])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        lv, gv = exe.run(main, feed={"x": np.array([2.0], np.float32)},
                         fetch_list=[loss.name, gx.name])
    # 2 iterations: h = ((2^2)^2) = 16, dh/dx = 4x^3 = 32
    assert float(np.asarray(lv)) == 16.0
    assert float(np.asarray(gv).reshape(-1)[0]) == 32.0
