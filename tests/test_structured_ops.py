"""OpTests for the structured-prediction op family (ops/structured.py):
linear_chain_crf, crf_decoding, nce, hierarchical_sigmoid, edit_distance,
ctc_align, chunk_eval — each against an independent numpy oracle
implementing the reference kernel semantics (linear_chain_crf_op.h:172,
crf_decoding_op.h, nce_op.h, hierarchical_sigmoid_op.h +
matrix_bit_code.h SimpleCode, edit_distance_op.h, ctc_align_op.h,
chunk_eval_op.h)."""
import math

import numpy as np
import pytest

import paddle_tpu as fluid
from op_test import OpTest

RNG = np.random.RandomState(7)


# -- numpy oracles ----------------------------------------------------------

def np_crf_nll(em, w, label, length):
    """Reference ForwardOneSequence in log space; returns -(gold - logZ)."""
    b, t, d = em.shape
    start, end, trans = w[0], w[1], w[2:]
    out = np.zeros((b, 1), np.float64)
    for i in range(b):
        L = int(length[i])
        x, y = em[i, :L].astype(np.float64), label[i, :L]
        gold = start[y[0]] + x[np.arange(L), y].sum() + end[y[L - 1]]
        for k in range(1, L):
            gold += trans[y[k - 1], y[k]]
        alpha = start + x[0]
        for k in range(1, L):
            alpha = np.array([
                np.logaddexp.reduce(alpha + trans[:, j]) + x[k, j]
                for j in range(d)])
        logz = np.logaddexp.reduce(alpha + end)
        out[i, 0] = logz - gold
    return out


def np_viterbi(em, w, length):
    b, t, d = em.shape
    start, end, trans = w[0], w[1], w[2:]
    paths = np.zeros((b, t), np.int64)
    for i in range(b):
        L = int(length[i])
        x = em[i, :L].astype(np.float64)
        delta = start + x[0]
        bp = np.zeros((L, d), np.int64)
        for k in range(1, L):
            scores = delta[:, None] + trans
            bp[k] = scores.argmax(0)
            delta = scores.max(0) + x[k]
        tag = int((delta + end).argmax())
        for k in range(L - 1, -1, -1):
            paths[i, k] = tag
            if k:
                tag = int(bp[k][tag])
    return paths


def np_edit_distance(h, hl, r, rl):
    out = np.zeros((len(h), 1), np.float32)
    for i in range(len(h)):
        a, bseq = list(h[i][:hl[i]]), list(r[i][:rl[i]])
        n, m = len(a), len(bseq)
        dp = np.zeros((n + 1, m + 1))
        dp[:, 0] = np.arange(n + 1)
        dp[0, :] = np.arange(m + 1)
        for p in range(1, n + 1):
            for q in range(1, m + 1):
                dp[p, q] = min(dp[p - 1, q] + 1, dp[p, q - 1] + 1,
                               dp[p - 1, q - 1] + (a[p - 1] != bseq[q - 1]))
        out[i, 0] = dp[n, m] if m else n
    return out


def np_hsigmoid(xv, wv, bias, label, num_classes):
    b = xv.shape[0]
    cost = np.zeros((b, 1), np.float64)
    for i in range(b):
        c = int(label[i]) + num_classes
        length = int(math.floor(math.log2(c)))
        for j in range(length):
            idx = (c >> (j + 1)) - 1
            bit = (c >> j) & 1
            z = xv[i] @ wv[idx] + bias[idx]
            cost[i, 0] += math.log1p(math.exp(-abs(z))) + max(z, 0) - z * bit
    return cost


# -- tests ------------------------------------------------------------------

class TestLinearChainCRF(OpTest):
    def setup(self):
        b, t, d = 3, 6, 4
        em = RNG.randn(b, t, d).astype(np.float32)
        w = (0.3 * RNG.randn(d + 2, d)).astype(np.float32)
        length = np.array([6, 4, 1], np.int32)
        label = RNG.randint(0, d, (b, t)).astype(np.int64)
        nll = np_crf_nll(em, w, label, length).astype(np.float32)
        self.op_type = "linear_chain_crf"
        self.inputs = {"Emission": em, "Transition": w, "Label": label,
                       "Length": length}
        self.attrs = {}
        self.outputs = {"LogLikelihood": nll}

    def test(self):
        self.check_output(atol=2e-4, rtol=2e-4,
                          no_check=("Alpha", "EmissionExps",
                                    "TransitionExps"))
        self.check_grad(["Emission", "Transition"], "LogLikelihood",
                        delta=1e-2, max_relative_error=0.02)


class TestCRFDecoding(OpTest):
    def setup(self):
        b, t, d = 3, 7, 5
        em = RNG.randn(b, t, d).astype(np.float32)
        w = (0.5 * RNG.randn(d + 2, d)).astype(np.float32)
        length = np.array([7, 3, 5], np.int32)
        self.op_type = "crf_decoding"
        self.inputs = {"Emission": em, "Transition": w, "Length": length}
        self.attrs = {}
        self.outputs = {"ViterbiPath": np_viterbi(em, w, length)}

    def test(self):
        self.check_output()


def test_crf_decoding_label_mode():
    """With Label, output is the 0/1 per-position correctness mask."""
    b, t, d = 2, 5, 3
    em = RNG.randn(b, t, d).astype(np.float32)
    w = (0.5 * RNG.randn(d + 2, d)).astype(np.float32)
    length = np.array([5, 4], np.int32)
    path = np_viterbi(em, w, length)
    label = path.copy()
    label[0, 2] = (label[0, 2] + 1) % d  # one wrong position
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        blk = fluid.default_main_program().global_block
        mk = lambda n, a: blk.create_var(
            name=n, shape=a.shape,
            dtype=str(a.dtype).replace("int32", "int32"), is_data=True)
        vs = {n: mk(n, a) for n, a in
              [("em", em), ("w", w), ("lbl", label), ("len", length)]}
        outv = blk.create_var(name="out", shape=(b, t), dtype="int64")
        blk.append_op("crf_decoding",
                      inputs={"Emission": vs["em"], "Transition": vs["w"],
                              "Label": vs["lbl"], "Length": vs["len"]},
                      outputs={"ViterbiPath": outv})
        exe = fluid.Executor(fluid.CPUPlace())
        got = exe.run(fluid.default_main_program(),
                      feed={"em": em, "w": w, "lbl": label, "len": length},
                      fetch_list=["out"])[0]
    expect = (path == label).astype(np.int64)
    expect[0, length[0]:] = 0
    expect[1, length[1]:] = 0
    np.testing.assert_array_equal(np.asarray(got), expect)


class TestEditDistance(OpTest):
    def setup(self):
        b, th, tr = 4, 8, 7
        hyp = RNG.randint(1, 6, (b, th)).astype(np.int64)
        ref = RNG.randint(1, 6, (b, tr)).astype(np.int64)
        hl = np.array([8, 5, 3, 0], np.int32)
        rl = np.array([7, 7, 2, 4], np.int32)
        self.op_type = "edit_distance"
        self.inputs = {"Hyps": hyp, "Refs": ref, "HypsLength": hl,
                       "RefsLength": rl}
        self.attrs = {"normalized": False}
        self.outputs = {"Out": np_edit_distance(hyp, hl, ref, rl),
                        "SequenceNum": np.array([b], np.int64)}

    def test(self):
        self.check_output()


class TestEditDistanceNormalized(TestEditDistance):
    def setup(self):
        super().setup()
        self.attrs = {"normalized": True}
        rl = self.inputs["RefsLength"]
        self.outputs["Out"] = (
            self.outputs["Out"] / np.maximum(rl, 1)[:, None]
        ).astype(np.float32)


class TestCTCAlign(OpTest):
    def setup(self):
        inp = np.array([[0, 1, 1, 0, 2, 2, 0, 3],
                        [1, 1, 2, 0, 0, 2, 4, 4]], np.int64)
        ilen = np.array([8, 6], np.int32)
        # merge ADJACENT repeats then drop blanks (blank=0): row 1's
        # [1,1,2,0,0,2] keeps both 2s — they are blank-separated (CTC rule)
        expect = np.zeros((2, 8), np.int64)
        expect[0, :3] = [1, 2, 3]
        expect[1, :3] = [1, 2, 2]
        self.op_type = "ctc_align"
        self.inputs = {"Input": inp, "InputLength": ilen}
        self.attrs = {"blank": 0, "merge_repeated": True}
        self.outputs = {"Output": expect,
                        "OutputLength": np.array([3, 3], np.int32)}

    def test(self):
        self.check_output()


class TestHSigmoid(OpTest):
    def setup(self):
        b, d, c = 5, 6, 7
        xv = RNG.randn(b, d).astype(np.float32)
        wv = (0.5 * RNG.randn(c - 1, d)).astype(np.float32)
        bias = (0.1 * RNG.randn(c - 1)).astype(np.float32)
        label = RNG.randint(0, c, (b, 1)).astype(np.int64)
        self.op_type = "hierarchical_sigmoid"
        self.inputs = {"X": xv, "W": wv, "Bias": bias, "Label": label}
        self.attrs = {"num_classes": c}
        self.outputs = {
            "Out": np_hsigmoid(xv, wv, bias, label, c).astype(np.float32)}

    def test(self):
        self.check_output(atol=1e-4, rtol=1e-4, no_check=("PreOut",))
        self.check_grad(["X", "W", "Bias"], "Out", delta=1e-2,
                        max_relative_error=0.02)


def test_nce_cost_matches_reference_formula():
    """Exact oracle (reference nce_op.h:237-245): fetch the op's own
    SampleLabels, recompute cost as -log(o/(o+b)) / -log(b/(o+b)) with
    o = sigmoid(s), b = k*q(class) in numpy, compare."""
    b, d, c, k = 4, 6, 12, 3
    xv = RNG.randn(b, d).astype(np.float32)
    wv = (0.5 * RNG.randn(c, d)).astype(np.float32)
    bias = (0.1 * RNG.randn(c)).astype(np.float32)
    label = RNG.randint(0, c, (b, 1)).astype(np.int64)
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        blk = fluid.default_main_program().global_block
        mk = lambda n, a, dt: blk.create_var(name=n, shape=a.shape,
                                             dtype=dt, is_data=True)
        vs = {"x": mk("x", xv, "float32"), "w": mk("w", wv, "float32"),
              "bias": mk("bias", bias, "float32"),
              "lbl": mk("lbl", label, "int64")}
        cost_v = blk.create_var(name="cost", shape=(b, 1), dtype="float32")
        sl_v = blk.create_var(name="slog", shape=(b, 1 + k),
                              dtype="float32")
        ids_v = blk.create_var(name="sids", shape=(b, 1 + k), dtype="int64")
        blk.append_op("nce",
                      inputs={"Input": vs["x"], "Label": vs["lbl"],
                              "Weight": vs["w"], "Bias": vs["bias"]},
                      outputs={"Cost": cost_v, "SampleLogits": sl_v,
                               "SampleLabels": ids_v},
                      attrs={"num_total_classes": c, "num_neg_samples": k,
                             "sampler": 0, "seed": 7})
        exe = fluid.Executor(fluid.CPUPlace())
        cost, slog, sids = [np.asarray(v) for v in exe.run(
            fluid.default_main_program(),
            feed={"x": xv, "w": wv, "bias": bias, "lbl": label},
            fetch_list=["cost", "slog", "sids"])]
    s = np.einsum("bd,bsd->bs", xv, wv[sids]) + bias[sids]
    o = 1.0 / (1.0 + np.exp(-s))
    np.testing.assert_allclose(slog, o, rtol=1e-5, atol=1e-6)
    bq = k * (1.0 / c)  # uniform sampler
    expect = np.zeros(b)
    for i in range(b):
        expect[i] = -np.log(o[i, 0] / (o[i, 0] + bq))
        for j in range(1, 1 + k):
            expect[i] += -np.log(bq / (o[i, j] + bq))
    np.testing.assert_allclose(cost.reshape(-1), expect, rtol=1e-4,
                               atol=1e-5)


def test_nce_loss_trains_and_matches_shape():
    """NCE is stochastic (sampled negatives) — check structure, a training
    run, and the full-softmax sanity (cost finite + decreases)."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data(name="x", shape=[8], dtype="float32")
        lbl = fluid.layers.data(name="y", shape=[1], dtype="int64")
        cost = fluid.layers.nce(input=xv, label=lbl, num_total_classes=50,
                                num_neg_samples=5, sampler="log_uniform")
        loss = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        xb = rng.randn(16, 8).astype(np.float32)
        yb = rng.randint(0, 50, (16, 1)).astype(np.int64)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            vals = [float(np.asarray(exe.run(
                fluid.default_main_program(), feed={"x": xb, "y": yb},
                fetch_list=[loss])[0]).reshape(-1)[0]) for _ in range(30)]
        assert np.isfinite(vals).all()
        assert vals[-1] < vals[0]


def test_chunk_eval_iob():
    """IOB chunk F1 against hand-counted chunks (reference
    chunk_eval_op.h): tags = type*2 + {B:0, I:1}, Other = 2*num_types."""
    # types: 0, 1; O = 4. B0=0 I0=1 B1=2 I1=3
    label = np.array([[0, 1, 4, 2, 3, 3],
                      [2, 4, 0, 1, 1, 4]], np.int64)
    infer = np.array([[0, 1, 4, 2, 3, 4],    # 2nd chunk ends early: wrong
                      [2, 4, 0, 1, 1, 4]], np.int64)  # all correct
    slen = np.array([6, 6], np.int32)
    # label chunks: [0-1]x2 + [3-5] , [0]x1 + [2-4] = 4; infer: 4
    # correct: seq0 [0,1]t0; seq1 [0]t1 + [2,4]t0 = 3
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        blk = fluid.default_main_program().global_block
        iv = blk.create_var(name="i", shape=infer.shape, dtype="int64",
                            is_data=True)
        lv = blk.create_var(name="l", shape=label.shape, dtype="int64",
                            is_data=True)
        sv = blk.create_var(name="s", shape=slen.shape, dtype="int32",
                            is_data=True)
        outs = {k: blk.create_var(name=k.lower(), shape=(1,),
                                  dtype="float32" if k in
                                  ("Precision", "Recall", "F1-Score")
                                  else "int64")
                for k in ("Precision", "Recall", "F1-Score",
                          "NumInferChunks", "NumLabelChunks",
                          "NumCorrectChunks")}
        blk.append_op("chunk_eval",
                      inputs={"Inference": iv, "Label": lv, "SeqLength": sv},
                      outputs={k: v for k, v in outs.items()},
                      attrs={"num_chunk_types": 2, "chunk_scheme": "IOB",
                             "excluded_chunk_types": []})
        exe = fluid.Executor(fluid.CPUPlace())
        res = exe.run(fluid.default_main_program(),
                      feed={"i": infer, "l": label, "s": slen},
                      fetch_list=[outs["NumInferChunks"],
                                  outs["NumLabelChunks"],
                                  outs["NumCorrectChunks"],
                                  outs["Precision"], outs["Recall"]])
    n_i, n_l, n_c, p, r = [np.asarray(v).reshape(-1)[0] for v in res]
    assert (n_i, n_l, n_c) == (4, 4, 3), (n_i, n_l, n_c)
    np.testing.assert_allclose(p, 0.75, rtol=1e-6)
    np.testing.assert_allclose(r, 0.75, rtol=1e-6)


def test_crf_layer_end_to_end_training():
    """linear_chain_crf + crf_decoding as layers: loss decreases and decode
    recovers a learnable pattern (the label IS argmax-able from emission)."""
    import paddle_tpu.unique_name as un

    b, t, d = 8, 6, 4
    rng = np.random.RandomState(3)
    with un.guard(), fluid.program_guard(fluid.Program(), fluid.Program()):
        fluid.default_main_program().random_seed = 11
        feat = fluid.layers.data(name="feat", shape=[t, d], dtype="float32",
                                 lod_level=0)
        lbl = fluid.layers.data(name="lbl", shape=[t], dtype="int64")
        lens = fluid.layers.data(name="lens", shape=[], dtype="int32")
        em = fluid.layers.fc(input=feat, size=d, num_flatten_dims=2)
        crf_cost = fluid.layers.linear_chain_crf(
            input=em, label=lbl, length=lens,
            param_attr=fluid.ParamAttr(name="crfw"))
        loss = fluid.layers.mean(crf_cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        decode = fluid.layers.crf_decoding(
            input=em, param_attr=fluid.ParamAttr(name="crfw"), length=lens)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        y = rng.randint(0, d, (b, t)).astype(np.int64)
        xb = np.eye(d, dtype=np.float32)[y] + 0.1 * rng.randn(
            b, t, d).astype(np.float32)
        ln = np.full((b,), t, np.int32)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            losses = []
            for _ in range(60):
                out = exe.run(fluid.default_main_program(),
                              feed={"feat": xb, "lbl": y, "lens": ln},
                              fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            path = np.asarray(exe.run(
                fluid.default_main_program(),
                feed={"feat": xb, "lbl": y, "lens": ln},
                fetch_list=[decode])[0])
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert (path == y).mean() > 0.9
