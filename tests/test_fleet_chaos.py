"""Fleet self-healing tests (ISSUE 15): wire-site fault injection,
poison-request bisection, router transport breaker + hardening, and the
replica supervisor (stub-process based — the real-replica end-to-end
story is ``tools/load_check.py --fleet-chaos``)."""
from __future__ import annotations

import json
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import serving
from paddle_tpu.resilience import faults
from paddle_tpu.serving.fleet import (FleetRouter, Replica, ReplicaCrashLoop,
                                      ReplicaLost, ReplicaSupervisor,
                                      RouterConfig, ServingFrontend,
                                      SupervisorConfig, wire)


@pytest.fixture(autouse=True)
def _flags_reset():
    from paddle_tpu import flags as flags_mod

    snap = dict(flags_mod._overrides)
    yield
    flags_mod._overrides.clear()
    flags_mod._overrides.update(snap)
    flags_mod._set_epoch += 1


# ---------------------------------------------------------------------------
# faults: wire sites, data-plane actions, seeded determinism, audit trail
# ---------------------------------------------------------------------------

def test_wire_sites_registered_and_data_actions_validated():
    assert {"wire_connect", "wire_response", "wire_stream"} \
        <= set(faults.SITES)
    # data-plane actions parse at wire sites only
    faults.FaultPlan("wire_connect:1:drop,wire_response:@2:corrupt,"
                     "wire_stream:p0.5:stall")
    with pytest.raises(ValueError, match="data-plane wire action"):
        faults.FaultPlan("step:1:drop")
    with pytest.raises(ValueError, match="unknown action"):
        faults.FaultPlan("wire_connect:1:mangle")


def test_wire_probability_rules_seeded_deterministic():
    """Same plan + seed => the same fire pattern, run after run — the
    documented pX replay contract at the new sites."""
    def pattern(seed):
        p = faults.FaultPlan("wire_response:p0.4:drop", seed=seed)
        return [p.action("wire_response") for _ in range(32)]

    a, b = pattern(11), pattern(11)
    assert a == b
    assert 0 < sum(x is not None for x in a) < 32   # actually probabilistic
    assert pattern(12) != a                          # seed-sensitive


def test_wire_fired_audit_trail_records_hits():
    p = faults.FaultPlan("wire_connect:@2:drop,wire_stream:1:corrupt")
    assert p.action("wire_stream") == "corrupt"
    assert p.action("wire_connect") is None
    assert p.action("wire_connect") == "drop"
    assert ("wire_stream", 1, "corrupt") in p.fired
    assert ("wire_connect", 2, "drop") in p.fired
    assert len(p.fired) == 2


def test_fault_action_still_raises_exception_actions():
    with faults.fault_plan_guard("wire_connect:1:ConnectionError"):
        with pytest.raises(ConnectionError) as ei:
            faults.fault_action("wire_connect")
        assert isinstance(ei.value, faults.InjectedFault)
    # and fault_point at a wire site ignores (logs) a data action rather
    # than crashing — defense for a plan/probe mismatch
    with faults.fault_plan_guard("wire_connect:1:drop"):
        faults.fault_point("wire_connect")


def test_stall_duration_flag():
    fluid.set_flags({"FLAGS_fault_stall_s": 0.08})
    t0 = time.monotonic()
    faults.stall()
    assert 0.06 <= time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# engine: poison-request bisection + quarantine
# ---------------------------------------------------------------------------

def _build_infer(hidden=4, in_dim=13):
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim], dtype="float32")
            pred = fluid.layers.fc(x, hidden, act="softmax")
        infer = main.clone(for_test=True)
    return infer, startup, pred.name


def _engine(**cfg_kw):
    infer, startup, pred = _build_infer()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    cfg = serving.ServingConfig(max_batch=cfg_kw.pop("max_batch", 4),
                                **cfg_kw)
    return serving.ServingEngine(infer, feed_names=["x"],
                                 fetch_list=[pred], scope=scope,
                                 executor=exe, config=cfg)


def _feed(rows=1, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(rows, 13).astype(np.float32)}


def _poison():
    f = _feed(seed=999)
    f["x"][0, :5] = np.nan
    return f


def _bisect_engine(**kw):
    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    kw.setdefault("bisect_depth", 3)
    kw.setdefault("batch_window_s", 0.2)
    eng = _engine(**kw)
    eng.warm_up()
    eng.start()
    return eng


def test_poison_isolated_innocents_bit_exact():
    """[i1, i2, i3, poison] coalesce into one batch; bisection splits
    [i1,i2] | [i3,p] and then [i3] | [p]. Bit-exactness is asserted
    against clean baselines AT THE SAME BUCKETS bisection re-dispatches
    at (i1+i2 co-batched at bucket 2, i3 solo at bucket 1) — XLA results
    legitimately differ in ULPs across bucket sizes, so a same-bucket
    baseline is the meaningful 'correct results' claim."""
    eng = _bisect_engine()
    try:
        i1, i2, i3 = (_feed(seed=i) for i in range(3))
        b1, b2 = eng.submit(i1), eng.submit(i2)      # clean pair, bucket 2
        base1, base2 = b1.result(timeout=60), b2.result(timeout=60)
        base3 = eng.submit(i3).result(timeout=60)    # clean solo, bucket 1
        f1, f2, f3 = eng.submit(i1), eng.submit(i2), eng.submit(i3)
        pfut = eng.submit(_poison())
        perr = pfut.exception(timeout=60)
        assert isinstance(perr, serving.PoisonRequest)
        assert perr.fingerprint
        assert isinstance(perr.__cause__, FloatingPointError)
        assert np.array_equal(f1.result(timeout=60)[0], base1[0])
        assert np.array_equal(f2.result(timeout=60)[0], base2[0])
        assert np.array_equal(f3.result(timeout=60)[0], base3[0])
        acct = eng.accounting()
        assert acct["exact"] and acct["poisoned"] == 1
        assert acct["failed"] == 0      # no whole-batch failure leaked
    finally:
        eng.stop()


def test_quarantine_sheds_repeat_offender_typed():
    eng = _bisect_engine()
    try:
        poison = _poison()
        err = eng.submit(poison).exception(timeout=60)
        assert isinstance(err, serving.PoisonRequest)
        with pytest.raises(serving.Overloaded) as ei:
            eng.submit(poison)
        assert ei.value.reason == "poison_quarantine"
        # a DIFFERENT feed is untouched by the quarantine
        assert eng.submit(_feed(seed=5)).result(timeout=60)
        acct = eng.accounting()
        assert acct["exact"] and acct["shed"] == 1
    finally:
        eng.stop()


def test_quarantine_is_bounded():
    eng = _bisect_engine(bisect_quarantine=2)
    try:
        for s in (101, 102, 103):
            f = _feed(seed=s)
            f["x"][0, 0] = np.nan
            err = eng.submit(f).exception(timeout=60)
            assert isinstance(err, serving.PoisonRequest)
        assert len(eng._quarantine) == 2    # oldest evicted
    finally:
        eng.stop()


def test_transient_batch_fault_absorbed_by_bisection():
    """An injected depth-0 batch failure whose re-dispatch succeeds:
    EVERY member completes — bisection turns a transient whole-batch
    failure into zero caller-visible errors."""
    from paddle_tpu.resilience import fault_plan_guard

    eng = _bisect_engine()
    try:
        with fault_plan_guard("batch_dispatch:@1:RuntimeError"):
            futs = [eng.submit(_feed(seed=i)) for i in range(4)]
            res = [f.result(timeout=60) for f in futs]
        assert len(res) == 4
        acct = eng.accounting()
        assert acct["exact"] and acct["failed"] == 0 \
            and acct["poisoned"] == 0 and acct["completed"] == 4
    finally:
        eng.stop()


def test_bisected_poisons_do_not_open_the_bucket_breaker():
    """Distinct poison feeds arriving round after round on one bucket:
    each bisection proves the bucket healthy (the co-batched innocent
    completes), so the depth-0 breaker failure is compensated and the
    bucket never reaches CircuitOpen against innocents."""
    eng = _bisect_engine(breaker_threshold=2)
    try:
        for j in range(4):   # 2x the threshold
            poison = _feed(seed=300 + j)
            poison["x"][0, 0] = np.nan
            innocent = _feed(seed=400 + j)
            pf = eng.submit(poison)
            inf_ = eng.submit(innocent)
            assert isinstance(pf.exception(timeout=60),
                              serving.PoisonRequest)
            assert inf_.result(timeout=60)
        assert all(b.state == "closed" for b in eng._breakers.values())
        acct = eng.accounting()
        assert acct["exact"] and acct["circuit_open"] == 0
    finally:
        eng.stop()


def test_broken_bucket_never_quarantines_innocents():
    """When EVERY member of a batch fails (a broken bucket, not one bad
    request) there is no completed-mate witness: members settle
    BatchFailed — never PoisonRequest — and nothing is quarantined, so
    legitimate resubmissions are not shed at admission."""
    eng = _bisect_engine()
    try:
        def broken(*a, **k):
            raise RuntimeError("bucket broken (state-safe)")

        real_run = eng._exe.run
        eng._exe.run = broken
        futs = [eng.submit(_feed(seed=i)) for i in range(2)]
        errs = [f.exception(timeout=60) for f in futs]
        assert all(isinstance(e, serving.BatchFailed) for e in errs)
        assert not any(isinstance(e, serving.PoisonRequest) for e in errs)
        assert len(eng._quarantine) == 0
        # the bucket heals -> the same feeds complete (not shed)
        eng._exe.run = real_run
        for i in range(2):
            assert eng.submit(_feed(seed=i)).result(timeout=60)
        acct = eng.accounting()
        assert acct["exact"] and acct["poisoned"] == 0 \
            and acct["shed"] == 0 and acct["failed"] == 2
    finally:
        eng.stop()


def test_bisect_off_keeps_whole_batch_failure():
    """Default config (bisect_depth=0): the PR 8 semantics stand — a
    failed batch fails every member typed BatchFailed."""
    from paddle_tpu.resilience import fault_plan_guard

    fluid.set_flags({"FLAGS_check_nan_inf": 1})
    eng = _engine(batch_window_s=0.2)
    eng.warm_up()
    eng.start()
    try:
        futs = [eng.submit(_feed(seed=i)) for i in range(2)]
        pfut = eng.submit(_poison())
        errs = [f.exception(timeout=60) for f in futs + [pfut]]
        assert all(isinstance(e, serving.BatchFailed) for e in errs)
        assert not any(isinstance(e, serving.PoisonRequest) for e in errs)
        acct = eng.accounting()
        assert acct["exact"] and acct["failed"] == 3
    finally:
        eng.stop()


def test_bisect_safety_classification():
    """Device-state-corrupting failures must never bisect: the whole
    batch fails rather than re-dispatching on corrupted state."""
    from paddle_tpu.resilience.distributed import WatchdogTimeout
    from paddle_tpu.resilience.elastic import DeviceLostError

    safe = serving.ServingEngine._bisect_safe
    assert safe(FloatingPointError("Nan found in output"))
    assert safe(RuntimeError("injected transient"))
    assert not safe(WatchdogTimeout("step", 2.0))
    assert not safe(DeviceLostError("chip preempted"))
    assert not safe(RuntimeError("Array has been deleted or donated"))
    # the classification walks the cause chain
    wrapped = RuntimeError("batch failed")
    wrapped.__cause__ = WatchdogTimeout("step", 2.0)
    assert not safe(wrapped)


def test_unsafe_error_fails_whole_batch_despite_bisection(monkeypatch):
    from paddle_tpu.resilience import fault_plan_guard

    eng = _bisect_engine()
    monkeypatch.setattr(serving.ServingEngine, "_bisect_safe",
                        staticmethod(lambda e: False))
    try:
        with fault_plan_guard("batch_dispatch:@1:RuntimeError"):
            futs = [eng.submit(_feed(seed=i)) for i in range(3)]
            errs = [f.exception(timeout=60) for f in futs]
        assert all(isinstance(e, serving.BatchFailed) for e in errs)
        assert eng.accounting()["failed"] == 3
    finally:
        eng.stop()


def test_expired_member_settles_deadline_not_redispatch(monkeypatch):
    """A member whose deadline expired by resolution time gets its typed
    DeadlineExceeded instead of riding a bisected re-dispatch."""
    eng = _bisect_engine()
    try:
        real_resolve = serving.ServingEngine._resolve_failed_batch

        def slow_resolve(self, batch, cause, depth, label, ctx=None):
            if depth == 0:
                time.sleep(0.3)   # outlive the poison batch's deadlines
            return real_resolve(self, batch, cause, depth, label, ctx)

        monkeypatch.setattr(serving.ServingEngine, "_resolve_failed_batch",
                            slow_resolve)
        futs = [eng.submit(_feed(seed=i), deadline_s=0.25)
                for i in range(2)]
        pfut = eng.submit(_poison(), deadline_s=0.25)
        errs = [f.exception(timeout=60) for f in futs + [pfut]]
        assert all(isinstance(e, serving.DeadlineExceeded) for e in errs)
        acct = eng.accounting()
        assert acct["exact"] and acct["deadline_exceeded"] == 3
    finally:
        eng.stop()


def test_poison_request_wire_roundtrip():
    e = serving.PoisonRequest("bad feed", fingerprint="abcd1234")
    assert wire.status_for(e) == 500          # a BatchFailed subclass
    body = wire.error_body(e, admitted=True)
    assert body["error"]["fingerprint"] == "abcd1234"
    back = wire.error_from_body(body)
    assert isinstance(back, serving.PoisonRequest)
    assert back.fingerprint == "abcd1234"
    assert not wire.response_is_unadmitted(500, body)   # never retried


# ---------------------------------------------------------------------------
# router: transport breaker, corrupt hardening, bounded stop
# ---------------------------------------------------------------------------

@pytest.fixture
def fleet1():
    """One real in-process replica behind a router configured with a
    tight transport breaker; tests add canned/dead siblings."""
    eng = _engine(batch_window_s=0.005)
    eng.warm_up()
    eng.start()
    fe = ServingFrontend(eng, replica_id="good")
    fe.start()
    router = FleetRouter(
        [Replica("good", "127.0.0.1", fe.port)],
        RouterConfig(poll_interval_s=0.05, connect_timeout_s=2.0,
                     request_timeout_s=5.0, breaker_threshold=2,
                     breaker_cooldown_s=0.2))
    router.poll_now()
    yield router, eng, fe
    router.stop()
    fe.stop(wait_inflight_s=2.0)
    if not eng._stopped:
        eng.stop(drain=False)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_router_breaker_ejects_dead_replica_after_threshold(fleet1):
    router, _, _ = fleet1
    dead = router.add_replica(Replica("dead", "127.0.0.1", _free_port()))
    # force the dead replica to look routable so dispatch tries it
    router.config.honor_drain = False
    for i in range(8):
        assert router.submit(_feed(seed=i))[0].shape == (1, 4)
    # consecutive connect-refusals opened the breaker; once open, the
    # dead replica is excluded — retries stop growing
    assert dead.breaker.state == "open"
    retries_at_open = router.accounting()["retries"]
    for i in range(4):
        router.submit(_feed(seed=10 + i))
    assert router.accounting()["retries"] == retries_at_open
    acct = router.accounting()
    assert acct["exact"] and acct["completed"] == 12


def test_router_breaker_probe_rides_healthz_poll(fleet1):
    router, _, _ = fleet1
    good = router.get_replica("good")
    # trip the breaker by hand (threshold 2), then let polls probe it
    router._breaker_failure(good)
    router._breaker_failure(good)
    assert good.breaker.state == "open"
    assert router._pick() is None          # ejected from routing
    deadline = time.monotonic() + 5.0
    while good.breaker.state != "closed" and time.monotonic() < deadline:
        router.poll_now()
        time.sleep(0.05)
    assert good.breaker.state == "closed"  # healthz probe readmitted it
    assert router._pick() is good
    assert router.submit(_feed())[0].shape == (1, 4)


def test_router_corrupt_200_is_typed_replica_lost(fleet1):
    from paddle_tpu.resilience import fault_plan_guard

    router, _, _ = fleet1
    with fault_plan_guard("wire_response:@1:corrupt"):
        with pytest.raises(ReplicaLost, match="undecodable"):
            router.submit(_feed())
    # breaker counted the corruption; the next clean submit works
    assert router.submit(_feed())[0].shape == (1, 4)
    acct = router.accounting()
    assert acct["exact"] and acct["replica_lost"] == 1


def test_router_corrupt_retryable_status_never_redispatches():
    """A corrupt body on a status the retry policy WOULD redispatch
    (410/429) loses the authoritative `admitted` flag — an admitted
    EngineStopped travels as 410 too, so guessing from the status map
    could execute one request twice. Must be typed ReplicaLost, and the
    sibling must receive nothing."""

    class _H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            raw = wire.dumps({"schema_version": 1, "status": "ok",
                              "ready": True, "queue_depth": 0,
                              "degraded": False, "open_buckets": []})
            self.send_response(200)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0) or 0)
            self.rfile.read(n)
            raw = b"\xffgarbage-not-json"
            self.send_response(410)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # a single replica makes misclassification visible: if the
        # corrupt 410 were treated as retryable-unadmitted, the retry
        # would find no sibling and the outcome would be Overloaded —
        # ReplicaLost with zero retries proves no redispatch happened
        router = FleetRouter(
            [Replica("corrupt410", "127.0.0.1", srv.server_address[1])],
            RouterConfig(poll_interval_s=10.0, honor_drain=False))
        with pytest.raises(ReplicaLost, match="undecodable"):
            router.submit(_feed())
        acct = router.accounting()
        assert acct["exact"] and acct["retries"] == 0 \
            and acct["replica_lost"] == 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_router_wire_connect_drop_retried_on_sibling(fleet1):
    from paddle_tpu import monitor
    from paddle_tpu.resilience import fault_plan_guard

    router, _, fe = fleet1
    router.add_replica(Replica("good2", "127.0.0.1", fe.port))
    router.poll_now()
    monitor.reset()
    with fault_plan_guard("wire_connect:@1:drop") as plan:
        assert router.submit(_feed())[0].shape == (1, 4)
        assert ("wire_connect", 1, "drop") in plan.fired
    acct = router.accounting()
    assert acct["retries"] == 1 and acct["completed"] == 1 and acct["exact"]


def test_router_stop_bounded_with_hung_healthz_poll():
    """Satellite: a /healthz that never answers must not delay router
    teardown past connect_timeout_s — stop() closes the poll socket."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)   # accepts connections, never answers
    try:
        router = FleetRouter(
            [Replica("hung", "127.0.0.1", srv.getsockname()[1])],
            RouterConfig(poll_interval_s=0.05, connect_timeout_s=30.0))
        router.start()
        time.sleep(0.3)   # a poll is now hung in the read
        t0 = time.monotonic()
        router.stop()
        assert time.monotonic() - t0 < 40.0  # not 2x30s of timeouts
        # with the 30s socket timeout, only the forced close explains a
        # sub-timeout return once a poll is in flight
    finally:
        srv.close()


def test_router_membership_add_remove_reassign():
    router = FleetRouter([])    # an empty fleet is legal now
    with pytest.raises(serving.Overloaded):
        router.submit(_feed())
    r = router.add_replica(("a", "127.0.0.1", 1234))
    assert router.get_replica("a") is r and r.breaker is not None
    with pytest.raises(ValueError):
        router.add_replica(("a", "127.0.0.1", 99))
    old_breaker = r.breaker
    router._breaker_failure(r)
    router.reassign_replica("a", "127.0.0.1", 4321)
    assert r.port == 4321
    assert r.breaker is not old_breaker          # fresh capacity
    assert router.remove_replica("a") is r
    assert router.get_replica("a") is None
    assert router.remove_replica("a") is None


# ---------------------------------------------------------------------------
# frontend wire faults: stream drop/corrupt surfaced typed by the router
# ---------------------------------------------------------------------------

class _CorruptStreamReplica:
    """Minimal generative-ish front-end: healthz advertises generative,
    /v1/generate streams two token chunks, then a corrupt one."""

    def __init__(self, mode="corrupt"):
        outer = self

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                raw = wire.dumps({"schema_version": 1, "status": "ok",
                                  "ready": True, "queue_depth": 0,
                                  "degraded": False, "open_buckets": [],
                                  "generative": True})
                self.send_response(200)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                self.send_response(200)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(b):
                    self.wfile.write(f"{len(b):x}\r\n".encode() + b
                                     + b"\r\n")
                    self.wfile.flush()

                chunk(wire.dumps({"tokens": [1]}) + b"\n")
                chunk(wire.dumps({"tokens": [2]}) + b"\n")
                if outer.mode == "corrupt":
                    chunk(b"\xffgarbage\n")
                    chunk(b"0\r\n\r\n"[:0] or b"x")  # keep stream open
                else:   # drop: sever without a terminal chunk
                    self.connection.close()

        self.mode = mode
        self.server = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.port = self.server.server_address[1]

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.mark.parametrize("mode", ["corrupt", "drop"])
def test_stream_corruption_and_drop_surface_typed_after_partials(mode):
    rep = _CorruptStreamReplica(mode)
    try:
        router = FleetRouter(
            [Replica("g", "127.0.0.1", rep.port)],
            RouterConfig(poll_interval_s=10.0, request_timeout_s=10.0))
        router.poll_now()
        it = router.generate([1, 2, 3], max_new_tokens=8)
        got = []
        with pytest.raises(ReplicaLost):
            for t in it:
                got.append(t)
        assert got == [1, 2]          # partials delivered, then typed
        acct = router.accounting()
        assert acct["exact"] and acct["replica_lost"] == 1
    finally:
        rep.close()


# ---------------------------------------------------------------------------
# supervisor (stub processes — no jax import per spawn)
# ---------------------------------------------------------------------------

_STUB = r"""
import json, signal, sys, time
mode = sys.argv[1]
if mode == "neverready":
    time.sleep(600)
print(json.dumps({"event": "ready", "replica_id": "s", "port": 18999,
                  "time_to_ready_s": 0.01}), flush=True)
if mode == "crash":
    time.sleep(0.1)
    print(json.dumps({"event": "exit", "replica_id": "s",
                      "reason": "crash", "error": "boom"}), flush=True)
    sys.exit(21)
if mode == "crash_once":
    import os
    marker = sys.argv[2]
    if not os.path.exists(marker):
        open(marker, "w").close()
        time.sleep(0.1)
        print(json.dumps({"event": "exit", "replica_id": "s",
                          "reason": "crash", "error": "boom"}), flush=True)
        sys.exit(21)
def term(*a):
    print(json.dumps({"event": "exit", "replica_id": "s",
                      "reason": "drain", "accounting": {}}), flush=True)
    sys.exit(0)
signal.signal(signal.SIGTERM, term)
while True:
    time.sleep(0.05)
"""


@pytest.fixture
def stub(tmp_path):
    path = tmp_path / "stub_replica.py"
    path.write_text(_STUB)

    def cmd(mode, *extra):
        return lambda h: [sys.executable, str(path), mode,
                          *[str(e) for e in extra]]

    return cmd, tmp_path


def _sup_cfg(**kw):
    kw.setdefault("max_restarts", 2)
    kw.setdefault("restart_window_s", 30.0)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_max_s", 0.2)
    kw.setdefault("ready_timeout_s", 15.0)
    kw.setdefault("exit_grace_s", 5.0)
    return SupervisorConfig(**kw)


def test_supervisor_registers_ready_replica_with_router(stub):
    cmd, tmp = stub
    router = FleetRouter([])
    sup = ReplicaSupervisor(router, _sup_cfg(), log_dir=str(tmp),
                            spawn_command=cmd("ok"))
    try:
        h = sup.add_replica("s0")
        info = h.wait_ready(15)
        assert info["port"] == 18999
        assert router.get_replica("s0").port == 18999
        assert h.state == "ready"
    finally:
        sup.stop()


def test_supervisor_graceful_drain_never_restarts(stub):
    cmd, tmp = stub
    sup = ReplicaSupervisor(None, _sup_cfg(), log_dir=str(tmp),
                            spawn_command=cmd("ok"))
    try:
        h = sup.add_replica("s0")
        h.wait_ready(15)
        sup.drain("s0")
        h.thread.join(15)
        assert h.state == "stopped" and h.restarts == 0
        assert h.last_exit["reason"] == "drain"
    finally:
        sup.stop()


def test_supervisor_restarts_crashed_replica_with_backoff(stub):
    cmd, tmp = stub
    marker = tmp / "crashed_once"
    router = FleetRouter([])
    sup = ReplicaSupervisor(router, _sup_cfg(), log_dir=str(tmp),
                            spawn_command=cmd("crash_once", marker))
    try:
        h = sup.add_replica("s0")
        h.wait_ready(15)           # first incarnation
        deadline = time.monotonic() + 20
        while (h.restarts < 1 or h.state != "ready") \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.restarts == 1 and h.state == "ready", h.status()
        assert h.last_exit["reason"] == "crash"
        assert h.error is None
        # one restart event with a backoff in the audit trail
        assert any(k == "restart" for _, k, _d in h.events)
    finally:
        sup.stop()


def test_supervisor_crash_loop_retires_typed(stub):
    cmd, tmp = stub
    router = FleetRouter([])
    sup = ReplicaSupervisor(router, _sup_cfg(), log_dir=str(tmp),
                            spawn_command=cmd("crash"))
    try:
        h = sup.add_replica("s0")
        assert h.wait_retired(30), h.status()
        assert h.state == "retired"
        assert isinstance(h.error, ReplicaCrashLoop)
        assert h.error.replica == "s0"
        assert h.restarts == sup.config.max_restarts
        assert h.spawns == sup.config.max_restarts + 1
        assert router.get_replica("s0") is None   # deregistered
        with pytest.raises(ReplicaCrashLoop):
            sup.check()
        with pytest.raises(ReplicaCrashLoop):
            h.wait_ready(5)        # fail fast, typed — never a spin
    finally:
        sup.stop()


def test_supervisor_negative_control_spawn_once(stub):
    cmd, tmp = stub
    sup = ReplicaSupervisor(None, _sup_cfg(restart=False),
                            log_dir=str(tmp), spawn_command=cmd("crash"))
    try:
        h = sup.add_replica("s0")
        h.thread.join(20)
        assert h.state == "down" and h.restarts == 0 and h.spawns == 1
        # wait_ready on a replica that will never come fails loudly
        # instead of spinning (even with no timeout deadline)
        with pytest.raises(RuntimeError, match="will not become ready"):
            h.wait_ready()
    finally:
        sup.stop()


def test_supervisor_kill_classification(stub):
    cmd, tmp = stub
    sup = ReplicaSupervisor(None, _sup_cfg(max_restarts=5),
                            log_dir=str(tmp), spawn_command=cmd("ok"))
    try:
        h = sup.add_replica("s0")
        h.wait_ready(15)
        sup.kill("s0")             # SIGKILL: no exit event
        deadline = time.monotonic() + 20
        while h.restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.restarts == 1, h.status()
        assert h.last_exit["reason"] == "kill"
    finally:
        sup.stop()


# ---------------------------------------------------------------------------
# replica worker: the crash path emits the exit event (satellite)
# ---------------------------------------------------------------------------

def test_replica_crash_path_emits_exit_event(monkeypatch, capsys):
    from paddle_tpu.serving.fleet import replica as replica_mod

    def boom(name, config):
        raise RuntimeError("probe exploded")

    monkeypatch.setattr(replica_mod, "build_probe", boom)
    rc = replica_mod.main(["--model", "mlp_tiny", "--replica-id", "rc1"])
    assert rc == 21
    events = [json.loads(l) for l in
              capsys.readouterr().out.strip().splitlines() if l]
    exits = [e for e in events if e.get("event") == "exit"]
    assert exits and exits[-1]["reason"] == "crash"
    assert "probe exploded" in exits[-1]["error"]
    assert exits[-1]["replica_id"] == "rc1"
