"""API tooling tail (VERDICT r5 missing #8): the signature freeze gate
(reference tools/print_signatures.py + check_api_compatible.py CI role),
the MultiSlot DataGenerator writer (reference incubate/data_generator),
and the custom-op extension path (reference fluid.framework:4394
load_op_library -> here, register_op IS the extension point)."""
import io
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_freeze():
    """The committed tools/api_signatures.txt must match the live API.
    On intentional API changes regenerate with:
    python tools/print_signatures.py paddle_tpu > tools/api_signatures.txt
    """
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import print_signatures

    live = print_signatures.walk("paddle_tpu")
    frozen = {}
    with open(os.path.join(REPO, "tools", "api_signatures.txt")) as f:
        for line in f:
            name, _, sig = line.rstrip("\n").partition(" ")
            frozen[name] = sig
    removed = sorted(set(frozen) - set(live))
    changed = sorted(n for n in set(frozen) & set(live)
                     if frozen[n] != live[n])
    assert not removed and not changed, (
        f"API freeze violated — removed: {removed[:5]}, changed: "
        f"{changed[:5]}. If intentional, regenerate "
        f"tools/api_signatures.txt (see this test's docstring).")
    # additions are allowed (the reference gate also only blocks breaks)


def test_multislot_data_generator_roundtrip():
    from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

    class MyData(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                ints = [int(v) for v in line.split()]
                yield [("words", ints), ("label", [ints[0] % 2])]
            return local_iter

    gen = MyData()
    out = io.StringIO()
    old_in, old_out = sys.stdin, sys.stdout
    sys.stdin, sys.stdout = io.StringIO("1 2 3\n40 50\n"), out
    try:
        gen.run_from_stdin()
    finally:
        sys.stdin, sys.stdout = old_in, old_out
    lines = out.getvalue().strip().split("\n")
    assert lines[0] == "3 1 2 3 1 1"
    assert lines[1] == "2 40 50 1 0"
    assert gen._proto_info == [("words", "uint64"), ("label", "uint64")]

    # float feasign upgrades the slot type (reference semantics)
    class FData(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                yield [("score", [0.5])]
            return local_iter

    g2 = FData()
    s = g2._gen_str([("score", [0.5])])
    assert s == "1 0.5\n"
    assert g2._proto_info == [("score", "float")]


def test_multislot_output_feeds_native_datafeed(tmp_path):
    """The writer's output is exactly what DatasetFactory ingests — the
    end-to-end contract the reference establishes between data_generator
    and MultiSlotDataFeed."""
    from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

    class MyData(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def local_iter():
                ints = [int(v) for v in line.split()]
                yield [("words", ints + [0] * (3 - len(ints))),
                       ("label", [ints[0] % 2])]
            return local_iter

    gen = MyData()
    out = io.StringIO()
    old_in, old_out = sys.stdin, sys.stdout
    sys.stdin, sys.stdout = io.StringIO("1 2 3\n4 5 6\n"), out
    try:
        gen.run_from_stdin()
    finally:
        sys.stdin, sys.stdout = old_in, old_out
    data_file = tmp_path / "part-0.txt"
    data_file.write_text(out.getvalue())

    dataset = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    dataset.set_use_var([("words", "int64", 3), ("label", "int64", 1)])
    dataset.set_filelist([str(data_file)])
    dataset.set_batch_size(2)
    batches = list(dataset.iter_batches())
    assert len(batches) == 1
    np.testing.assert_array_equal(batches[0]["words"],
                                  [[1, 2, 3], [4, 5, 6]])
    np.testing.assert_array_equal(batches[0]["label"].reshape(-1), [1, 0])


def test_custom_op_via_register_op():
    """Custom-op extension: a user registers a new op against the SAME
    registry the built-ins use (the load_op_library role — no .so, the
    lowering rule IS the kernel) and drives it through a program,
    including its autodiff via the generic vjp."""
    from paddle_tpu.ops.common import out as op_out, register_op, x as op_x
    from paddle_tpu.core import registry

    if not registry.has_op("my_custom_gelu2"):
        @register_op("my_custom_gelu2", inputs=["X"], outputs=["Out"],
                     attrs={"alpha": 1.0})
        def _my_custom_gelu2(ctx, ins, attrs):
            import jax

            v = op_x(ins)
            return op_out(attrs["alpha"] * jax.nn.gelu(v))

    with fluid.program_guard(fluid.Program(), fluid.Program()):
        xv = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(xv, 4, param_attr=fluid.ParamAttr(name="w"))
        blk = fluid.default_main_program().global_block
        ov = blk.create_var(name="cust_out", shape=(-1, 4),
                            dtype="float32")
        blk.append_op("my_custom_gelu2", inputs={"X": h},
                      outputs={"Out": ov}, attrs={"alpha": 2.0})
        loss = fluid.layers.mean(blk.var("cust_out"))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        xb = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        with fluid.scope_guard(scope):
            exe.run(fluid.default_startup_program())
            w0 = scope.numpy("w").copy()
            (lv,) = exe.run(fluid.default_main_program(),
                            feed={"x": xb}, fetch_list=[loss])
            w1 = scope.numpy("w")
    import jax

    # numeric check of the custom op itself + grads flowed into w
    assert np.isfinite(np.asarray(lv)).all()
    assert np.abs(w1 - w0).max() > 0
