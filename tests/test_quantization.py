"""Quantization-aware training (reference contrib/slim/quantization/
quantization_pass.py + fake_quantize_op.h)."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.unique_name as un
from op_test import OpTest
from paddle_tpu.contrib.slim.quantization import quant_aware

RNG = np.random.RandomState(7)


class TestFakeQuantAbsMax(OpTest):
    def setup(self):
        v = RNG.randn(4, 6).astype(np.float32)
        scale = np.abs(v).max()
        q = np.round(np.clip(v / scale, -1, 1) * 127) / 127 * scale
        self.op_type = "fake_quantize_dequantize_abs_max"
        self.inputs = {"X": v}
        self.outputs = {"Out": q.astype(np.float32),
                        "OutScale": np.array([scale], np.float32)}

    def test(self):
        self.check_output(rtol=1e-6, atol=1e-7)


def test_fake_quant_straight_through_gradient():
    """STE: the analytic grad is identity (1/n for mean loss) even though
    the true derivative of the staircase is 0 a.e. — finite differences
    can't check this, so assert the property exactly."""
    v = RNG.randn(3, 5).astype(np.float32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[5], dtype="float32",
                              stop_gradient=False)
        blk = main.global_block
        q = blk.create_var(name="q", dtype="float32")
        s = blk.create_var(name="s", dtype="float32")
        blk.append_op("fake_quantize_dequantize_abs_max",
                      inputs={"X": "x"}, outputs={"Out": "q", "OutScale": "s"})
        loss = fluid.layers.mean(q)
        (gx,) = fluid.gradients([loss], [x])
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": v}, fetch_list=[gx.name])
    np.testing.assert_allclose(np.asarray(g), np.full_like(v, 1 / v.size),
                               rtol=1e-6)


def test_quant_aware_training():
    """QAT MNIST-ish MLP: fake-quant ops inserted on weights AND
    activations, model still trains, and the quantized forward differs
    from fp32 by a bounded amount (8-bit resolution)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[16], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, 32, act="relu")
            logits = fluid.layers.fc(h, 4)
            test_prog = main.clone(for_test=True)
            quant_aware(main, startup)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)
    main.random_seed = 9

    types = [op.type for op in main.global_block.ops]
    assert types.count("fake_quantize_dequantize_abs_max") == 2  # 2 weights
    assert types.count(
        "fake_quantize_dequantize_moving_average_abs_max") >= 2  # acts

    rng = np.random.RandomState(0)
    xb = rng.randn(32, 16).astype(np.float32)
    yb = (np.abs(xb[:, :4]).argmax(1)).astype(np.int64).reshape(-1, 1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(60):
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        # quantized vs fp32 forward on the same trained params
        (q_logits,) = exe.run(main, feed={"x": xb, "y": yb},
                              fetch_list=[logits.name])
        (f_logits,) = exe.run(test_prog, feed={"x": xb, "y": yb},
                              fetch_list=[logits.name])
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    diff = np.abs(np.asarray(q_logits) - np.asarray(f_logits))
    assert diff.max() > 0           # quantization actually changes values
    assert diff.max() < 0.3         # ...but within 8-bit resolution


def test_skip_pattern_respects_name_scope():
    """Ops created under fluid.name_scope('skip_quant') are excluded
    (reference checks the op namescope)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            h = fluid.layers.fc(x, 8)
            with fluid.name_scope("skip_quant"):
                out = fluid.layers.fc(h, 4)
            quant_aware(main, startup)
    types = [op.type for op in main.global_block.ops]
    # only the first fc's weight+activation got quantized
    assert types.count("fake_quantize_dequantize_abs_max") == 1


def test_requantize_after_inplace_rewrite():
    """A var name re-defined by a later op must be re-quantized for later
    consumers — the per-name cache is invalidated at each redefinition
    (advisor finding: stale quantized value reused otherwise)."""
    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32")
            h1 = fluid.layers.fc(x, 8)
            # re-define h1's name in place via scale writing to same var
            blk = main.global_block
            blk.append_op("scale", inputs={"X": h1},
                          outputs={"Out": h1}, attrs={"scale": 2.0})
            out = fluid.layers.fc(h1, 4)  # consumes the REDEFINED h1
            quant_aware(main, startup)
    ops = main.global_block.ops
    # find the second fc's mul: its X input must be a .quantized name that
    # was produced AFTER the in-place scale op
    scale_idx = [i for i, op in enumerate(ops) if op.type == "scale"][0]
    muls = [i for i, op in enumerate(ops) if op.type == "mul"]
    second_mul = [i for i in muls if i > scale_idx][0]
    qname = ops[second_mul].inputs["X"][0]
    assert ".quantized" in qname
    producer = [i for i, op in enumerate(ops)
                if qname in sum(op.outputs.values(), [])][0]
    assert producer > scale_idx, (
        "second fc consumes a fake-quant computed before the in-place "
        "redefinition — stale value")
