"""LR schedulers, io save/load, inference export, clip, regularizer, metrics.
(reference analogues: test_learning_rate_scheduler.py, test_io_save_load*,
test_gradient_clip.py, test_regularizer.py)"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.layers import learning_rate_scheduler as lrs


def _run_lr(build_fn, steps):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lr = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    out = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            (v,) = exe.run(main, fetch_list=[lr])
            out.append(float(np.asarray(v).reshape(-1)[0]))
    return out


def test_exponential_decay():
    got = _run_lr(lambda: lrs.exponential_decay(0.1, 10, 0.5), 5)
    want = [0.1 * 0.5 ** (i / 10) for i in range(5)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_piecewise_decay():
    got = _run_lr(lambda: lrs.piecewise_decay([2, 4], [0.1, 0.01, 0.001]), 6)
    np.testing.assert_allclose(got, [0.1, 0.1, 0.01, 0.01, 0.001, 0.001],
                               rtol=1e-6)


def test_noam_decay():
    got = _run_lr(lambda: lrs.noam_decay(512, 4, learning_rate=2.0), 6)
    want = [2.0 * 512 ** -0.5 * min((s + 1) ** -0.5, (s + 1) * 4 ** -1.5)
            for s in range(6)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_cosine_decay():
    got = _run_lr(lambda: lrs.cosine_decay(0.1, 2, 10), 4)
    want = [0.5 * 0.1 * (np.cos((s // 2) * np.pi / 10) + 1) for s in range(4)]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_linear_warmup():
    got = _run_lr(lambda: lrs.linear_lr_warmup(0.1, 4, 0.0, 0.1), 6)
    want = [0.0, 0.025, 0.05, 0.075, 0.1, 0.1]
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_lr_scheduler_drives_optimizer():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(pred)
        lr = lrs.exponential_decay(0.1, 5, 0.5)
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        xb = np.ones((2, 4), np.float32)
        for _ in range(3):
            exe.run(main, feed={"x": xb}, fetch_list=[loss])


def test_save_load_roundtrip(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        pred = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=fluid.ParamAttr(name="b"))
    exe = fluid.Executor(fluid.CPUPlace())
    s1 = fluid.Scope()
    with fluid.scope_guard(s1):
        exe.run(startup)
        fluid.io.save_params(exe, str(tmp_path / "ckpt"), main)
        w1 = s1.numpy("w")

    s2 = fluid.Scope()
    with fluid.scope_guard(s2):
        exe.run(startup)  # different init
        fluid.io.load_params(exe, str(tmp_path / "ckpt"), main)
        np.testing.assert_array_equal(s2.numpy("w"), w1)


def test_save_load_shape_mismatch_error(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fluid.layers.data("x", shape=[3], dtype="float32")
        b = main.global_block.create_parameter("p", [4], "float32")
        startup.global_block.create_parameter("p", [4], "float32")
        startup.global_block.append_op(
            "fill_constant", outputs={"Out": "p"},
            attrs={"shape": [4], "dtype": "float32", "value": 1.0})
    exe = fluid.Executor(fluid.CPUPlace())
    s = fluid.Scope()
    with fluid.scope_guard(s):
        exe.run(startup)
        fluid.io.save_params(exe, str(tmp_path / "c"), main)
    # build a program with different shape for p
    main2 = fluid.Program()
    p2 = main2.global_block.create_parameter("p", [5], "float32")
    with pytest.raises(RuntimeError, match="shape mismatch"):
        fluid.io.load_params(exe, str(tmp_path / "c"), main2, scope=fluid.Scope())


def test_save_load_inference_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    xb = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        # one train step mutates w, THEN export
        exe.run(main, feed={"x": xb, "y": np.zeros((4, 1), np.float32)},
                fetch_list=[])
        fluid.io.save_inference_model(str(tmp_path / "m"), ["x"], [pred], exe,
                                      main)
        # numpy oracle from the saved params
        w = scope.numpy("w")
        bias_name = [p.name for p in main.all_parameters()
                     if p.name != "w"][0]
        want = xb @ w + scope.numpy(bias_name)
    # fresh scope + program from disk
    s2 = fluid.Scope()
    with fluid.scope_guard(s2):
        prog, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path / "m"), exe)
        assert feeds == ["x"]
        # pruned program must not contain optimizer ops
        assert not any(op.type == "sgd" for op in prog.global_block.ops)
        got = exe.run(prog, feed={"x": xb}, fetch_list=fetches)[0]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_gradient_clip_global_norm():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=False)
        loss = fluid.layers.mean(pred)
        fluid.set_gradient_clip(fluid.GradientClipByGlobalNorm(1e-3))
        try:
            opt = fluid.optimizer.SGD(1.0)
            opt.minimize(loss)
        finally:
            fluid.set_gradient_clip(None)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = scope.numpy("w").copy()
        xb = np.full((2, 4), 100.0, np.float32)
        exe.run(main, feed={"x": xb}, fetch_list=[loss])
        w1 = scope.numpy("w")
    # update magnitude bounded by lr * clip_norm
    assert np.abs(w1 - w0).max() <= 1e-3 + 1e-7


def test_l2_regularizer_changes_grad():
    def build(reg):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[2], dtype="float32")
            pred = fluid.layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"),
                                   bias_attr=False)
            loss = fluid.layers.mean(pred)
            fluid.optimizer.SGD(1.0, regularization=reg).minimize(loss)
        return main, startup

    def final_w(reg):
        main, startup = build(reg)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            import jax.numpy as jnp

            scope.set_var("w", jnp.ones((2, 1), jnp.float32))
            exe.run(main, feed={"x": np.zeros((1, 2), np.float32)},
                    fetch_list=[])
            return scope.numpy("w")

    w_plain = final_w(None)
    w_reg = final_w(fluid.regularizer.L2Decay(0.1))
    # with zero input, grad=0; L2 adds 0.1*w -> w_new = w - 0.1*w = 0.9
    np.testing.assert_allclose(w_plain, 1.0, atol=1e-6)
    np.testing.assert_allclose(w_reg, 0.9, atol=1e-6)


def test_metrics_accuracy_auc():
    m = fluid.metrics.Accuracy()
    m.update(0.75, 4)
    m.update(0.5, 4)
    assert abs(m.eval() - 0.625) < 1e-9

    auc = fluid.metrics.Auc()
    preds = np.array([[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]])
    labels = np.array([0, 1, 1, 0])
    auc.update(preds, labels)
    assert auc.eval() > 0.9


def test_clone_for_test_after_minimize_prunes_grad_ops():
    """Regression: generic grad ops must NOT inherit the forward op's
    __op_role__ (they'd survive clone(for_test=True) and demand grad
    feeds at inference)."""
    import paddle_tpu.unique_name as un

    with un.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            out = fluid.layers.fc(x, 2)
            loss = fluid.layers.mean(out)
            fluid.optimizer.SGD(0.1).minimize(loss)
    infer = main.clone(for_test=True)
    types = [op.type for op in infer.global_block.ops]
    assert not any(t.endswith("_grad") or t == "sgd" for t in types), types
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (v,) = exe.run(infer, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[out.name])
    assert np.asarray(v).shape == (2, 2)


def test_profiler_timeline_roundtrip(tmp_path):
    """profiler span dump -> tools/timeline.py -> chrome trace JSON
    (reference tools/timeline.py contract)."""
    import json
    import subprocess
    import sys

    from paddle_tpu import profiler as prof

    d = str(tmp_path / "prof")
    import os
    os.makedirs(d)
    prof.reset_profiler()
    with prof.profiler(profile_path=d):
        with prof.RecordEvent("step"):
            with prof.RecordEvent("forward"):
                np.ones((64, 64)) @ np.ones((64, 64))
    out = str(tmp_path / "tl.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, os.path.join(repo, "tools",
                                                     "timeline.py"),
                        "--profile_path", d, "--timeline_path", out],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    tl = json.load(open(out))
    names = {e["name"] for e in tl["traceEvents"]}
    assert {"step", "forward"} <= names
    for e in tl["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0
